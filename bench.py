"""Headline bench: serving throughput through EngineCore (continuous batching).

Measures what BASELINE.md asks for — tokens/sec/chip on the 1B-class bench
model served through the engine's continuous-batching step loop (the same code
path /v1/chat/completions runs), plus TTFT p50 and an MFU estimate.

Robustness (VERDICT r1 item 1): the TPU backend is probed in a SUBPROCESS with
a bounded timeout and one retry, because a broken axon tunnel hangs backend
init indefinitely. If the TPU is unreachable the bench falls back to a CPU run
of the same engine path on a tiny config and reports the probe diagnostics —
the output is always exactly ONE JSON line on stdout:

    {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N, ...}

All diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Stand-in baseline: per-chip decode throughput of a 1B-class model on a
# vLLM/A100-class serving stack at batch ~32 (public figures cluster ~2-3k
# tok/s per accelerator for 1B models; we take the high end as the bar).
A100_CLASS_TOKS_PER_SEC = 3000.0

PROBE_TIMEOUT_S = 150
PROBE_LONG_TIMEOUT_S = 420  # init over a tunnel can legitimately take minutes


def log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def tpu_possibly_present() -> bool:
    """Cheap host-side TPU evidence check, run BEFORE any jax import.

    On a CPU-only host the staged subprocess probe still burns its full
    timeout budget per attempt inside libtpu's make_c_api_client retry loop
    (BENCH_r05 spent 30 s+ per attempt doing exactly that), so the bench
    harness must decide "no TPU here" from the host alone and pin
    JAX_PLATFORMS=cpu before the first device touch. The evidence policy
    (device nodes, TPU-VM metadata env vars, pinned JAX_PLATFORMS) is
    SHARED with the engine server's init guard — tpu_probe.tpu_expected,
    one policy for both callers; the bench adds only the explicit operator
    override (LLMLB_BENCH_FORCE_TPU_PROBE=1 — e.g. a remote TPU behind a
    tunnel that leaves no local trace)."""
    if os.environ.get("LLMLB_BENCH_FORCE_TPU_PROBE"):
        return True
    from llmlb_tpu.engine.tpu_probe import tpu_expected

    return tpu_expected()


def force_cpu_platform(reason: str) -> None:
    """Pin jax to CPU before backend init (env var first; config API too in
    case a sitecustomize already imported jax and re-set JAX_PLATFORMS)."""
    log(f"forcing JAX_PLATFORMS=cpu ({reason})")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def probe_tpu() -> tuple[bool, str, dict]:
    """Check TPU backend health in a subprocess so a hung init can't wedge the
    bench. The staged probe itself (import → device enum → matmul, periodic
    faulthandler stack dumps, captured child stderr as evidence) is shared
    with the engine server's startup guard — llmlb_tpu/engine/tpu_probe.py.
    One short attempt, then one long one (init over a tunnel can take
    minutes). Returns (ok, diagnostic, evidence)."""
    from llmlb_tpu.engine.tpu_probe import staged_probe

    return staged_probe((PROBE_TIMEOUT_S, PROBE_LONG_TIMEOUT_S), log_fn=log)


def run_engine_bench(platform: str) -> dict:
    """Bench the continuous-batching engine loop. Called AFTER the jax
    platform has been decided (TPU left alone / CPU forced)."""
    import jax

    from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams
    from llmlb_tpu.engine.presets import get_preset

    on_tpu = platform == "tpu"
    if on_tpu:
        preset = "tinyllama-1.1b"
        num_slots, capacity = 32, 2048  # model max ctx; 4k prompts need 8k-ctx models
        buckets = (128, 256, 512)
        prompt_len, warm_tokens, max_tokens = 128, 16, 512
        measure_s = 10.0
        # Burst 16: with ~93 ms of host readback latency per fetch through
        # the tunnel and single-digit-ms decode steps, k=16 keeps the sync
        # under ~40% of the burst. Operators tune via the same env knob.
        burst = int(os.environ.get("LLMLB_DECODE_BURST", "16"))
    else:
        preset = "debug-tiny"
        num_slots, capacity = 4, 128
        buckets = (16, 32)
        prompt_len, warm_tokens, max_tokens = 16, 4, 96
        measure_s = 3.0
        burst = 1

    cfg = get_preset(preset)
    devices = jax.devices()
    n_chips = len(devices) if on_tpu else 1
    kind = getattr(devices[0], "device_kind", "unknown")
    log(f"backend={jax.default_backend()} devices={n_chips} kind={kind}")

    t0 = time.perf_counter()
    core = EngineCore(
        cfg, num_slots=num_slots, slot_capacity=capacity,
        prefill_buckets=buckets, seed=0, decode_burst=burst,
    )
    core.start()
    log(f"engine up in {time.perf_counter() - t0:.1f}s "
        f"(slots={num_slots} cap={capacity})")

    import numpy as np

    rng = np.random.default_rng(0)

    def make_request(max_toks: int) -> Request:
        ids = list(rng.integers(1, cfg.vocab_size, size=(prompt_len,)))
        return Request(
            prompt_ids=ids,
            sampling=SamplingParams(temperature=0.7, top_p=0.95,
                                    max_tokens=max_toks),
        )

    def drain_until_done(reqs: list[Request], timeout: float) -> None:
        deadline = time.monotonic() + timeout
        for r in reqs:
            while time.monotonic() < deadline:
                kind_, _val = r.events.get(timeout=max(1.0, deadline - time.monotonic()))
                if kind_ in ("done", "error"):
                    break

    # ---- warmup: trigger every compile (prefill bucket + decode + sampling)
    t0 = time.perf_counter()
    warm = [make_request(warm_tokens) for _ in range(2)]
    for r in warm:
        core.submit(r)
    drain_until_done(warm, timeout=1200)
    log(f"warmup (compiles) in {time.perf_counter() - t0:.1f}s")

    # ---- measured run: fill all slots, sample steady-state throughput from
    # the engine's own token counter while every slot stays active.
    reqs = [make_request(max_tokens) for _ in range(num_slots)]
    submit_t = time.monotonic()
    for r in reqs:
        core.submit(r)

    while any(r.first_token_at is None for r in reqs):
        time.sleep(0.005)
        if time.monotonic() - submit_t > 1200:
            raise RuntimeError("requests never reached first token")
    ttfts = sorted((r.first_token_at - r.submitted_at) for r in reqs)
    ttft_p50_ms = 1000.0 * ttfts[len(ttfts) // 2]
    ttft_p99_ms = 1000.0 * ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))]

    stats0 = core.stats()
    t0 = time.monotonic()
    while True:
        time.sleep(0.25)
        s = core.stats()
        if s.active_slots < num_slots or time.monotonic() - t0 >= measure_s:
            break
    stats1 = core.stats()
    t1 = time.monotonic()
    window_tokens = stats1.total_tokens - stats0.total_tokens
    window_s = t1 - t0
    toks_per_sec = window_tokens / window_s

    drain_until_done(reqs, timeout=1200)

    # Long-context TTFT: one prompt far beyond the largest one-shot bucket
    # exercises the chunked-prefill path (BENCH evidence for VERDICT r2
    # item 5). Tiny on CPU; ~1.5k tokens (within tinyllama's 2k ctx) on TPU.
    long_len = min(capacity - max(64, warm_tokens) - 2, 4096)
    long_ttft_ms = None
    if long_len > max(buckets):
        lr = make_request(16)
        lr.prompt_ids = list(rng.integers(1, cfg.vocab_size, size=(long_len,)))
        core.submit(lr)
        deadline = time.monotonic() + 1200
        while lr.first_token_at is None and time.monotonic() < deadline:
            time.sleep(0.005)
        if lr.first_token_at is not None:
            long_ttft_ms = 1000.0 * (lr.first_token_at - lr.submitted_at)
            log(f"long-prompt ({long_len} tokens) TTFT {long_ttft_ms:.0f}ms "
                f"(chunked prefill)")
        drain_until_done([lr], timeout=1200)

    core.stop()

    per_chip = toks_per_sec / max(n_chips, 1)

    # MFU: decode FLOPs/token ~= 2 * params, against the shared peak-spec
    # table (engine/telemetry.py CHIP_SPECS — the same figures the engine's
    # live llmlb_engine_mfu_ratio gauge divides by).
    from llmlb_tpu.engine.telemetry import chip_spec_for, model_flops_per_token

    n_params = sum(int(np.prod(v.shape)) for k, v in core.params.items()
                   if not k.endswith("_scale"))  # scales aren't parameters
    spec = chip_spec_for(kind)
    # weight-quantized engines are judged against the chip's int8 peak
    # (same column the live gauge divides by — telemetry.ChipSpec)
    peak = (spec.int8_flops if (spec and core.quant.weights)
            else (spec.peak_flops if spec else None))
    mfu = (model_flops_per_token(cfg, n_params) * per_chip / peak
           if (spec and on_tpu) else None)
    # the engine's own live figure over its recent decode window — should
    # track the bench's steady-state estimate on TPU
    engine_perf = core.perf_info()

    kernels = "pallas" if (on_tpu and n_chips == 1 and os.environ.get(
        "LLMLB_TPU_ATTENTION", "auto") != "xla") else "xla"
    # the engine resolves LLMLB_QUANTIZE itself; report what actually ran
    # next to the MFU estimate so a quantized number is never mistaken for
    # a bf16 one (int8 weights are judged against the int8 peak — the
    # engine's perf_info already picks the right column)
    quant_mode = core.quant.mode
    log(f"steady-state: {window_tokens} tokens / {window_s:.2f}s = "
        f"{toks_per_sec:.1f} tok/s ({per_chip:.1f}/chip), "
        f"ttft p50 {ttft_p50_ms:.1f}ms, kernels={kernels}, "
        f"mfu={mfu if mfu is not None else 'n/a'} quantize={quant_mode}")

    return {
        "metric": f"engine_decode_tokens_per_sec_per_chip_{preset}",
        "value": round(per_chip, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(per_chip / A100_CLASS_TOKS_PER_SEC, 4),
        "platform": "tpu" if on_tpu else "cpu",
        "device_kind": str(kind),
        "n_chips": n_chips,
        "model": preset,
        "batch_slots": num_slots,
        "decode_burst": burst,
        "ttft_p50_ms": round(ttft_p50_ms, 1),
        "ttft_p99_ms": round(ttft_p99_ms, 1),
        "long_prompt_tokens": long_len if long_ttft_ms is not None else None,
        "long_prompt_ttft_ms": (
            round(long_ttft_ms, 1) if long_ttft_ms is not None else None
        ),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "quantize": quant_mode,
        "engine_mfu_live": engine_perf.get("mfu"),
        "engine_hbm_bw_utilization_live": engine_perf.get(
            "hbm_bw_utilization"
        ),
        "attention_kernels": kernels,
        "through_engine_core": True,
    }


def main() -> None:
    if not tpu_possibly_present():
        # CPU-only host: skip the subprocess probe entirely — it would hang
        # tens of seconds per attempt in TPU backend init with no TPU to
        # find. One clear line, then the CPU diagnostic run.
        force_cpu_platform("no TPU evidence on this host; "
                           "set LLMLB_BENCH_FORCE_TPU_PROBE=1 to override")
        ok, diag, evidence = False, "no TPU evidence on host (probe skipped)", {}
    else:
        ok, diag, evidence = probe_tpu()
    if ok:
        try:
            result = run_engine_bench("tpu")
        except Exception as e:  # contract: one JSON line even on TPU failure
            import traceback

            traceback.print_exc(file=sys.stderr)
            print(json.dumps({
                "metric": "engine_decode_tokens_per_sec_per_chip",
                "value": 0.0,
                "unit": "tokens/sec/chip",
                "vs_baseline": 0.0,
                "platform": "tpu",
                "error": f"{type(e).__name__}: {e}",
            }))
            return
    else:
        log(f"TPU unavailable ({diag}); falling back to CPU diagnostic run")
        # Force the CPU backend BEFORE jax initializes; the axon sitecustomize
        # overrides JAX_PLATFORMS, so use the config API which it honours.
        import jax

        jax.config.update("jax_platforms", "cpu")
        try:
            result = run_engine_bench("cpu")
        except Exception as e:  # keep the contract: one JSON line, always
            print(json.dumps({
                "metric": "engine_decode_tokens_per_sec_per_chip",
                "value": 0.0,
                "unit": "tokens/sec/chip",
                "vs_baseline": 0.0,
                "platform": "none",
                "error": f"{type(e).__name__}: {e}",
                "tpu_probe_error": diag,
                "tpu_probe_evidence": evidence,
            }))
            return
        result["tpu_probe_error"] = diag
        result["tpu_probe_evidence"] = evidence
        result["vs_baseline"] = 0.0  # CPU number is a smoke value, not a claim
    print(json.dumps(result))


if __name__ == "__main__":
    main()
