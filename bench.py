"""Headline bench: continuous-decode throughput, tokens/sec/chip.

Runs the 1B-class bench model (random weights — checkpoint download is not
available in the bench environment) with a full decode batch and measures
sustained decode throughput per chip, the BASELINE.md "tokens/sec/chip" target
(the reference publishes no model-serving numbers; `vs_baseline` is measured
against A100_CLASS_TOKS_PER_SEC, a vLLM-on-A100-class per-chip decode rate for
1B-class models, per the BASELINE.json north-star framing).

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

# Stand-in baseline: per-chip decode throughput of a 1B-class model on a
# vLLM/A100-class serving stack at batch 32 (public figures cluster ~2-3k tok/s
# per accelerator for 1B models; we take the high end as the bar to beat).
A100_CLASS_TOKS_PER_SEC = 3000.0

BATCH = 32
CAPACITY = 1024
PREFILL_LEN = 128
DECODE_STEPS = 64
WARMUP_STEPS = 8


def main() -> None:
    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.models.llama import (
        decode_step,
        init_kv_cache,
        init_params,
        prefill,
    )
    from llmlb_tpu.ops.sampling import sample_tokens

    # Unsharded single-device run: params and caches live on the default
    # device, so throughput is per-chip by construction regardless of how many
    # chips the host exposes.
    n_chips = 1
    cfg = get_preset("tinyllama-1.1b")

    params = init_params(cfg, jax.random.PRNGKey(0))
    ck, cv = init_kv_cache(cfg, BATCH, CAPACITY)

    ids = jax.random.randint(
        jax.random.PRNGKey(1), (BATCH, PREFILL_LEN), 0, cfg.vocab_size
    )
    lens = jnp.full((BATCH,), PREFILL_LEN, jnp.int32)
    logits, ck, cv = prefill(params, cfg, ids, lens, ck, cv)

    temp = jnp.full((BATCH,), 0.7, jnp.float32)
    top_p = jnp.full((BATCH,), 0.95, jnp.float32)
    top_k = jnp.zeros((BATCH,), jnp.int32)
    key = jax.random.PRNGKey(2)

    def step(carry):
        logits, ck, cv, seq_lens, key = carry
        key, sk = jax.random.split(key)
        tokens = sample_tokens(logits, sk, temp, top_p, top_k)
        logits, ck, cv = decode_step(params, cfg, tokens, seq_lens, ck, cv)
        return logits, ck, cv, seq_lens + 1, key

    carry = (logits, ck, cv, lens, key)
    for _ in range(WARMUP_STEPS):
        carry = step(carry)
    carry[0].block_until_ready()

    start = time.perf_counter()
    for _ in range(DECODE_STEPS):
        carry = step(carry)
    carry[0].block_until_ready()
    elapsed = time.perf_counter() - start

    toks_per_sec = BATCH * DECODE_STEPS / elapsed
    per_chip = toks_per_sec / max(n_chips, 1)
    print(
        json.dumps(
            {
                "metric": "decode_tokens_per_sec_per_chip_1b_bf16_batch32",
                "value": round(per_chip, 2),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(per_chip / A100_CLASS_TOKS_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
