"""llmlb_tpu — a TPU-native LLM serving gateway.

A brand-new framework with the capabilities of akiojin/llmlb (an OpenAI-compatible
LLM gateway / load balancer; see SURVEY.md): OpenAI + Anthropic API surface, TPS-EMA
load balancing across endpoints, pull-based health checking, model sync, auth, audit
chain, dashboard — plus a first-class in-tree ``tpu://`` endpoint type: a JAX/XLA
continuous-batching inference engine (prefill/decode split, paged KV cache in HBM,
pjit/shard_map tensor parallelism over ICI meshes).

Layout:
    llmlb_tpu.models    — functional JAX model families (Llama/Qwen/Mistral, ...)
    llmlb_tpu.ops       — core TPU ops (attention incl. paged, RoPE, norms, sampling)
    llmlb_tpu.parallel  — mesh construction + sharding rules (tp/dp/sp/ep)
    llmlb_tpu.engine    — continuous-batching TPU inference engine + its HTTP server
    llmlb_tpu.gateway   — the load-balancer gateway (API, balancer, registry, health,
                          auth, audit, db, events, update)
    llmlb_tpu.native    — ctypes bindings to the C++ native components (native/)
"""

__version__ = "0.1.0"
