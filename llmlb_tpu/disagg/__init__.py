"""Disaggregated prefill/decode serving (docs/disaggregation.md).

Two composable pieces on top of the existing engine and gateway:

- In-process split (`--role split`, disagg/split.py): one engine process
  runs a prefill pool and a decode pool as two step loops over one shared
  PagePool. Handoff is a page-id exchange — the block-table row moves, no
  KV bytes do — and the adopted request continues exactly like a PR 10
  parked request resumes, so streams are token-identical to `--role both`.

- Cross-process roles (`--role prefill|decode`, disagg/wire.py +
  disagg/gateway.py): engines advertise their role through the capability
  plumbing, the gateway steers prefill-heavy requests to prefill-capable
  endpoints, and the decode pool adopts the stream via a
  prompt+committed-tokens replay carried on the handoff wire (the
  park/resume bit-identity argument makes the replay exact).
"""

from llmlb_tpu.disagg.wire import (  # noqa: F401
    HANDOFF_WIRE_VERSION,
    HandoffError,
    handoff_payload,
    parse_handoff,
)

ROLES = ("both", "split", "prefill", "decode")


def normalize_role(role: str | None) -> str:
    """Resolve a role string ('' / None fall back to 'both'); raises
    ValueError for anything outside ROLES."""
    r = (role or "both").strip().lower()
    if r not in ROLES:
        raise ValueError(f"role must be one of {ROLES}, got {role!r}")
    return r
