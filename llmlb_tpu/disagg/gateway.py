"""Role-aware gateway routing for disaggregated prefill/decode serving.

The gateway learns each endpoint's serving role from two places that are
deliberately redundant (docs/disaggregation.md):

- the health probe: tpu:// engines report ``disagg.role`` in /api/health,
  re-parsed on EVERY probe cycle — a restarted engine that changed role
  re-routes within one probe interval with no endpoint re-registration;
- model sync: roles ride the /v1/models capability list ("prefill" /
  "decode" entries, the PR 5 structured-outputs advertisement as template),
  so role-aware selection composes with the existing capability routing.

Routing policy (soft preferences — the filters always fall back to the
full candidate set rather than 404ing a servable request):

- prefill-heavy requests (long prompt, cold prefix) steer to
  prefill-capable endpoints;
- everything else steers AWAY from prefill-only endpoints (their slots are
  reserved for prefill bursts);
- when the chosen endpoint is prefill-ONLY, the proxy orchestrates the
  two-phase handoff: POST /v1/handoff/prefill there, then hand the wire
  payload to a decode-capable adopter's /v1/handoff, which streams the
  full completion. Prefix affinity composes: the affinity hash steers
  WITHIN the role-filtered candidate list, so a warm prefix still lands on
  the engine whose KV cache holds it.

Non-TPU endpoints never advertise a role and default to "both" — they are
candidates everywhere, exactly as before this module existed.
"""

from __future__ import annotations

import os

from llmlb_tpu.disagg import ROLES

# A prompt at or above this many (estimated) tokens counts as prefill-heavy
# and is steered to prefill-capable endpoints. 0 disables role steering of
# fresh requests (role surfaces and handoff orchestration stay live).
PREFILL_HEAVY_TOKENS = 256


def prefill_heavy_threshold() -> int:
    raw = os.environ.get("LLMLB_DISAGG_PREFILL_THRESHOLD")
    if raw:
        try:
            return max(0, int(raw))
        except ValueError:
            pass
    return PREFILL_HEAVY_TOKENS


def _caps_role(model) -> str | None:
    """Role derived from an EndpointModel's capability list (the /v1/models
    advertisement, persisted through model sync). The capability fallback
    matters in multi-worker gateways: the pull health checker runs in the
    elected primary only, so sibling workers have no probe telemetry — but
    every worker reloads the synced capability list from the shared DB."""
    if model is None:
        return None
    caps = {getattr(c, "value", c) for c in getattr(model, "capabilities", [])}
    p, d = "prefill" in caps, "decode" in caps
    if p and not d:
        return "prefill"
    if d and not p:
        return "decode"
    if p and d:
        return "both"  # both/split are indistinguishable here; routing
    return None        # only needs capability, not the loop topology


def endpoint_role(ep, model=None) -> str:
    """The endpoint's served role: the last health probe's disagg block
    first, the model's capability advertisement second, "both" when
    neither says anything (full-service, the pre-disaggregation default)."""
    role = getattr(getattr(ep, "accelerator", None), "role", None)
    if role in ROLES:
        return role
    return _caps_role(model) or "both"


def prefill_capable(ep, model=None) -> bool:
    return endpoint_role(ep, model) in ("prefill", "both", "split")


def decode_capable(ep, model=None) -> bool:
    return endpoint_role(ep, model) in ("decode", "both", "split")


def role_filter(endpoints: list, *, prefill_heavy: bool,
                models: list | None = None) -> list:
    """Role-preference filter over a candidate list (`models` is the
    optional parallel EndpointModel list for the capability fallback).
    Soft: an empty preferred set falls back to the input unchanged, so
    role steering can never make a servable model unroutable."""
    ms = models if models is not None else [None] * len(endpoints)
    if prefill_heavy:
        preferred = [ep for ep, m in zip(endpoints, ms)
                     if prefill_capable(ep, m)]
    else:
        # keep prefill-only endpoints free for prefill bursts
        preferred = [ep for ep, m in zip(endpoints, ms)
                     if endpoint_role(ep, m) != "prefill"]
    return preferred or endpoints


def is_prefill_heavy(state, model: str, prompt_tokens_estimate: int,
                     prefix_hash: str | None) -> bool:
    """Long prompt AND cold prefix. A warm prefix makes the prefill nearly
    free on the endpoint that holds it, so affinity wins over role
    steering. Cold-prefix detection reads the lru affinity map; in ring
    mode ownership is a pure hash (no warmth signal), so a long prompt
    counts as heavy and the consistent-hash owner is consulted within the
    role-filtered set."""
    threshold = prefill_heavy_threshold()
    if threshold <= 0 or prompt_tokens_estimate < threshold:
        return False
    lm = state.load_manager
    if prefix_hash is not None and lm.affinity_mode == "lru":
        if lm._affinity_endpoint(model, prefix_hash) is not None:
            return False  # warm prefix: stick with the cache
    return True


def speaks_handoff_wire(ep, model=None) -> bool:
    """True only when the endpoint EXPLICITLY advertises decode capability
    — a probed disagg role or a "decode" entry on its capability list.
    `decode_capable`'s "both" DEFAULT is deliberately not enough here: a
    generic OpenAI-compatible endpoint defaults to "both" for steering
    purposes but has no /v1/handoff route, and POSTing a wire payload at
    it would 404 a perfectly servable request."""
    role = getattr(getattr(ep, "accelerator", None), "role", None)
    if role in ROLES:
        return role in ("decode", "both", "split")
    return _caps_role(model) in ("decode", "both")


def adopter_candidates(state, model: str, capability,
                       exclude: set[str] | None = None) -> list:
    """Online endpoints serving `model` that explicitly speak the handoff
    wire — where a payload can be adopted. The originating prefill-only
    endpoint is never in this list, and neither is a non-TPU endpoint that
    merely DEFAULTS to "both" (it has no /v1/handoff)."""
    return [
        ep for ep, m in state.registry.find_by_model(model, capability)
        if speaks_handoff_wire(ep, m)
        and (not exclude or ep.id not in exclude)
    ]
