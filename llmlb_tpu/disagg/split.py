"""In-process disaggregated prefill/decode: two step loops, one PagePool.

`--role split` partitions an engine's slots into a PREFILL pool and a DECODE
pool and runs one step-loop thread per pool (docs/disaggregation.md):

- The prefill loop owns admission (`_try_insert`) and chunked prefill
  (`_advance_prefill`). When a slot's prompt KV is fully landed the slot is
  STAGED — its final logits row is held on device — instead of activated.
- The handoff pump adopts staged requests into free decode slots. The
  transfer is a page-id exchange: the block-table row moves from the prefill
  slot to the decode slot and not one KV byte is copied (refcounts are
  untouched — ownership moves with the row, exactly a pin/unpin pair
  collapsed). The grammar-constraint cursor and the prompt-lookup drafter
  move with the request, and activation then runs the standard PR 10
  resume-shaped path, so adopted streams are token-identical to
  `--role both` for greedy and seeded-stochastic sampling.
- The decode loop runs `_decode_active` only. The tier-1 acceptance
  invariant — ZERO prefill dispatches on the decode loop — is enforced by
  construction and asserted over `EngineCore.prefill_dispatch_by_loop`.

Both loops serialize device work through one lock (a single host has one
device; the split removes SCHEDULING contention, not compute), with a
decode-first turnstile so a decoder's inter-token latency is bounded by one
prefill chunk rather than a whole admission+prefill iteration. Under decode
pressure the handoff pump may preempt: a staged request of a more important
class parks the least-important decoding victim (the PR 10 machinery), which
later resumes through the prefill pool and hands off again.
"""

from __future__ import annotations

import logging
import os
import threading
import time

import numpy as np

log = logging.getLogger("llmlb_tpu.disagg")


class SplitRuntime:
    """The split-mode scheduler runtime attached to one EngineCore."""

    def __init__(self, core, prefill_slots: int | None = None):
        self.core = core
        n = core.num_slots
        if n < 2:
            raise ValueError(
                "--role split needs at least 2 slots (1 prefill + 1 decode)"
            )
        if prefill_slots is None:
            env = os.environ.get("LLMLB_DISAGG_PREFILL_SLOTS")
            if env:
                try:
                    prefill_slots = int(env)
                except ValueError:
                    log.warning(
                        "LLMLB_DISAGG_PREFILL_SLOTS=%r is not an integer; "
                        "using the default split", env,
                    )
        if prefill_slots is None:
            # prefill is bursty, decode is the steady state: a 1:3 split
            # keeps most capacity serving tokens
            prefill_slots = max(1, n // 4)
        p = min(max(1, int(prefill_slots)), n - 1)
        self.prefill_pool: tuple[int, ...] = tuple(range(p))
        self.decode_pool: tuple[int, ...] = tuple(range(p, n))
        # One lock serializes device dispatches across the two loops (the
        # caches are donated per dispatch — concurrent dispatch would
        # consume the same buffers twice).
        self.lock = threading.Lock()
        # Decode-first turnstile: the decode loop raises this before taking
        # the lock and the prefill loop backs off while it is up, so a
        # decode step never waits behind more than the in-flight chunk.
        self._decode_wants = threading.Event()
        self._threads: list[threading.Thread] = []
        log.info(
            "split mode: %d prefill slot(s) %s, %d decode slot(s) %s",
            len(self.prefill_pool), list(self.prefill_pool),
            len(self.decode_pool), list(self.decode_pool),
        )

    # ------------------------------------------------------------------ loops

    def start(self) -> None:
        self._threads = [
            threading.Thread(target=self._prefill_loop,
                             name="engine-prefill-pool", daemon=True),
            threading.Thread(target=self._decode_loop,
                             name="engine-decode-pool", daemon=True),
        ]
        for t in self._threads:
            t.start()

    def join(self, timeout: float | None = None) -> None:
        for t in self._threads:
            t.join(timeout=timeout)

    def _yield_to_decode(self) -> None:
        while self._decode_wants.is_set() and self.core._running:
            time.sleep(0.0002)

    def _fail_reset(self) -> None:
        core = self.core
        log.exception("split step failed; resetting engine state")
        with self.lock:
            core._fail_all("engine step error")
            core._reset_caches()

    def _prefill_loop(self) -> None:
        core = self.core
        core._tls.tag = "prefill"
        while core._running:
            did = False
            try:
                self._yield_to_decode()
                with self.lock:
                    did |= self.pump_handoffs()
                    did |= core._try_insert()
                self._yield_to_decode()
                with self.lock:
                    did |= core._advance_prefill()
            except Exception:  # pragma: no cover - fail loud, keep serving
                self._fail_reset()
            if not did:
                time.sleep(0.001)

    def _decode_loop(self) -> None:
        core = self.core
        core._tls.tag = "decode"
        while core._running:
            did = False
            try:
                self._decode_wants.set()
                try:
                    with self.lock:
                        self._decode_wants.clear()
                        did |= core._decode_active()
                        # a finished/parked slot frees capacity: adopt the
                        # oldest staged request before the next decode step
                        did |= self.pump_handoffs()
                finally:
                    self._decode_wants.clear()
            except Exception:  # pragma: no cover - fail loud, keep serving
                self._fail_reset()
            if not did:
                time.sleep(0.001)

    # -------------------------------------------------------------- admission

    def free_prefill_slots(self) -> list[int]:
        return [
            i for i in self.prefill_pool
            if self.core.slots[i].request is None
        ]

    def backlog(self) -> int:
        return sum(
            1 for i in self.prefill_pool
            if self.core.slots[i].handoff_ready
        )

    # --------------------------------------------------------------- handoff

    def stage_group(self, group, logits) -> None:
        """A prefill-loop activation lands here instead: pin the finished
        prompt KV in the prefill slot's pages, hold the final logits row
        (the first token samples from it at adoption), and park the device
        seq_len at capacity-1 so batched decode's garbage writes for this
        row stay in the never-read last cell until the pages move."""
        core = self.core
        rows = []
        for row, (slot_id, request, n) in enumerate(group):
            slot = core.slots[slot_id]
            slot.prefilling = True
            slot.prefill_pos = n
            slot.handoff_ready = True
            slot.handoff_logits = logits[row:row + 1]
            slot.handoff_ready_at = time.monotonic()
            core._seq_lens[slot_id] = 0
            rows.append(slot_id)
            core._fr_emit(request, "staged", tokens=n, slot=slot_id)
        import jax.numpy as jnp

        core._d_seq_lens = core._d_seq_lens.at[
            jnp.asarray(rows, jnp.int32)
        ].set(core.slot_capacity - 1)
        core.metrics.set_handoff_backlog(self.backlog())

    def _drop_staged(self, slot_id: int, reason: str) -> None:
        # the scheduler's one terminal-teardown helper clears every slot
        # field (handoff_* included) — no second copy of that invariant
        self.core._finish_slot(slot_id, reason)

    def _acquire_decode_slot(self, prio: int) -> int | None:
        """A free decode slot, or one freed by parking a less-important
        decoding victim (the split-mode preemption point — admission-time
        slot-pressure preemption cannot free a prefill slot)."""
        core = self.core
        for j in self.decode_pool:
            if core.slots[j].request is None:
                return j
        cands = [c for c in core._preempt_candidates(prio)
                 if c in self.decode_pool]
        if cands:
            core._park_slot(cands[0])
            return cands[0]
        return None

    def _adopt(self, i: int, j: int) -> None:
        """Move one staged request from prefill slot `i` to decode slot `j`:
        block-table row exchange (zero KV copy), host cursors (grammar FSM,
        drafter) ride along, then the standard activation runs against the
        decode slot — for a resumed (previously parked) request this IS the
        PR 10 resume, so the stream stays token-identical."""
        core = self.core
        slot_i = core.slots[i]
        request = slot_i.request
        n = slot_i.prefill_pos
        logits = slot_i.handoff_logits
        latency = time.monotonic() - slot_i.handoff_ready_at
        slot_j = core.slots[j]
        assert slot_j.request is None, "adoption into an occupied decode slot"

        # page-id exchange: the row moves, ownership moves with it, no
        # refcount traffic and no KV bytes
        core._slot_pages[j] = core._slot_pages[i]
        core._slot_pages[i] = []
        core._block_tables[j, :] = core._block_tables[i, :]
        core._block_tables[i, :] = 0
        core._tables_dirty = True

        # host-side cursors travel with the request (a fresh grammar FSM
        # would re-mask from the string start — the PR 10 park bug)
        slot_j.constraint = slot_i.constraint
        if slot_j.constraint is not None:
            core._set_mask_row(j, slot_j.constraint)
            if core._mask_bias is not None:
                core._mask_bias[i] = 0.0
                core._mask_dirty_rows.add(i)
        slot_j.drafter = slot_i.drafter
        slot_j.spec_k = slot_i.spec_k
        slot_j.cache_entry = slot_i.cache_entry

        slot_i.request = None
        slot_i.constraint = None  # moved: _constrained_count is unchanged
        slot_i.cache_entry = None
        slot_i.drafter = None
        slot_i.spec_k = 0
        slot_i.generated = 0
        slot_i.out_tokens = []
        slot_i.first_pending = False
        slot_i.prefilling = False
        slot_i.prefill_pos = 0
        slot_i.handoff_ready = False
        slot_i.handoff_logits = None
        slot_i.handoff_ready_at = 0.0
        core._seq_lens[i] = 0

        prev = core._loop_tag()
        core._tls.tag = "handoff"
        try:
            core._activate_group(
                [(j, request, n)],
                np.asarray([j], np.int32),
                np.asarray([n], np.int32),
                logits,
            )
        finally:
            core._tls.tag = prev
        core.metrics.record_handoff("in_process", latency)
        core._fr_emit(request, "adopted", in_process=True,
                      staged_s=round(latency, 6))

    def pump_handoffs(self) -> bool:
        """Adopt staged requests into decode slots, most important class
        first (FIFO by readiness within a class, slot id as the final tie).
        Strictly ordered: a blocked head blocks everything behind it — a
        later request must not steal the slot an earlier one is owed."""
        core = self.core
        ready = [i for i in self.prefill_pool
                 if core.slots[i].handoff_ready]
        if not ready:
            core.metrics.set_handoff_backlog(0)
            return False
        ready.sort(key=lambda i: (
            core._priority_of(core.slots[i].request),
            core.slots[i].handoff_ready_at, i,
        ))
        progress = False
        for i in ready:
            slot = core.slots[i]
            request = slot.request
            if core._is_cancelled(request):
                self._drop_staged(i, "cancelled")
                progress = True
                continue
            j = self._acquire_decode_slot(core._priority_of(request))
            if j is None:
                break
            self._adopt(i, j)
            progress = True
        core.metrics.set_handoff_backlog(self.backlog())
        return progress
