"""The cross-process handoff wire: everything a decode engine needs to adopt
a stream the prefill pool started.

The payload is the disagg counterpart of the multihost plan wire
(engine/scheduler._plan_wire): `sampling` is `dataclasses.asdict(SamplingParams)`
on the way out and `SamplingParams(**payload["sampling"])` on the way back, so
EVERY declared field — priority, deadline_ms, constraint, speculative, seed —
rides automatically and tests/disagg/test_handoff_wire.py fails the moment a
new field is declared without surviving the round trip. The gateway relays the
payload verbatim between engines; it never interprets the sampling block.

Adoption is a prompt+committed-tokens replay: the decode engine chunk-prefills
`prompt_ids + committed_ids` (the PR 10 park/resume path), which lands every
token's KV at the exact position the uninterrupted run had it and makes the
continuation token-identical for greedy and seeded-stochastic sampling.
"""

from __future__ import annotations

import dataclasses
import time

HANDOFF_WIRE_VERSION = 1

# Hard cap on wire token counts: the payload crosses process boundaries as
# JSON, and an absurd length means a corrupted or hostile payload, not a
# real request (slot capacities are orders of magnitude below this).
_MAX_WIRE_TOKENS = 4_000_000


class HandoffError(ValueError):
    """Malformed or unsupported handoff payload."""


def handoff_payload(
    prompt_ids: list[int],
    committed_ids: list[int],
    sampling,
    *,
    stop: list[str] | None = None,
    request_id: str | None = None,
    kv_pages: dict | None = None,
) -> dict:
    """JSON-safe wire form of an in-flight request at its handoff point.

    ``kv_pages`` optionally carries the origin's serialized KV page payload
    (engine/kv_transfer.py) so the adopter can land pages instead of
    replaying the prefill. It rides as a sibling of the token fields — an
    OLDER adopter ignores unknown top-level keys and replays as before, a
    NEWER one validates the payload's own versioned header, so the
    attachment needs no handoff wire-version bump."""
    out = {
        "version": HANDOFF_WIRE_VERSION,
        "request_id": request_id,
        "prompt_ids": [int(t) for t in prompt_ids],
        "committed_ids": [int(t) for t in committed_ids],
        "stop": [str(s) for s in (stop or []) if s],
        "sampling": dataclasses.asdict(sampling),
        # emission stamp: the adopting engine reports now - t as the
        # cross-process handoff latency (same-host clocks; skew caveat in
        # docs/disaggregation.md)
        "t": time.time(),
    }
    if kv_pages is not None:
        out["kv_pages"] = kv_pages
    return out


def _token_list(payload: dict, key: str, *, min_len: int = 0) -> list[int]:
    raw = payload.get(key)
    if not isinstance(raw, list) or len(raw) < min_len:
        raise HandoffError(f"'{key}' must be a list of token ids")
    if len(raw) > _MAX_WIRE_TOKENS:
        raise HandoffError(f"'{key}' is implausibly long ({len(raw)} tokens)")
    try:
        return [int(t) for t in raw]
    except (TypeError, ValueError):
        raise HandoffError(f"'{key}' must contain only integers")


def parse_handoff(payload: dict):
    """Validate + rebuild the adoption inputs:
    (prompt_ids, committed_ids, SamplingParams, stop, request_id, t).

    Raises HandoffError on anything malformed — the decode engine turns
    that into a 400, never a crashed step loop."""
    from llmlb_tpu.engine.scheduler import SamplingParams

    if not isinstance(payload, dict):
        raise HandoffError("handoff payload must be a JSON object")
    if payload.get("version") != HANDOFF_WIRE_VERSION:
        raise HandoffError(
            f"unsupported handoff wire version {payload.get('version')!r} "
            f"(this engine speaks {HANDOFF_WIRE_VERSION})"
        )
    prompt_ids = _token_list(payload, "prompt_ids", min_len=1)
    committed_ids = _token_list(payload, "committed_ids")
    raw_sampling = payload.get("sampling")
    if not isinstance(raw_sampling, dict):
        raise HandoffError("'sampling' must be an object")
    known = {f.name for f in dataclasses.fields(SamplingParams)}
    unknown = set(raw_sampling) - known
    if unknown:
        # a NEWER prefill engine added a field this one does not know;
        # silently dropping it would desync the continuation
        raise HandoffError(
            f"unknown sampling fields on the handoff wire: {sorted(unknown)}"
        )
    try:
        sampling = SamplingParams(**raw_sampling)
    except TypeError as e:
        raise HandoffError(f"bad sampling block: {e}")
    stop = payload.get("stop") or []
    if not isinstance(stop, list) or any(not isinstance(s, str) for s in stop):
        raise HandoffError("'stop' must be a list of strings")
    request_id = payload.get("request_id")
    if request_id is not None and not isinstance(request_id, str):
        raise HandoffError("'request_id' must be a string")
    t = payload.get("t")
    t = float(t) if isinstance(t, (int, float)) else 0.0
    return prompt_ids, committed_ids, sampling, list(stop), request_id, t
