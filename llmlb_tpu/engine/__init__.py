"""The in-tree `tpu://` inference engine (BASELINE.json north star).

A JAX/XLA continuous-batching server: prefill/decode-split scheduler over a
slot-based KV cache in HBM, tensor-parallel over an ICI mesh, exposing the same
endpoint contract the gateway expects from any runtime (`/v1/models`,
`/v1/chat/completions`, `/v1/responses`, `/api/health` with chip/HBM telemetry).
"""
