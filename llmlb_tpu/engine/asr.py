"""ASR service: WAV bytes → text via the JAX whisper model (models/whisper.py).

Serves /v1/audio/transcriptions on the tpu:// engine. The reference gateway
re-proxies multipart transcription bodies to external runtimes
(api/audio.rs:199-370); this service is the in-tree runtime those requests
land on. Audio handling is dependency-free: stdlib `wave` for RIFF/PCM
parsing, numpy linear resampling to 16 kHz.
"""

from __future__ import annotations

import io
import json
import os
import wave

import jax
import numpy as np

from llmlb_tpu.models import whisper


def decode_wav(data: bytes) -> tuple[np.ndarray, int]:
    """RIFF/WAV bytes -> (mono float32 in [-1, 1], sample_rate).
    Accepts PCM16/PCM8/PCM32 via stdlib wave. Raises ValueError (a client
    error) for anything that is not a decodable WAV."""
    try:
        with wave.open(io.BytesIO(data), "rb") as wf:
            rate = wf.getframerate()
            n = wf.getnframes()
            width = wf.getsampwidth()
            channels = wf.getnchannels()
            raw = wf.readframes(n)
    except (wave.Error, EOFError) as e:
        raise ValueError(f"not a decodable WAV file: {e}") from None
    if rate <= 0:
        raise ValueError("WAV reports a non-positive sample rate")
    if width == 2:
        audio = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 4:
        audio = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    elif width == 1:  # unsigned 8-bit
        audio = (np.frombuffer(raw, "u1").astype(np.float32) - 128.0) / 128.0
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if channels > 1:
        audio = audio.reshape(-1, channels).mean(axis=1)
    return audio, rate


def resample_linear(audio: np.ndarray, src_rate: int, dst_rate: int) -> np.ndarray:
    if src_rate == dst_rate:
        return audio
    n_out = int(round(len(audio) * dst_rate / src_rate))
    x_out = np.linspace(0.0, len(audio) - 1.0, n_out)
    return np.interp(x_out, np.arange(len(audio)), audio).astype(np.float32)


class AsrEngine:
    """One loaded whisper model + transcription entry points."""

    def __init__(self, cfg: whisper.WhisperConfig, params, tokenizer=None,
                 model_id: str = "whisper"):
        self.cfg = cfg
        self.params = jax.tree.map(jax.numpy.asarray, params)
        self.tokenizer = tokenizer  # None => digit-joined token ids (tests)
        self.model_id = model_id
        self.total_requests = 0

    # ------------------------------------------------------------ construction

    @classmethod
    def from_random(cls, cfg: whisper.WhisperConfig | None = None,
                    model_id: str = "whisper-random", seed: int = 0):
        cfg = cfg or whisper.WhisperConfig(
            vocab_size=1024, n_mels=80, d_model=64, encoder_layers=2,
            decoder_layers=2, num_heads=4, n_audio_ctx=200, n_text_ctx=64,
            sot_token=1000, eot_token=1001, transcribe_token=1002,
            no_timestamps_token=1003, english_token=1004,
        )
        params = whisper.init_params(cfg, jax.random.PRNGKey(seed))
        return cls(cfg, params, model_id=model_id)

    @classmethod
    def from_checkpoint(cls, model_dir: str, model_id: str | None = None):
        """HF whisper checkpoint directory (config.json + safetensors +
        tokenizer files)."""
        from llmlb_tpu.engine.weights import _safetensors_getter

        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = whisper.WhisperConfig.from_hf_config(json.load(f))
        params = whisper.convert_hf_tensors(cfg, _safetensors_getter(model_dir))
        tokenizer = None
        try:
            from transformers import WhisperTokenizer

            tokenizer = WhisperTokenizer.from_pretrained(model_dir)
        except Exception:  # allow-silent: optional dependency — byte
            pass           # fallback tokenizer below serves without it
        return cls(cfg, params, tokenizer,
                   model_id or os.path.basename(model_dir.rstrip("/")))

    # --------------------------------------------------------------- serving

    def _mel_for(self, audio: np.ndarray) -> np.ndarray:
        """Frame audio to mel with pow2-bucketed frame counts (bounded compile
        count), capped at the model's audio context."""
        mel = np.asarray(whisper.log_mel_spectrogram(
            jax.numpy.asarray(audio), self.cfg.n_mels
        ))
        max_frames = self.cfg.n_audio_ctx * 2
        frames = mel.shape[0]
        bucket = 16
        while bucket < frames:
            bucket *= 2
        bucket = min(bucket, max_frames)
        out = np.zeros((bucket, self.cfg.n_mels), np.float32)
        out[: min(frames, bucket)] = mel[:bucket]
        return out

    def transcribe_audio(self, audio: np.ndarray, sample_rate: int,
                         max_tokens: int = 128) -> str:
        """Mono float32 audio at any rate -> transcript text."""
        self.total_requests += 1
        audio = resample_linear(audio, sample_rate, whisper.SAMPLE_RATE)
        max_samples = self.cfg.n_audio_ctx * 2 * whisper.HOP_LENGTH
        audio = audio[:max_samples]
        if len(audio) < whisper.N_FFT:
            audio = np.pad(audio, (0, whisper.N_FFT - len(audio)))
        mel = self._mel_for(audio)
        tokens = whisper.greedy_transcribe_tokens(
            self.params, self.cfg, jax.numpy.asarray(mel), max_tokens
        )
        if self.tokenizer is not None:
            return self.tokenizer.decode(tokens, skip_special_tokens=True)
        return " ".join(str(t) for t in tokens)

    def transcribe_wav_bytes(self, data: bytes, max_tokens: int = 128) -> str:
        audio, rate = decode_wav(data)
        return self.transcribe_audio(audio, rate, max_tokens)
