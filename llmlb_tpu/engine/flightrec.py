"""Per-request flight recorder: engine-side lifecycle event log.

The gateway's trace ring (llmlb_tpu/gateway/tracing.py) sees a request
only from the proxy side; PR 6's step stats see dispatches with no request
identity. This module records WHAT HAPPENED TO ONE REQUEST inside the
engine — every lifecycle edge the scheduler crosses:

  admitted         request passed validation and entered the engine
  queued           landed on a priority-class queue (class + depth)
  prefill_chunk    one prompt-KV fill dispatch (tokens, cached-prefix
                   tokens reused from the prefix cache)
  staged           split-mode prefill complete, first token staged for a
                   decode-pool adoption (disagg, in-process)
  handoff_emitted  committed tokens wrapped into a cross-process handoff
                   wire payload (/v1/handoff/prefill answered)
  adopted          this engine adopted a stream another engine started
                   (/v1/handoff, /v1/resume, or the in-process split)
  parked           slot preempted (reason: preempt | drain | pages) with
                   generated-token count — resumable state retained
  resumed          a parked request re-activated (chunk-prefill replay,
                   or page restore when KV travelled as bytes)
  kv_shipped       this request's KV pages serialized D2H for transport
                   (tokens, pages, bytes — handoff/resume export)
  kv_spilled       parked-slot pages serialized into the host-RAM offload
                   tier instead of being dropped (reason, tokens, bytes)
  kv_restored      serialized pages landed H2D into this engine's pool —
                   decode continues with zero prefill dispatches
                   (source: wire | offload; kind: stream | prefix)
  lora_acquire     adapter pinned for the request (+ load wait seconds)
  spec_accept      one speculative verify step's drafted/accepted counts
  shed             dropped before prefill (deadline exceeded)
  finished         terminal success (reason: stop | length | cancelled)
  errored          terminal failure (message)
  slow_step        this request sat in a dispatch the slow-step detector
                   flagged (kind, total seconds, step seq)

Events are keyed by the gateway-minted ``X-Request-Id`` (the scheduler's
request_id minus its uniquifying ``.{8 hex}`` suffix), so the gateway can
join them to its own trace spans — ``/api/traces/{id}?view=timeline``
fetches ``GET /api/requests/{id}/timeline`` from every engine the request
touched and merges one cross-process timeline (docs/tracing.md).

Budget: like the step recorder, the guarantee is < 1% of CPU-engine step
time — events fire per lifecycle EDGE (a handful per request), never per
token, and each emit is one clock read, one dict build, and two deque
appends behind a lock held for microseconds. ``LLMLB_FLIGHTREC=0``
short-circuits emit() before the clock read, restoring bit-identical
pre-recorder behavior.

Timestamps are wall-clock (``time.time()`` — the only clock two processes
share; same caveat as the handoff wire stamp in docs/disaggregation.md).
In-process ordering is exact via a monotonic sequence number; the gateway
merge uses (ts, seq) and repairs causal edges the clock skew may flip.

Post-mortem (``LLMLB_FLIGHTREC_SPOOL``): memory dies with the process —
a SIGKILLed engine cannot answer for its own events. When the spool knob
names a directory, every event is also appended to a per-request JSONL
file there (the PR 9 sibling-merge pattern: engines sharing the directory
serve each other's events, so the chaos drill's survivor answers for the
victim). Off by default: the zero-disk-I/O path is the overhead-budgeted
one.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import OrderedDict, deque

# The lifecycle taxonomy (docs/tracing.md documents each event's fields).
EVENTS = (
    "admitted", "queued", "prefill_chunk", "staged", "handoff_emitted",
    "adopted", "parked", "resumed", "kv_shipped", "kv_spilled",
    "kv_restored", "lora_acquire", "spec_accept",
    "shed", "finished", "errored", "slow_step",
)

# scheduler request ids are "{gateway_rid}.{uuid4().hex[:8]}"
_SUFFIX_RE = re.compile(r"\.[0-9a-f]{8}$")
# spool filenames must not traverse; gateway ids already match this shape
_UNSAFE_RE = re.compile(r"[^A-Za-z0-9_.:\-]")

_PRUNE_EVERY = 256  # emits between lazy retention sweeps
_SPOOL_PRUNE_EVERY = 128  # spool writes between stale-file sweeps


def gateway_rid(request_id: str) -> str:
    """Strip the scheduler's uniquifying ``.{8 hex}`` suffix, recovering
    the gateway-minted X-Request-Id the events are keyed by. Ids without
    the suffix (engine-local uuids, test ids) pass through unchanged."""
    return _SUFFIX_RE.sub("", request_id)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FlightRecorder:
    """Bounded per-request event deques + a global recent-events ring +
    aggregate counters. Thread-safe: emit() runs on the step loop and the
    HTTP service threads; timeline()/counters() on scrape handlers."""

    def __init__(self, *, enabled: bool | None = None,
                 ring: int | None = None,
                 max_requests: int | None = None,
                 events_per_request: int | None = None,
                 retention_s: float | None = None,
                 spool_dir: str | None = None,
                 source: str | None = None):
        if enabled is None:
            enabled = os.environ.get(
                "LLMLB_FLIGHTREC", "1").lower() not in ("0", "false", "no")
        self.enabled = bool(enabled)
        self.ring_capacity = max(
            16, ring if ring is not None
            else _env_int("LLMLB_FLIGHTREC_RING", 4096))
        self.max_requests = max(
            1, max_requests if max_requests is not None
            else _env_int("LLMLB_FLIGHTREC_REQS", 256))
        self.events_per_request = max(
            8, events_per_request if events_per_request is not None
            else _env_int("LLMLB_FLIGHTREC_EVENTS", 128))
        self.retention_s = float(
            retention_s if retention_s is not None
            else _env_int("LLMLB_FLIGHTREC_RETENTION_S", 600))
        if spool_dir is None:
            spool_dir = os.environ.get("LLMLB_FLIGHTREC_SPOOL") or None
        self.spool_dir = spool_dir
        # source tag on every event: which process recorded it. The engine
        # has no registry name for itself, so pid is the honest identity;
        # the gateway merge re-labels sources with endpoint names.
        self.source = source or f"engine-pid{os.getpid()}"
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=self.ring_capacity)
        # rid -> {"events": deque, "dropped": int, ...accounting stamps};
        # ordered by last touch, so the front is the eviction candidate
        self._reqs: "OrderedDict[str, dict]" = OrderedDict()
        self._seq = 0
        self.events_total = 0
        self.events_dropped_total = 0
        self.requests_total = 0
        self.spool_errors_total = 0
        self.by_event: dict[str, int] = {}
        # timeline-derived queue-vs-compute accounting (the Grafana panel):
        # admitted -> first prefill_chunk is queue time; first prefill_chunk
        # -> terminal is service time.
        self.queue_seconds_total = 0.0
        self.service_seconds_total = 0.0
        self._spool_writes = 0
        if self.enabled and self.spool_dir:
            try:
                os.makedirs(self.spool_dir, exist_ok=True)
            except OSError:
                self.spool_errors_total += 1
                self.spool_dir = None

    # ------------------------------------------------------------- recording

    def emit(self, request_id: str, event: str, **attrs) -> None:
        """Record one lifecycle event. Safe from any thread; a no-op (before
        the first clock read) when the recorder is disabled."""
        if not self.enabled:
            return
        now = time.time()
        rid = gateway_rid(request_id)
        with self._lock:
            self._seq += 1
            ev: dict = {"seq": self._seq, "ts": round(now, 6),
                        "src": self.source, "event": event,
                        "request_id": rid}
            if request_id != rid:
                ev["engine_request_id"] = request_id
            if attrs:
                ev["attrs"] = attrs
            rec = self._reqs.get(rid)
            if rec is None:
                rec = {"events": deque(maxlen=self.events_per_request),
                       "dropped": 0, "first_ts": now}
                self._reqs[rid] = rec
                self.requests_total += 1
                while len(self._reqs) > self.max_requests:
                    self._reqs.popitem(last=False)
            else:
                self._reqs.move_to_end(rid)
            if len(rec["events"]) == self.events_per_request:
                rec["dropped"] += 1
                self.events_dropped_total += 1
            rec["events"].append(ev)
            rec["last_ts"] = now
            self._ring.append(ev)
            self.events_total += 1
            self.by_event[event] = self.by_event.get(event, 0) + 1
            if event == "admitted":
                rec["admitted_ts"] = now
            elif event == "prefill_chunk" and "compute_ts" not in rec:
                rec["compute_ts"] = now
                if "admitted_ts" in rec:
                    self.queue_seconds_total += now - rec["admitted_ts"]
            elif event in ("finished", "errored", "shed"):
                start = rec.get("compute_ts", rec.get("admitted_ts"))
                if start is not None:
                    self.service_seconds_total += now - start
            if self._seq % _PRUNE_EVERY == 0:
                self._prune_locked(now)
        if self.spool_dir:
            self._spool(rid, ev)

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.retention_s
        while self._reqs:
            rid, rec = next(iter(self._reqs.items()))
            if rec.get("last_ts", rec["first_ts"]) >= horizon:
                break
            del self._reqs[rid]

    # --------------------------------------------------------------- spooling

    def _spool(self, rid: str, ev: dict) -> None:
        path = os.path.join(self.spool_dir,
                            f"req-{_UNSAFE_RE.sub('_', rid)}.jsonl")
        try:
            with open(path, "a") as f:
                f.write(json.dumps(ev, separators=(",", ":")) + "\n")
        except (OSError, TypeError, ValueError):
            with self._lock:
                self.spool_errors_total += 1
            return
        self._spool_writes += 1
        if self._spool_writes % _SPOOL_PRUNE_EVERY == 0:
            self._prune_spool()

    def _prune_spool(self) -> None:
        horizon = time.time() - self.retention_s
        try:
            names = os.listdir(self.spool_dir)
        except OSError:
            return
        for name in names:
            if not name.startswith("req-"):
                continue
            p = os.path.join(self.spool_dir, name)
            try:
                if os.path.getmtime(p) < horizon:
                    os.unlink(p)
            except OSError:
                continue  # allow-silent: sibling pruned it first

    def _read_spool(self, rid: str) -> list[dict]:
        path = os.path.join(self.spool_dir,
                            f"req-{_UNSAFE_RE.sub('_', rid)}.jsonl")
        events: list[dict] = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue  # torn tail line from a killed writer
                    if isinstance(ev, dict) and "event" in ev:
                        events.append(ev)
        except OSError:
            return []
        return events

    # ---------------------------------------------------------------- reading

    def timeline(self, request_id: str) -> dict | None:
        """JSON view of one request's events (memory merged with any
        spooled sibling events), sorted by (ts, src, seq). None when the
        recorder knows nothing about the id."""
        rid = gateway_rid(request_id)
        with self._lock:
            rec = self._reqs.get(rid)
            events = list(rec["events"]) if rec is not None else []
            dropped = rec["dropped"] if rec is not None else 0
        if self.spool_dir:
            seen = {(e["src"], e["seq"]) for e in events}
            for ev in self._read_spool(rid):
                key = (ev.get("src"), ev.get("seq"))
                if key not in seen:
                    seen.add(key)
                    events.append(ev)
        if not events:
            return None
        events.sort(key=lambda e: (e.get("ts", 0.0), str(e.get("src", "")),
                                   e.get("seq", 0)))
        return {
            "request_id": rid,
            "source": self.source,
            "events": events,
            "dropped": dropped,
            "first_ts": events[0].get("ts"),
            "last_ts": events[-1].get("ts"),
        }

    def counters(self) -> dict:
        """Aggregate view for /api/steps and /metrics."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "events_total": self.events_total,
                "events_dropped_total": self.events_dropped_total,
                "requests_total": self.requests_total,
                "requests_tracked": len(self._reqs),
                "by_event": dict(self.by_event),
                "queue_seconds_total": round(self.queue_seconds_total, 6),
                "service_seconds_total": round(self.service_seconds_total, 6),
                "spool": bool(self.spool_dir),
                "spool_errors_total": self.spool_errors_total,
            }
