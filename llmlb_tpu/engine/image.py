"""Image generation service: prompt → PNG via the JAX diffusion model.

Serves /v1/images/generations on the tpu:// engine (reference proxies these
to capability-advertising endpoints, api/images.rs:184). PNG encoding is
stdlib-only (zlib + struct).
"""

from __future__ import annotations

import base64
import struct
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from llmlb_tpu.models import diffusion


def encode_png(rgb: np.ndarray) -> bytes:
    """[H, W, 3] uint8 -> PNG bytes (8-bit truecolor, no filtering)."""
    h, w, _ = rgb.shape

    def chunk(tag: bytes, payload: bytes) -> bytes:
        return (struct.pack(">I", len(payload)) + tag + payload
                + struct.pack(">I", zlib.crc32(tag + payload) & 0xFFFFFFFF))

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)
    raw = b"".join(b"\x00" + rgb[y].tobytes() for y in range(h))
    return (b"\x89PNG\r\n\x1a\n"
            + chunk(b"IHDR", ihdr)
            + chunk(b"IDAT", zlib.compress(raw, 6))
            + chunk(b"IEND", b""))


class ImageEngine:
    """One loaded diffusion model + generation entry points."""

    def __init__(self, cfg: diffusion.DiffusionConfig, params,
                 model_id: str = "diffusion", sample_steps: int = 20):
        self.cfg = cfg
        self.params = jax.tree.map(
            lambda x: None if x is None else jnp.asarray(x), params,
            is_leaf=lambda x: x is None,
        )
        self.model_id = model_id
        self.sample_steps = sample_steps
        self.total_requests = 0
        # itertools.count.__next__ is atomic under the GIL — concurrent
        # requests on different executor threads each get a distinct seed
        import itertools

        self._seed_counter = itertools.count(
            int(np.random.SeedSequence().entropy % (2**30))
        )

    @classmethod
    def from_random(cls, cfg: diffusion.DiffusionConfig | None = None,
                    model_id: str = "diffusion-random", seed: int = 0,
                    sample_steps: int = 8):
        cfg = cfg or diffusion.DiffusionConfig(
            img_size=16, base_ch=16, ch_mults=(1, 2), text_dim=32,
            max_text_len=64,
        )
        params = diffusion.init_params(cfg, jax.random.PRNGKey(seed))
        return cls(cfg, params, model_id=model_id, sample_steps=sample_steps)

    @classmethod
    def from_checkpoint(cls, model_dir: str, model_id: str | None = None,
                        sample_steps: int = 20):
        cfg, params = diffusion.load_checkpoint(model_dir)
        import os

        return cls(cfg, params,
                   model_id or os.path.basename(model_dir.rstrip("/")),
                   sample_steps)

    def generate(self, prompt: str, n: int = 1, seed: int | None = None
                 ) -> list[bytes]:
        """Prompt -> n PNG images."""
        if not prompt:
            raise ValueError("'prompt' is required")
        if not 1 <= n <= 10:
            raise ValueError("'n' must be between 1 and 10")
        self.total_requests += 1

        data = prompt.encode("utf-8", errors="replace")[: self.cfg.max_text_len]
        ln = len(data)
        ids = np.zeros((1, self.cfg.max_text_len), np.int32)
        ids[0, :ln] = np.frombuffer(data, np.uint8) + 1  # 0 is pad
        if seed is None:
            seed = next(self._seed_counter) % (2**31)
        imgs = diffusion.ddim_sample(
            self.params, self.cfg, jax.random.PRNGKey(seed),
            jnp.asarray(ids), jnp.asarray([ln], np.int32),
            n, n_steps=self.sample_steps,
        )
        out = []
        for i in range(n):
            arr = np.asarray((imgs[i] + 1.0) * 127.5).clip(0, 255).astype(np.uint8)
            out.append(encode_png(arr))
        return out

    def generate_b64(self, prompt: str, n: int = 1, seed: int | None = None
                     ) -> list[str]:
        return [base64.b64encode(p).decode() for p in self.generate(prompt, n, seed)]
