"""Tiered host-RAM KV offload: cold pages spill D2H, warm returns skip prefill.

HBM pages are the scarcest resource the scheduler manages; host RAM is two
orders of magnitude larger and sits idle. This tier is the middle rung:
when the prefix cache evicts a cold entry under page pressure, or a parked
slot gives up its pages, the serialized page payload (kv_transfer) lands
here instead of vanishing — bounded LRU over host bytes, its own budget
(LLMLB_KV_OFFLOAD_BYTES, default 0 = off). A multi-turn user returning
after minutes restores H2D into freshly allocated pages and decodes on
warm KV; a preempted request resumes without re-prefilling what it already
computed.

Two keyspaces share one budget and one LRU clock:

- **prefix** entries, keyed ``(ns, tokens)`` exactly like the live radix
  cache's namespaces — spilled by ``_evict_one_prefix``, restored at
  admission time just before the live-cache match so the ordinary
  zero-copy hit path takes over;
- **parked** entries, keyed by engine request id — spilled by
  ``_park_slot``, popped when the parked request re-activates and landed
  via the same page-restore path the wire payloads use.

The tier is deliberately dumb storage: all policy (when to spill, whether
a restore is worth pages, metric accounting) lives in the scheduler; all
format knowledge lives in kv_transfer. Counters here exist so
``/api/health`` and the metrics exposition can report occupancy and
hit/miss traffic without reaching into scheduler internals.
"""

from __future__ import annotations

import collections
import threading

from .kv_transfer import KVPages


class KVOffloadTier:
    """Bounded-LRU host-RAM store of parsed KV page payloads."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = max(0, int(budget_bytes))
        # key -> KVPages; key is ("prefix", ns, tokens) or ("parked", rid).
        # OrderedDict move_to_end gives the LRU clock.
        self._entries: collections.OrderedDict[tuple, KVPages] = (
            collections.OrderedDict()
        )
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.spills = 0
        self.evictions = 0
        self.spilled_bytes = 0
        self.restored_bytes = 0

    # -- capacity -----------------------------------------------------------

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def would_admit(self, nbytes: int) -> bool:
        """Cheap pre-check so callers can skip the D2H gather entirely for
        payloads the budget could never hold."""
        return 0 < nbytes <= self.budget_bytes

    def _admit(self, key: tuple, kvp: KVPages) -> bool:
        if not self.would_admit(kvp.nbytes):
            return False
        old = self._entries.pop(key, None)
        if old is not None:
            self._bytes -= old.nbytes
        while self._bytes + kvp.nbytes > self.budget_bytes and self._entries:
            _, victim = self._entries.popitem(last=False)
            self._bytes -= victim.nbytes
            self.evictions += 1
        self._entries[key] = kvp
        self._bytes += kvp.nbytes
        self.spills += 1
        self.spilled_bytes += kvp.nbytes
        return True

    # -- prefix keyspace ----------------------------------------------------

    def put_prefix(self, ns, tokens: tuple, kvp: KVPages) -> bool:
        with self._lock:
            return self._admit(("prefix", ns, tuple(tokens)), kvp)

    def match_prefix(self, ns, tokens, max_len: int):
        """Best stored entry sharing a head with ``tokens[:max_len]`` in
        namespace ``ns`` -> (stored_tokens, KVPages), consumed from the
        tier (the caller lands it back into HBM; a later eviction
        re-spills it). An entry LONGER than max_len still matches on its
        usable head — the returning-user case is the exact same prompt,
        whose full-length spilled entry must not be unreachable just
        because one suffix token has to prefill; the caller slices pages
        (they are position-independent) down to what it can use. Linear
        over stored prefix entries — the byte budget keeps the entry count
        small, and this only runs on admission after the live radix cache
        missed."""
        with self._lock:
            best_key = None
            best_len = 0
            for key in self._entries:
                if key[0] != "prefix" or key[1] != ns:
                    continue
                stored = key[2]
                eff = min(len(stored), max_len)
                if eff <= best_len:
                    continue
                if tuple(tokens[:eff]) == stored[:eff]:
                    best_key, best_len = key, eff
            if best_key is None:
                self.misses += 1
                return None
            kvp = self._entries.pop(best_key)
            self._bytes -= kvp.nbytes
            self.hits += 1
            self.restored_bytes += kvp.nbytes
            return best_key[2], kvp

    # -- parked keyspace ----------------------------------------------------

    def put_parked(self, request_id: str, kvp: KVPages) -> bool:
        with self._lock:
            return self._admit(("parked", request_id), kvp)

    def pop_parked(self, request_id: str) -> KVPages | None:
        with self._lock:
            kvp = self._entries.pop(("parked", request_id), None)
            if kvp is None:
                self.misses += 1
                return None
            self._bytes -= kvp.nbytes
            self.hits += 1
            self.restored_bytes += kvp.nbytes
            return kvp

    def drop_parked(self, request_id: str) -> None:
        """Forget a parked spill whose request terminated (cancel/shed) —
        dead bytes must not squat in the budget until LRU reaps them."""
        with self._lock:
            kvp = self._entries.pop(("parked", request_id), None)
            if kvp is not None:
                self._bytes -= kvp.nbytes

    # -- introspection ------------------------------------------------------

    def info(self) -> dict:
        with self._lock:
            prefix = sum(1 for k in self._entries if k[0] == "prefix")
            return {
                "enabled": True,
                "budget_bytes": self.budget_bytes,
                "bytes": self._bytes,
                "entries": len(self._entries),
                "prefix_entries": prefix,
                "parked_entries": len(self._entries) - prefix,
                "hits": self.hits,
                "misses": self.misses,
                "spills": self.spills,
                "evictions": self.evictions,
                "spilled_bytes": self.spilled_bytes,
                "restored_bytes": self.restored_bytes,
            }
