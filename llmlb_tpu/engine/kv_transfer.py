"""Serialized KV page shipping: move cached state as bytes, not recompute.

Every state-movement path in the engine — the cross-process ``/v1/handoff``,
mid-stream ``/v1/resume``, and preemption park/resume — historically rebuilt
KV by chunk-prefilling prompt+committed tokens: an O(context) compute bill
per move. This module is the O(bytes-moved) alternative: a request's KV
pages (named exactly by its block-table row) serialize into a versioned,
length-prefixed wire blob that an adopting engine lands straight into its
own page pool, entering decode with ZERO prefill dispatches.

Wire form (JSON-safe dict):
- every ``KVWireHeader`` field flat on the payload (version, layer count,
  page geometry, kv dtype, covered tokens) — the compatibility gate reads
  ONLY the header, so an incompatible peer refuses before touching the
  blob and falls back to the replay path with a labeled reason;
- ``data``: base64 of ``KVSH`` + version + length-prefixed named sections.
  Plain pools ship ``{k, v}`` pages ``[L, P, page, K, D]``; int8-quantized
  pools ship ``{k_q, k_s, v_q, v_s}`` — the int8 codes AND their f32
  per-vector scales, bit-exact copies of the donor's pool cells (PR 8's
  byte win carries straight onto the wire: ~(D+4)/2D of the bf16 bytes).

The header field set is a dataclass on purpose: like the handoff sampling
block, tests/disagg/test_handoff_wire.py auto-probes EVERY declared field
through a round trip, and an unknown inbound field is refused loudly — a
newer peer's extension must version-bump, never silently drop.

Token-identity contract (why shipping [0, n-1) rows is exactly enough):
the adopter sets ``seq_len = n-1`` and ``last_token = committed[-1]``; its
next decode dispatch writes position n-1's KV itself and samples with the
pre-increment fold ``n-1`` — the same kernel, step fold, and cached bytes
the uninterrupted run used for that position, so greedy and seeded
continuations match bit for bit (scheduler._insert_restored).
"""

from __future__ import annotations

import base64
import dataclasses
import struct

import numpy as np

KV_WIRE_VERSION = 1
KV_WIRE_MAGIC = b"KVSH"

# Hard caps: the payload crosses process boundaries; absurd figures mean a
# corrupted or hostile blob, not a real request (same stance as the handoff
# wire's _MAX_WIRE_TOKENS).
_MAX_PAGES = 1 << 20
_MAX_SECTION_BYTES = 1 << 33  # 8 GiB

# kv_dtype names this build can land into a pool. "int8" means quantized
# {q, s} pools: codes ship with their float32 per-vector scales.
_PLAIN_DTYPES = ("bfloat16", "float32", "float16")
_SECTIONS_PLAIN = ("k", "v")
_SECTIONS_INT8 = ("k_q", "k_s", "v_q", "v_s")


class KVTransferError(ValueError):
    """Malformed or unsupported KV page payload. ``reason`` is the
    fallback-counter label the caller records (version | error)."""

    def __init__(self, message: str, reason: str = "error"):
        super().__init__(message)
        self.reason = reason


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        import ml_dtypes  # jax dependency, always importable next to it

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


@dataclasses.dataclass(frozen=True)
class KVWireHeader:
    """Everything the compatibility gate needs BEFORE touching the blob.
    Auto-probed by tests/disagg/test_handoff_wire.py: every field here must
    round-trip the wire, and an undeclared inbound field is refused."""

    version: int
    layers: int
    page_size: int
    num_kv_heads: int
    head_dim: int
    kv_dtype: str  # "bfloat16" | "float32" | "float16" | "int8"
    tokens: int  # KV rows valid in [0, tokens) across the shipped pages
    num_pages: int


_HEADER_FIELDS = tuple(f.name for f in dataclasses.fields(KVWireHeader))


@dataclasses.dataclass
class KVPages:
    """Parsed, validated page payload: host numpy sections ready to land in
    a pool. ``source`` tags where it came from for the flight record —
    "wire" (handoff/resume payload) or "offload" (host-RAM tier)."""

    header: KVWireHeader
    sections: dict[str, np.ndarray]
    source: str = "wire"

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.sections.values())


def expected_sections(header: KVWireHeader) -> dict[str, tuple[tuple, str]]:
    """Section name -> (shape, numpy dtype name) for a header's geometry."""
    shape = (header.layers, header.num_pages, header.page_size,
             header.num_kv_heads, header.head_dim)
    if header.kv_dtype == "int8":
        scale = shape[:-1]  # per-vector scales drop the head_dim axis
        return {"k_q": (shape, "int8"), "k_s": (scale, "float32"),
                "v_q": (shape, "int8"), "v_s": (scale, "float32")}
    return {"k": (shape, header.kv_dtype), "v": (shape, header.kv_dtype)}


def serialize_kv_pages(header: KVWireHeader,
                       sections: dict[str, np.ndarray]) -> dict:
    """JSON-safe wire payload: the flat header plus a base64 blob of
    length-prefixed sections. Shapes/dtypes are asserted against the header
    on the way OUT too — a malformed export must fail the exporter, never
    ship bytes an adopter would misread."""
    want = expected_sections(header)
    if set(sections) != set(want):
        raise KVTransferError(
            f"sections {sorted(sections)} do not match kv_dtype "
            f"{header.kv_dtype!r} (want {sorted(want)})"
        )
    parts = [KV_WIRE_MAGIC, struct.pack("<II", header.version, len(want))]
    for name in sorted(want):
        shape, dtype = want[name]
        arr = np.ascontiguousarray(sections[name])
        if tuple(arr.shape) != shape or arr.dtype != _np_dtype(dtype):
            raise KVTransferError(
                f"section {name!r} is {arr.dtype}{arr.shape}, header "
                f"implies {dtype}{shape}"
            )
        raw = arr.tobytes()
        nm = name.encode("ascii")
        parts.append(struct.pack("<H", len(nm)))
        parts.append(nm)
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    payload = dict(dataclasses.asdict(header))
    payload["data"] = base64.b64encode(b"".join(parts)).decode("ascii")
    return payload


def _int_field(payload: dict, key: str, lo: int, hi: int) -> int:
    v = payload.get(key)
    if isinstance(v, bool) or not isinstance(v, int) or not lo <= v <= hi:
        raise KVTransferError(
            f"kv payload field {key!r} must be an integer in "
            f"[{lo}, {hi}], got {v!r}"
        )
    return v


def parse_kv_header(payload: dict) -> KVWireHeader:
    """Validate the flat header of a kv_pages payload. Refuses version skew
    (reason="version") and any undeclared field — silently dropping an
    unknown field would desync the restore, same discipline as the handoff
    sampling block."""
    if not isinstance(payload, dict):
        raise KVTransferError("kv_pages payload must be a JSON object")
    if payload.get("version") != KV_WIRE_VERSION:
        raise KVTransferError(
            f"unsupported kv wire version {payload.get('version')!r} "
            f"(this engine speaks {KV_WIRE_VERSION})", reason="version",
        )
    unknown = set(payload) - set(_HEADER_FIELDS) - {"data"}
    if unknown:
        raise KVTransferError(
            f"unknown kv_pages fields on the wire: {sorted(unknown)}"
        )
    kv_dtype = payload.get("kv_dtype")
    if kv_dtype not in _PLAIN_DTYPES + ("int8",):
        raise KVTransferError(f"unsupported kv_dtype {kv_dtype!r}")
    num_pages = _int_field(payload, "num_pages", 1, _MAX_PAGES)
    page_size = _int_field(payload, "page_size", 1, 1 << 16)
    return KVWireHeader(
        version=KV_WIRE_VERSION,
        layers=_int_field(payload, "layers", 1, 1 << 12),
        page_size=page_size,
        num_kv_heads=_int_field(payload, "num_kv_heads", 1, 1 << 12),
        head_dim=_int_field(payload, "head_dim", 1, 1 << 16),
        kv_dtype=kv_dtype,
        tokens=_int_field(payload, "tokens", 1, num_pages * page_size),
        num_pages=num_pages,
    )


def parse_kv_payload(payload: dict) -> KVPages:
    """Full parse: header + blob -> host numpy sections shaped per the
    header. Every structural lie (bad magic, section count/name/length
    mismatch, trailing bytes) raises KVTransferError — the caller counts a
    labeled fallback and replays; a bad payload is never a client error."""
    header = parse_kv_header(payload)
    raw = payload.get("data")
    if not isinstance(raw, str):
        raise KVTransferError("kv_pages payload has no 'data' blob")
    try:
        blob = base64.b64decode(raw.encode("ascii"), validate=True)
    except Exception:
        raise KVTransferError("kv_pages 'data' is not valid base64")
    if blob[:4] != KV_WIRE_MAGIC:
        raise KVTransferError("kv_pages blob has a bad magic")
    off = 4
    if len(blob) < off + 8:
        raise KVTransferError("kv_pages blob is truncated")
    version, nsec = struct.unpack_from("<II", blob, off)
    off += 8
    if version != header.version:
        raise KVTransferError("kv_pages blob/header version mismatch",
                              reason="version")
    want = expected_sections(header)
    if nsec != len(want):
        raise KVTransferError(
            f"kv_pages blob carries {nsec} sections, header implies "
            f"{len(want)}"
        )
    sections: dict[str, np.ndarray] = {}
    for _ in range(nsec):
        if len(blob) < off + 2:
            raise KVTransferError("kv_pages blob is truncated")
        (nlen,) = struct.unpack_from("<H", blob, off)
        off += 2
        name = blob[off:off + nlen].decode("ascii", errors="replace")
        off += nlen
        if name not in want or name in sections:
            raise KVTransferError(f"unexpected kv section {name!r}")
        if len(blob) < off + 8:
            raise KVTransferError("kv_pages blob is truncated")
        (nbytes,) = struct.unpack_from("<Q", blob, off)
        off += 8
        shape, dtype = want[name]
        dt = _np_dtype(dtype)
        expect = int(np.prod(shape)) * dt.itemsize
        if nbytes != expect or nbytes > _MAX_SECTION_BYTES:
            raise KVTransferError(
                f"kv section {name!r} is {nbytes} bytes, geometry implies "
                f"{expect}"
            )
        if len(blob) < off + nbytes:
            raise KVTransferError("kv_pages blob is truncated")
        sections[name] = np.frombuffer(
            blob, dtype=dt, count=int(np.prod(shape)), offset=off
        ).reshape(shape)
        off += nbytes
    if off != len(blob):
        raise KVTransferError("kv_pages blob has trailing bytes")
    return KVPages(header=header, sections=sections)


def kv_compat_reason(header: KVWireHeader, *, layers: int, page_size: int,
                     num_kv_heads: int, head_dim: int,
                     kv_dtype: str) -> str | None:
    """None when this engine can land the shipped pages verbatim; otherwise
    the fallback-counter reason label (dtype | page_size | geometry). The
    check is strict equality on purpose: re-paging or re-quantizing foreign
    bytes would be a silent numerics change — mismatches replay instead."""
    if header.kv_dtype != kv_dtype:
        return "dtype"
    if header.page_size != page_size:
        return "page_size"
    if (header.layers, header.num_kv_heads, header.head_dim) != (
            layers, num_kv_heads, head_dim):
        return "geometry"
    return None
