"""Engine Prometheus metrics: counters, gauges, and latency histograms.

The reference exposes Prometheus text only for cloud-proxy calls
(cloud_metrics.rs:21-39); the tpu:// engine goes further and instruments the
serving loop itself — TTFT and inter-token latency histograms, token/request
counters, queue depth — because those are the numbers a TPU serving operator
tunes against (and what the gateway's telemetry-aware scheduler ultimately
reflects). Dependency-free text exposition; threadsafe for the step loop.
"""

from __future__ import annotations

import threading

# Bucket edges in seconds, chosen around serving targets: TTFT p50 goals are
# tens of ms (one-shot prefill) to seconds (chunked 4k prompts); ITL goals
# are single-digit ms on TPU.
TTFT_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
ITL_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0)
# Per-dispatch step durations: prefill is tens of ms to seconds (bucketed
# prompt groups), a decode step is single-digit ms on TPU (burst-amortized).
STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5)
# Schema→DFA→mask-table compiles: milliseconds for byte-level vocabularies,
# seconds for 128k-token vocabularies (docs/structured-outputs.md sizing).
COMPILE_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0)
# Step-phase breakdown (engine/stepstats.py taxonomy): host-side phases
# (plan/sync/dispatch/fetch/emit) are tens of µs to low ms; compute spans
# µs (CPU debug configs) to hundreds of ms (chunked prefill on TPU).
PHASE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)

# Why a KV page transfer fell back to chunk-prefill replay
# (docs/kv-cache.md): shipping knob off / split mode / multihost
# (disabled), no payload arrived with shipping on (absent), wire version
# skew (version), pool dtype or page-size or model-geometry mismatch
# (dtype / page_size / geometry), adopter could not reserve pages
# (capacity), malformed payload (error). Closed set: the fallback counter
# renders one series per reason from the first scrape.
KV_FALLBACK_REASONS = ("disabled", "absent", "version", "dtype",
                       "page_size", "geometry", "capacity", "error")


class Histogram:
    def __init__(self, buckets: tuple[float, ...]):
        self.edges = tuple(buckets)
        self.counts = [0] * (len(buckets) + 1)  # last = +Inf
        self.total = 0.0
        self.n = 0
        self.max = 0.0

    def observe(self, value: float) -> None:
        for i, edge in enumerate(self.edges):
            if value <= edge:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.total += value
        self.n += 1
        if value > self.max:
            self.max = value

    def percentile(self, pct: float) -> float | None:
        """Approximate percentile, linearly interpolated within the landing
        bucket (None if empty). The bucket's mass is assumed uniform between
        its lower and upper edge (lower edge 0 for the first bucket), so a
        sample entirely below the first edge no longer reports the full edge.
        Percentiles above the top edge report the max observed value — a
        finite, JSON-safe figure (`inf` would serialize as the non-standard
        `Infinity` token and break strict parsers of /api/health)."""
        if self.n == 0:
            return None
        target = self.n * pct / 100.0
        seen = 0
        lower = 0.0
        for i, edge in enumerate(self.edges):
            count = self.counts[i]
            if count and seen + count >= target:
                frac = (target - seen) / count
                return lower + frac * (edge - lower)
            seen += count
            lower = edge
        return max(self.edges[-1], self.max)



def _render_histogram(lines: list, name: str, hist: "Histogram",
                      label: str = "") -> None:
    """Append one histogram family in Prometheus exposition form (shared by
    every histogram block in render() — cumulative buckets, +Inf, sum,
    count). `label` is a pre-rendered `k="v"` pair for labeled families."""
    brace = f"{{{label},le=" if label else "{le="
    cumulative = 0
    for i, edge in enumerate(hist.edges):
        cumulative += hist.counts[i]
        lines.append(f'{name}_bucket{brace}"{edge}"}} {cumulative}')
    cumulative += hist.counts[-1]
    lines.append(f'{name}_bucket{brace}"+Inf"}} {cumulative}')
    suffix = f"{{{label}}}" if label else ""
    lines.append(f"{name}_sum{suffix} {hist.total}")
    lines.append(f"{name}_count{suffix} {hist.n}")


class EngineMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.tokens_total = 0
        self.errors_total = 0
        self.cancelled_total = 0
        self.ttft = Histogram(TTFT_BUCKETS)
        self.itl = Histogram(ITL_BUCKETS)
        # Step-loop phase breakdown: duration of each prefill dispatch and
        # each (burst-amortized) decode step, plus the decode batch occupancy
        # at the last step — the figures every scheduling/perf PR tunes.
        self.prefill_step = Histogram(STEP_BUCKETS)
        self.decode_step = Histogram(STEP_BUCKETS)
        self.batch_occupancy = 0
        # Prefix KV cache (engine/prefix_cache.py): hit/miss per insert,
        # prompt tokens served from cached KV instead of prefill compute,
        # donor-slot insertions/evictions. The pinned-state gauges (entries,
        # slots, HBM bytes) are scraped live from the scheduler at render
        # time — they are state, not events.
        self.prefix_hits_total = 0
        self.prefix_misses_total = 0
        self.prefix_cached_tokens_total = 0
        self.prefix_insertions_total = 0
        self.prefix_inserted_tokens_total = 0
        self.prefix_evictions_total = 0
        # Structured outputs (llmlb_tpu/structured): constrained requests
        # served, decode dispatches that applied a grammar mask, requests
        # that ended without grammar acceptance, schema→mask compile cost,
        # and the compiled-mask LRU cache traffic. The cache-size gauges
        # (entries/bytes) are scraped from the compiler at render time.
        self.structured_requests_total = 0
        self.masked_decode_steps_total = 0
        self.constraint_violations_total = 0
        self.mask_cache_hits_total = 0
        self.mask_cache_misses_total = 0
        self.mask_cache_evictions_total = 0
        self.schema_compile = Histogram(COMPILE_BUCKETS)
        # Speculative decoding (llmlb_tpu/spec): verify dispatches run,
        # draft tokens proposed, drafts accepted by the model, and tokens
        # emitted by verify steps (accepted + 1 per speculating slot).
        # acceptance rate = accepted / drafted; speedup proxy =
        # emitted / verify steps per slot.
        self.spec_verify_steps_total = 0
        self.spec_draft_tokens_total = 0
        self.spec_accepted_tokens_total = 0
        self.spec_emitted_tokens_total = 0
        # Fused decode (docs/fused-decode.md): decode/verify steps served by
        # the single-program path, total device dispatches issued by the
        # decode loop (fused: exactly one per step — the invariant
        # scripts/check_fused_dispatch.py pins), and constrained slots that
        # fell back to single-step legacy decode (grammar-table budget or
        # fused mode off).
        self.fused_decode_steps_total = 0
        self.decode_dispatches_total = 0
        self.constrained_burst_fallback_total = 0
        # Overload protection (docs/scheduling.md): slots parked under
        # slot/page pressure, parked requests re-activated, and requests
        # shed at admission because their deadline had already passed.
        self.preemptions_total = 0
        self.preempt_resumes_total = 0
        self.deadline_shed_total = 0
        # Disaggregated prefill/decode (docs/disaggregation.md): handoffs by
        # kind — in_process (split mode's page-id exchange), emitted (this
        # prefill-role engine handed a stream away), adopted (this
        # decode-role engine replayed and continued one) — plus the time a
        # ready request waited between prefill completion and decode
        # adoption, and the live count of requests stuck in that gap.
        self.handoff_total: dict[str, int] = {
            "in_process": 0, "emitted": 0, "adopted": 0,
        }
        self.handoff_latency = Histogram(STEP_BUCKETS)
        self.handoff_backlog = 0
        # Graceful drain (docs/deployment.md): 1 while the engine refuses
        # new admissions and winds down, plus the decoding slots parked when
        # the drain grace expired (their streams resume on another engine
        # via the gateway's replay path).
        self.drain_state = 0
        self.drain_parked_total = 0
        # KV page shipping (docs/kv-cache.md, docs/disaggregation.md):
        # exports serialized for transport (count/bytes/seconds), restores
        # landed H2D with zero prefill dispatches, and the reason-labeled
        # replay fallbacks — without the reason label, replay and transfer
        # are indistinguishable in /metrics. The label set is closed (code
        # picks from KV_FALLBACK_REASONS), so cardinality is bounded and
        # every series renders from scrape one. The offload-tier gauges
        # scrape live from the tier's info() block at render time.
        self.kv_ship_total = 0
        self.kv_ship_bytes_total = 0
        self.kv_ship_seconds_total = 0.0
        self.kv_restored_total = 0
        self.kv_restored_bytes_total = 0
        self.kv_ship_fallback_total: dict[str, int] = {
            r: 0 for r in KV_FALLBACK_REASONS
        }
        # Multi-LoRA serving (llmlb_tpu/lora, docs/lora.md): adapter
        # hot-loads/evictions (their RATE is the thrash signal the
        # EngineLoraThrash alert pages on), disk→device load latency, and a
        # cardinality-capped per-adapter request counter. The residency
        # gauge (llmlb_engine_lora_loaded) scrapes live from the manager at
        # render time — state, not an event.
        self.lora_loads_total = 0
        self.lora_evictions_total = 0
        self.lora_load = Histogram(COMPILE_BUCKETS)
        self.lora_requests_total: dict[str, int] = {}
        self._LORA_LABEL_CAP = 64
        # LoRA requests that disabled the context-parallel prefill mesh and
        # fell back to chunked prefill (the bgmv delta is not mesh-sharded;
        # docs/lora.md). Rate, not a one-off: sustained growth means long
        # LoRA prompts are paying single-chip prefill latency.
        self.lora_cp_fallback_total = 0
        # Step-phase time breakdown (engine/stepstats.py): one histogram per
        # phase of the step loop, fed once per dispatch, plus the slow-step
        # anomaly counter. Lazily keyed so only phases that occur render.
        from llmlb_tpu.engine.stepstats import PHASES

        self.step_phase: dict[str, Histogram] = {
            p: Histogram(PHASE_BUCKETS) for p in PHASES
        }
        self.slow_steps_total = 0

    # ------------------------------------------------------------ recorders

    def record_ttft(self, seconds: float) -> None:
        with self._lock:
            self.ttft.observe(seconds)

    def record_itl(self, seconds: float) -> None:
        with self._lock:
            self.itl.observe(seconds)

    def record_token(self, n: int = 1) -> None:
        with self._lock:
            self.tokens_total += n

    def record_emit(self, itl_seconds: float | None) -> None:
        """One locked update for the per-token hot path: a token plus its
        inter-token latency (None for a slot's first emitted token)."""
        with self._lock:
            self.tokens_total += 1
            if itl_seconds is not None:
                self.itl.observe(itl_seconds)

    def record_prefill_step(self, seconds: float) -> None:
        with self._lock:
            self.prefill_step.observe(seconds)

    def record_decode_step(self, seconds: float, active_slots: int) -> None:
        with self._lock:
            self.decode_step.observe(seconds)
            self.batch_occupancy = active_slots

    def set_batch_occupancy(self, active_slots: int) -> None:
        with self._lock:
            self.batch_occupancy = active_slots

    def record_prefix_hit(self, cached_tokens: int) -> None:
        """One cache-hit insert serving `cached_tokens` prompt tokens from
        copied KV rows instead of prefill."""
        with self._lock:
            self.prefix_hits_total += 1
            self.prefix_cached_tokens_total += cached_tokens

    def record_prefix_miss(self) -> None:
        with self._lock:
            self.prefix_misses_total += 1

    def record_prefix_insert(self, tokens: int) -> None:
        with self._lock:
            self.prefix_insertions_total += 1
            self.prefix_inserted_tokens_total += tokens

    def record_prefix_eviction(self) -> None:
        with self._lock:
            self.prefix_evictions_total += 1

    def record_structured_request(self) -> None:
        with self._lock:
            self.structured_requests_total += 1

    def record_masked_decode_step(self) -> None:
        with self._lock:
            self.masked_decode_steps_total += 1

    def record_constraint_violation(self) -> None:
        """A constrained request terminated without grammar acceptance
        (max_tokens/capacity cut it short, or a vocabulary gap forced EOS)."""
        with self._lock:
            self.constraint_violations_total += 1

    def record_schema_compile(self, seconds: float) -> None:
        with self._lock:
            self.schema_compile.observe(seconds)

    def record_mask_cache_hit(self) -> None:
        with self._lock:
            self.mask_cache_hits_total += 1

    def record_mask_cache_miss(self) -> None:
        with self._lock:
            self.mask_cache_misses_total += 1

    def record_mask_cache_eviction(self) -> None:
        with self._lock:
            self.mask_cache_evictions_total += 1

    def record_spec_step(self, drafted: int, accepted: int,
                         emitted: int) -> None:
        """One speculative verify dispatch: `drafted` tokens proposed across
        the batch, `accepted` of them matched by the model's own samples,
        `emitted` tokens delivered (accepted + 1 per speculating slot)."""
        with self._lock:
            self.spec_verify_steps_total += 1
            self.spec_draft_tokens_total += drafted
            self.spec_accepted_tokens_total += accepted
            self.spec_emitted_tokens_total += emitted

    def record_decode_dispatches(self, n: int, fused: bool = False) -> None:
        """Device dispatches issued by one decode-loop step (decode or
        verify kind). `fused` marks steps served by the single-program
        path; legacy steps report their honest multi-dispatch count."""
        with self._lock:
            self.decode_dispatches_total += max(0, int(n))
            if fused:
                self.fused_decode_steps_total += 1

    def record_constrained_burst_fallback(self) -> None:
        """A constrained slot forced the decode loop off the fused/burst
        path into single-step legacy decode this step."""
        with self._lock:
            self.constrained_burst_fallback_total += 1

    def record_step_phases(self, phases: dict[str, float],
                           slow: bool = False) -> None:
        """One locked update per step: every phase duration plus the
        anomaly flag. Skipping zero-duration phases keeps absent phases
        (e.g. fetch on a prefill record) out of the histograms."""
        with self._lock:
            for name, seconds in phases.items():
                hist = self.step_phase.get(name)
                if hist is not None and seconds > 0.0:
                    hist.observe(seconds)
            if slow:
                self.slow_steps_total += 1

    def record_preemption(self) -> None:
        with self._lock:
            self.preemptions_total += 1

    def record_resume(self) -> None:
        with self._lock:
            self.preempt_resumes_total += 1

    def record_deadline_shed(self) -> None:
        with self._lock:
            self.deadline_shed_total += 1

    def record_handoff(self, kind: str, latency_s: float | None = None) -> None:
        """One prefill→decode handoff. `kind` is in_process / emitted /
        adopted; `latency_s` is the prefill-complete→decode-adoption gap
        (absent for 'emitted' — the prefill side cannot see adoption)."""
        with self._lock:
            if kind in self.handoff_total:
                self.handoff_total[kind] += 1
            if latency_s is not None and latency_s >= 0.0:
                self.handoff_latency.observe(latency_s)

    def set_handoff_backlog(self, n: int) -> None:
        with self._lock:
            self.handoff_backlog = n

    def record_lora_load(self, seconds: float) -> None:
        with self._lock:
            self.lora_loads_total += 1
            self.lora_load.observe(seconds)

    def record_lora_eviction(self) -> None:
        with self._lock:
            self.lora_evictions_total += 1

    def record_lora_cp_fallback(self) -> None:
        """A LoRA request's long prompt skipped the context-parallel
        prefill mesh and took chunked prefill instead."""
        with self._lock:
            self.lora_cp_fallback_total += 1

    def record_lora_request(self, adapter: str) -> None:
        """Per-adapter request counter (docs/lora.md). Label cardinality is
        bounded: past _LORA_LABEL_CAP distinct adapters, further names fold
        into the "_other" label instead of growing /metrics without bound."""
        with self._lock:
            if (adapter not in self.lora_requests_total
                    and len(self.lora_requests_total) >= self._LORA_LABEL_CAP):
                adapter = "_other"
            self.lora_requests_total[adapter] = (
                self.lora_requests_total.get(adapter, 0) + 1
            )

    def set_drain_state(self, state: int) -> None:
        with self._lock:
            self.drain_state = int(state)

    def record_kv_ship(self, nbytes: int, seconds: float) -> None:
        """One KV page payload serialized D2H for transport (handoff
        export, resume export, or an offload-tier spill)."""
        with self._lock:
            self.kv_ship_total += 1
            self.kv_ship_bytes_total += max(0, int(nbytes))
            self.kv_ship_seconds_total += max(0.0, float(seconds))

    def record_kv_restore(self, nbytes: int) -> None:
        """One serialized payload landed H2D into the page pool — a state
        movement that dispatched zero prefill work."""
        with self._lock:
            self.kv_restored_total += 1
            self.kv_restored_bytes_total += max(0, int(nbytes))

    def record_kv_ship_fallback(self, reason: str) -> None:
        """A movement path replayed instead of transferring pages. Unknown
        reasons fold into "error" so the label set stays closed."""
        with self._lock:
            if reason not in self.kv_ship_fallback_total:
                reason = "error"
            self.kv_ship_fallback_total[reason] += 1

    def record_drain_park(self) -> None:
        with self._lock:
            self.drain_parked_total += 1

    def record_request_done(self, finish: str) -> None:
        with self._lock:
            self.requests_total += 1
            if finish == "cancelled":
                self.cancelled_total += 1
            elif finish == "error":
                self.errors_total += 1

    # ----------------------------------------------------------- exposition

    def summary(self) -> dict:
        """Compact JSON figures for /api/health consumers (the gateway's
        scheduler and dashboard)."""
        with self._lock:
            return {
                "requests_total": self.requests_total,
                "tokens_total": self.tokens_total,
                "errors_total": self.errors_total,
                "cancelled_total": self.cancelled_total,
                "ttft_p50_s": self.ttft.percentile(50),
                "ttft_p99_s": self.ttft.percentile(99),
                "itl_p50_s": self.itl.percentile(50),
                "itl_p99_s": self.itl.percentile(99),
                "prefix_hits_total": self.prefix_hits_total,
                "prefix_misses_total": self.prefix_misses_total,
                "prefix_cached_tokens_total": self.prefix_cached_tokens_total,
                "prefix_evictions_total": self.prefix_evictions_total,
                "structured_requests_total": self.structured_requests_total,
                "constraint_violations_total":
                    self.constraint_violations_total,
                "schema_compile_p50_s": self.schema_compile.percentile(50),
                "spec_verify_steps_total": self.spec_verify_steps_total,
                "spec_draft_tokens_total": self.spec_draft_tokens_total,
                "spec_accepted_tokens_total": self.spec_accepted_tokens_total,
                "spec_acceptance_rate": (
                    round(self.spec_accepted_tokens_total
                          / self.spec_draft_tokens_total, 4)
                    if self.spec_draft_tokens_total else None
                ),
                "fused_decode_steps_total": self.fused_decode_steps_total,
                "decode_dispatches_total": self.decode_dispatches_total,
                "constrained_burst_fallback_total":
                    self.constrained_burst_fallback_total,
                "preemptions_total": self.preemptions_total,
                "preempt_resumes_total": self.preempt_resumes_total,
                "deadline_shed_total": self.deadline_shed_total,
                "handoff_total": dict(self.handoff_total),
                "handoff_backlog": self.handoff_backlog,
                "handoff_latency_p50_s": self.handoff_latency.percentile(50),
                "drain_state": self.drain_state,
                "drain_parked_total": self.drain_parked_total,
                "kv_ship_total": self.kv_ship_total,
                "kv_ship_bytes_total": self.kv_ship_bytes_total,
                "kv_restored_total": self.kv_restored_total,
                "kv_ship_fallback_total": dict(self.kv_ship_fallback_total),
                "lora_loads_total": self.lora_loads_total,
                "lora_evictions_total": self.lora_evictions_total,
            }

    def render(self, *, queue_depth: int, active_slots: int,
               num_slots: int, prefix_cache: dict | None = None,
               kv_cache: dict | None = None,
               structured: dict | None = None,
               perf: dict | None = None,
               quant: dict | None = None,
               sched: dict | None = None,
               lora: dict | None = None,
               flightrec: dict | None = None,
               kv_offload: dict | None = None) -> str:
        """Prometheus text exposition format. `prefix_cache` is the
        scheduler's prefix_cache_info() block (pinned-state gauges live
        there; the event counters live here); `kv_cache` is its
        kv_cache_info() block — page-pool gauges render when the paged
        layout is active; `structured` is the constraint compiler's info()
        block (mask-cache size gauges); `perf` is its perf_info() block —
        MFU / HBM-bandwidth gauges render when the chip is in the peak-spec
        table and decode traffic has flowed; `quant` is its quant_info()
        block (active int8 mode + honest byte footprints); `flightrec` is
        the flight recorder's counters() block (docs/tracing.md) — the
        queue/service seconds pair feeds the Grafana queue-vs-compute
        panel."""
        with self._lock:
            lines = [
                "# TYPE llmlb_engine_requests_total counter",
                f"llmlb_engine_requests_total {self.requests_total}",
                "# TYPE llmlb_engine_tokens_total counter",
                f"llmlb_engine_tokens_total {self.tokens_total}",
                "# TYPE llmlb_engine_errors_total counter",
                f"llmlb_engine_errors_total {self.errors_total}",
                "# TYPE llmlb_engine_cancelled_total counter",
                f"llmlb_engine_cancelled_total {self.cancelled_total}",
                "# TYPE llmlb_engine_queue_depth gauge",
                f"llmlb_engine_queue_depth {queue_depth}",
                "# TYPE llmlb_engine_active_slots gauge",
                f"llmlb_engine_active_slots {active_slots}",
                "# TYPE llmlb_engine_num_slots gauge",
                f"llmlb_engine_num_slots {num_slots}",
                "# TYPE llmlb_engine_batch_occupancy gauge",
                f"llmlb_engine_batch_occupancy {self.batch_occupancy}",
                "# TYPE llmlb_engine_prefix_cache_hits_total counter",
                f"llmlb_engine_prefix_cache_hits_total {self.prefix_hits_total}",
                "# TYPE llmlb_engine_prefix_cache_misses_total counter",
                "llmlb_engine_prefix_cache_misses_total "
                f"{self.prefix_misses_total}",
                "# TYPE llmlb_engine_prefix_cache_cached_tokens_total counter",
                "llmlb_engine_prefix_cache_cached_tokens_total "
                f"{self.prefix_cached_tokens_total}",
                "# TYPE llmlb_engine_prefix_cache_insertions_total counter",
                "llmlb_engine_prefix_cache_insertions_total "
                f"{self.prefix_insertions_total}",
                "# TYPE llmlb_engine_prefix_cache_inserted_tokens_total "
                "counter",
                "llmlb_engine_prefix_cache_inserted_tokens_total "
                f"{self.prefix_inserted_tokens_total}",
                "# TYPE llmlb_engine_prefix_cache_evictions_total counter",
                "llmlb_engine_prefix_cache_evictions_total "
                f"{self.prefix_evictions_total}",
                "# TYPE llmlb_engine_structured_requests_total counter",
                "llmlb_engine_structured_requests_total "
                f"{self.structured_requests_total}",
                "# TYPE llmlb_engine_masked_decode_steps_total counter",
                "llmlb_engine_masked_decode_steps_total "
                f"{self.masked_decode_steps_total}",
                "# TYPE llmlb_engine_constraint_violations_total counter",
                "llmlb_engine_constraint_violations_total "
                f"{self.constraint_violations_total}",
                "# TYPE llmlb_engine_mask_cache_hits_total counter",
                f"llmlb_engine_mask_cache_hits_total {self.mask_cache_hits_total}",
                "# TYPE llmlb_engine_mask_cache_misses_total counter",
                "llmlb_engine_mask_cache_misses_total "
                f"{self.mask_cache_misses_total}",
                "# TYPE llmlb_engine_mask_cache_evictions_total counter",
                "llmlb_engine_mask_cache_evictions_total "
                f"{self.mask_cache_evictions_total}",
                "# TYPE llmlb_engine_slow_steps_total counter",
                f"llmlb_engine_slow_steps_total {self.slow_steps_total}",
                "# TYPE llmlb_engine_spec_verify_steps_total counter",
                "llmlb_engine_spec_verify_steps_total "
                f"{self.spec_verify_steps_total}",
                "# TYPE llmlb_engine_spec_draft_tokens_total counter",
                "llmlb_engine_spec_draft_tokens_total "
                f"{self.spec_draft_tokens_total}",
                "# TYPE llmlb_engine_spec_accepted_tokens_total counter",
                "llmlb_engine_spec_accepted_tokens_total "
                f"{self.spec_accepted_tokens_total}",
                "# TYPE llmlb_engine_spec_emitted_tokens_total counter",
                "llmlb_engine_spec_emitted_tokens_total "
                f"{self.spec_emitted_tokens_total}",
                "# TYPE llmlb_engine_fused_decode_steps_total counter",
                "llmlb_engine_fused_decode_steps_total "
                f"{self.fused_decode_steps_total}",
                "# TYPE llmlb_engine_decode_dispatches_total counter",
                "llmlb_engine_decode_dispatches_total "
                f"{self.decode_dispatches_total}",
                "# TYPE llmlb_engine_constrained_burst_fallback_total "
                "counter",
                "llmlb_engine_constrained_burst_fallback_total "
                f"{self.constrained_burst_fallback_total}",
                "# TYPE llmlb_engine_preemptions_total counter",
                f"llmlb_engine_preemptions_total {self.preemptions_total}",
                "# TYPE llmlb_engine_preempt_resumes_total counter",
                "llmlb_engine_preempt_resumes_total "
                f"{self.preempt_resumes_total}",
                "# TYPE llmlb_engine_deadline_shed_total counter",
                f"llmlb_engine_deadline_shed_total {self.deadline_shed_total}",
                "# TYPE llmlb_engine_handoff_total counter",
            ]
            for kind in ("in_process", "emitted", "adopted"):
                lines.append(
                    f'llmlb_engine_handoff_total{{kind="{kind}"}} '
                    f"{self.handoff_total[kind]}"
                )
            lines += [
                "# TYPE llmlb_engine_handoff_backlog gauge",
                f"llmlb_engine_handoff_backlog {self.handoff_backlog}",
                "# TYPE llmlb_engine_drain_state gauge",
                f"llmlb_engine_drain_state {self.drain_state}",
                "# TYPE llmlb_engine_drain_parked_total counter",
                f"llmlb_engine_drain_parked_total {self.drain_parked_total}",
                "# TYPE llmlb_engine_kv_ship_total counter",
                f"llmlb_engine_kv_ship_total {self.kv_ship_total}",
                "# TYPE llmlb_engine_kv_ship_bytes_total counter",
                f"llmlb_engine_kv_ship_bytes_total {self.kv_ship_bytes_total}",
                "# TYPE llmlb_engine_kv_ship_seconds_total counter",
                "llmlb_engine_kv_ship_seconds_total "
                f"{self.kv_ship_seconds_total}",
                "# TYPE llmlb_engine_kv_restored_total counter",
                f"llmlb_engine_kv_restored_total {self.kv_restored_total}",
                "# TYPE llmlb_engine_kv_restored_bytes_total counter",
                "llmlb_engine_kv_restored_bytes_total "
                f"{self.kv_restored_bytes_total}",
                "# TYPE llmlb_engine_kv_ship_fallback_total counter",
            ]
            for reason in KV_FALLBACK_REASONS:
                lines.append(
                    f'llmlb_engine_kv_ship_fallback_total{{reason="{reason}"}}'
                    f" {self.kv_ship_fallback_total[reason]}"
                )
            if kv_offload is not None and kv_offload.get("enabled"):
                lines += [
                    "# TYPE llmlb_engine_kv_offload_budget_bytes gauge",
                    "llmlb_engine_kv_offload_budget_bytes "
                    f"{kv_offload.get('budget_bytes', 0)}",
                    "# TYPE llmlb_engine_kv_offload_bytes gauge",
                    f"llmlb_engine_kv_offload_bytes {kv_offload.get('bytes', 0)}",
                    "# TYPE llmlb_engine_kv_offload_entries gauge",
                    "llmlb_engine_kv_offload_entries "
                    f"{kv_offload.get('entries', 0)}",
                    "# TYPE llmlb_engine_kv_offload_hits_total counter",
                    f"llmlb_engine_kv_offload_hits_total {kv_offload.get('hits', 0)}",
                    "# TYPE llmlb_engine_kv_offload_misses_total counter",
                    "llmlb_engine_kv_offload_misses_total "
                    f"{kv_offload.get('misses', 0)}",
                    "# TYPE llmlb_engine_kv_offload_spills_total counter",
                    "llmlb_engine_kv_offload_spills_total "
                    f"{kv_offload.get('spills', 0)}",
                    "# TYPE llmlb_engine_kv_offload_evictions_total counter",
                    "llmlb_engine_kv_offload_evictions_total "
                    f"{kv_offload.get('evictions', 0)}",
                    "# TYPE llmlb_engine_kv_offload_spilled_bytes_total counter",
                    "llmlb_engine_kv_offload_spilled_bytes_total "
                    f"{kv_offload.get('spilled_bytes', 0)}",
                    "# TYPE llmlb_engine_kv_offload_restored_bytes_total "
                    "counter",
                    "llmlb_engine_kv_offload_restored_bytes_total "
                    f"{kv_offload.get('restored_bytes', 0)}",
                ]
            if sched is not None:
                lines.append(
                    "# TYPE llmlb_engine_queue_depth_class gauge"
                )
                for name, depth in sorted(
                    (sched.get("queued_by_class") or {}).items()
                ):
                    lines.append(
                        f'llmlb_engine_queue_depth_class'
                        f'{{priority="{name}"}} {depth}'
                    )
                by_role = sched.get("queued_by_role")
                if by_role:
                    # split-mode engines only: work waiting for a prefill
                    # slot vs prefilled work waiting for decode adoption
                    lines.append(
                        "# TYPE llmlb_engine_queue_depth_role gauge"
                    )
                    for name, depth in sorted(by_role.items()):
                        lines.append(
                            f'llmlb_engine_queue_depth_role'
                            f'{{role="{name}"}} {depth}'
                        )
            if lora is not None and lora.get("enabled"):
                # Multi-LoRA serving (docs/lora.md): residency gauges scrape
                # the manager's live state; load/evict counters and the
                # per-adapter request counter are event-sourced above.
                lines += [
                    "# TYPE llmlb_engine_lora_loaded gauge",
                    "llmlb_engine_lora_loaded "
                    f"{len(lora.get('resident') or ())}",
                    "# TYPE llmlb_engine_lora_available gauge",
                    "llmlb_engine_lora_available "
                    f"{len(lora.get('available') or ())}",
                    "# TYPE llmlb_engine_lora_max_adapters gauge",
                    "llmlb_engine_lora_max_adapters "
                    f"{lora.get('max_adapters', 0)}",
                    "# TYPE llmlb_engine_lora_loads_total counter",
                    f"llmlb_engine_lora_loads_total {self.lora_loads_total}",
                    "# TYPE llmlb_engine_lora_evictions_total counter",
                    "llmlb_engine_lora_evictions_total "
                    f"{self.lora_evictions_total}",
                    "# TYPE llmlb_engine_lora_cp_fallback_total counter",
                    "llmlb_engine_lora_cp_fallback_total "
                    f"{self.lora_cp_fallback_total}",
                ]
                if self.lora_requests_total:
                    lines.append(
                        "# TYPE llmlb_engine_lora_requests_total counter"
                    )
                    for name_, count in sorted(
                        self.lora_requests_total.items()
                    ):
                        lines.append(
                            'llmlb_engine_lora_requests_total'
                            f'{{adapter="{name_}"}} {count}'
                        )
                hname = "llmlb_engine_lora_load_seconds"
                lines.append(f"# TYPE {hname} histogram")
                _render_histogram(lines, hname, self.lora_load)
            if flightrec is not None and flightrec.get("enabled"):
                lines += [
                    "# TYPE llmlb_engine_flightrec_events_total counter",
                    "llmlb_engine_flightrec_events_total "
                    f"{flightrec.get('events_total', 0)}",
                    "# TYPE llmlb_engine_flightrec_events_dropped_total "
                    "counter",
                    "llmlb_engine_flightrec_events_dropped_total "
                    f"{flightrec.get('events_dropped_total', 0)}",
                    "# TYPE llmlb_engine_flightrec_requests_tracked gauge",
                    "llmlb_engine_flightrec_requests_tracked "
                    f"{flightrec.get('requests_tracked', 0)}",
                    "# TYPE llmlb_engine_flightrec_queue_seconds_total "
                    "counter",
                    "llmlb_engine_flightrec_queue_seconds_total "
                    f"{flightrec.get('queue_seconds_total', 0.0)}",
                    "# TYPE llmlb_engine_flightrec_service_seconds_total "
                    "counter",
                    "llmlb_engine_flightrec_service_seconds_total "
                    f"{flightrec.get('service_seconds_total', 0.0)}",
                ]
            if perf is not None and perf.get("available"):
                lines += [
                    "# TYPE llmlb_engine_mfu_ratio gauge",
                    f"llmlb_engine_mfu_ratio {perf['mfu']}",
                    "# TYPE llmlb_engine_hbm_bw_utilization_ratio gauge",
                    "llmlb_engine_hbm_bw_utilization_ratio "
                    f"{perf['hbm_bw_utilization']}",
                    "# TYPE llmlb_engine_model_flops_per_token gauge",
                    "llmlb_engine_model_flops_per_token "
                    f"{perf['flops_per_token']}",
                    "# TYPE llmlb_engine_model_bytes_per_token gauge",
                    "llmlb_engine_model_bytes_per_token "
                    f"{perf['bytes_per_token']}",
                ]
            if structured is not None and structured.get("enabled"):
                lines += [
                    "# TYPE llmlb_engine_mask_cache_entries gauge",
                    "llmlb_engine_mask_cache_entries "
                    f"{structured['mask_cache_entries']}",
                    "# TYPE llmlb_engine_mask_cache_bytes gauge",
                    "llmlb_engine_mask_cache_bytes "
                    f"{structured['mask_cache_bytes']}",
                ]
            if prefix_cache is not None and prefix_cache.get("enabled"):
                lines += [
                    "# TYPE llmlb_engine_prefix_cache_entries gauge",
                    "llmlb_engine_prefix_cache_entries "
                    f"{prefix_cache['entries']}",
                    "# TYPE llmlb_engine_prefix_cache_pinned_slots gauge",
                    "llmlb_engine_prefix_cache_pinned_slots "
                    f"{prefix_cache['pinned_slots']}",
                    "# TYPE llmlb_engine_prefix_cache_pinned_hbm_bytes gauge",
                    "llmlb_engine_prefix_cache_pinned_hbm_bytes "
                    f"{prefix_cache['pinned_hbm_bytes']}",
                ]
                if "pinned_pages" in prefix_cache:
                    lines += [
                        "# TYPE llmlb_engine_prefix_cache_pinned_pages gauge",
                        "llmlb_engine_prefix_cache_pinned_pages "
                        f"{prefix_cache['pinned_pages']}",
                    ]
            if quant is not None:
                # info-style gauge: one series per mode, active one = 1, so
                # dashboards can legend the running quantization mode
                lines.append("# TYPE llmlb_engine_quant_mode gauge")
                for mode in ("off", "weights", "kv", "all"):
                    lines.append(
                        f'llmlb_engine_quant_mode{{mode="{mode}"}} '
                        f'{1 if quant.get("mode") == mode else 0}'
                    )
                lines += [
                    "# TYPE llmlb_engine_param_bytes gauge",
                    f"llmlb_engine_param_bytes {quant.get('param_bytes', 0)}",
                ]
            if kv_cache is not None:
                # honest-dtype KV footprint: renders for BOTH layouts so
                # capacity dashboards never fall back to implied-bf16 math
                lines += [
                    "# TYPE llmlb_engine_kv_hbm_bytes gauge",
                    f"llmlb_engine_kv_hbm_bytes {kv_cache.get('hbm_bytes', 0)}",
                ]
            if kv_cache is not None and kv_cache.get("layout") == "paged":
                lines += [
                    "# TYPE llmlb_engine_kv_bytes_per_page gauge",
                    "llmlb_engine_kv_bytes_per_page "
                    f"{kv_cache.get('bytes_per_page', 0)}",
                    "# TYPE llmlb_engine_kv_pages_total gauge",
                    f"llmlb_engine_kv_pages_total {kv_cache['pages_total']}",
                    "# TYPE llmlb_engine_kv_pages_free gauge",
                    f"llmlb_engine_kv_pages_free {kv_cache['pages_free']}",
                    "# TYPE llmlb_engine_kv_pages_active gauge",
                    f"llmlb_engine_kv_pages_active {kv_cache['pages_active']}",
                    "# TYPE llmlb_engine_kv_pages_pinned gauge",
                    f"llmlb_engine_kv_pages_pinned {kv_cache['pages_pinned']}",
                    "# TYPE llmlb_engine_kv_page_size_tokens gauge",
                    f"llmlb_engine_kv_page_size_tokens {kv_cache['page_size']}",
                    "# TYPE llmlb_engine_kv_pool_utilization_ratio gauge",
                    "llmlb_engine_kv_pool_utilization_ratio "
                    f"{kv_cache['utilization']}",
                    "# TYPE llmlb_engine_kv_page_fragmentation_ratio gauge",
                    "llmlb_engine_kv_page_fragmentation_ratio "
                    f"{kv_cache['fragmentation']}",
                    "# TYPE llmlb_engine_kv_page_waste_tokens_mean gauge",
                    "llmlb_engine_kv_page_waste_tokens_mean "
                    f"{kv_cache['waste_tokens_mean']}",
                ]
            for name, hist in (
                ("llmlb_engine_ttft_seconds", self.ttft),
                ("llmlb_engine_itl_seconds", self.itl),
                ("llmlb_engine_prefill_step_seconds", self.prefill_step),
                ("llmlb_engine_decode_step_seconds", self.decode_step),
                ("llmlb_engine_schema_compile_seconds", self.schema_compile),
                ("llmlb_engine_handoff_latency_seconds",
                 self.handoff_latency),
            ):
                lines.append(f"# TYPE {name} histogram")
                _render_histogram(lines, name, hist)
            # per-phase step breakdown: one histogram family labeled by
            # phase (engine/stepstats.py taxonomy); empty phases still
            # render so dashboards see a complete label set
            name = "llmlb_engine_step_phase_seconds"
            lines.append(f"# TYPE {name} histogram")
            for phase, hist in self.step_phase.items():
                _render_histogram(lines, name, hist, label=f'phase="{phase}"')
            return "\n".join(lines) + "\n"
