"""Leader-follower lockstep for multi-host serving.

When a model is sharded across hosts (jax.distributed, SURVEY.md §2.4
TPU-native equivalents), every jitted step is a cross-process collective:
ALL processes must dispatch the same program in the same order or the
cluster deadlocks. HTTP requests only arrive at one process, so the serving
loop needs a control plane:

- host 0 (leader) serves HTTP and owns the request queue;
- each engine tick, the leader broadcasts a *plan* — new requests,
  cancellations, shutdown — over the jax.distributed CPU mesh
  (broadcast_one_to_all; rides DCN, not ICI);
- every host then runs the identical scheduler logic on mirrored state, so
  the sequence of device programs (prefill / chunk / decode / sample) is
  identical everywhere, and the RNG key streams stay in lockstep because
  they advance with the same ops from the same seed.

The broadcast is two-phase (length, then padded payload) because
broadcast_one_to_all needs identical shapes on every process while plans are
variable-size. The reference has no counterpart — its "distributed backend"
is HTTP between gateway and single-host runtimes.
"""

from __future__ import annotations

import pickle

import jax
import numpy as np

_MAX_PLAN_BYTES = 64 * 1024 * 1024  # sanity bound: a plan is requests, not data


class StepCoordinator:
    """Per-tick plan broadcast from the leader to every follower."""

    def __init__(self):
        self.num_hosts = jax.process_count()
        self.is_leader = jax.process_index() == 0

    def exchange(self, plan: dict | None) -> dict:
        """Leader passes its plan (possibly empty); followers pass None.
        Returns the leader's plan on every host. Blocking: this is the
        synchronization point that keeps hosts in lockstep."""
        from jax.experimental import multihost_utils as mhu

        payload = pickle.dumps(plan) if self.is_leader else b""
        if len(payload) > _MAX_PLAN_BYTES:
            raise ValueError(f"tick plan too large: {len(payload)} bytes")
        n = mhu.broadcast_one_to_all(
            np.asarray([len(payload)], np.int64)
        )
        buf = np.zeros((int(n[0]),), np.uint8)
        if self.is_leader:
            buf[:] = np.frombuffer(payload, np.uint8)
        buf = mhu.broadcast_one_to_all(buf)
        return pickle.loads(buf.tobytes())
