"""Refcounted page allocator for the paged KV cache.

The paged layout replaces the engine's dense per-slot KV block
[L, slots, slot_capacity, K, D] with one global page pool
[L, num_pages, page_size, K, D] plus a per-slot block table mapping logical
token positions to pool pages. This module owns the pure host-side
bookkeeping: a free list and per-page refcounts. No jax, no locks — every
call happens on the scheduler's step-loop thread (in multihost lockstep all
hosts run the same deterministic sequence of calls, so pools stay mirrored).

Refcount semantics:
- `alloc(n)` hands out n pages with refcount 1, all-or-nothing (None when the
  pool cannot cover the request — the caller evicts prefix pages or queues).
- `ref(page)` adds an owner: the prefix cache pins donated prompt pages this
  way, and a cache hit adds the reading slot as a second owner of the shared
  pages (zero-copy sharing — no KV bytes move).
- `unref(page)` drops an owner and returns the page to the free list at zero.
  Unref of an already-free page raises PageError: a double free means two
  owners think they hold the same page and silent reuse would corrupt KV.
- OWNERSHIP TRANSFER needs no refcount traffic at all: split-mode handoff
  (llmlb_tpu/disagg/split.py, docs/disaggregation.md) moves a whole
  block-table row from a prefill slot to a decode slot — the refcount held
  by "the slot that owns this row" simply changes which slot that is. It is
  a ref(new)+unref(old) pair collapsed to nothing; the invariant that
  exactly one live table row references an owned page is what makes the
  exchange safe, and it is why the donor slot's row must be zeroed in the
  same step the adopter's row is written.

Page 0 is reserved as the *trash page* (refcount pinned forever): block-table
entries default to it, so the batched decode step's garbage writes for
empty/parked slot rows land in cells nothing ever reads — the paged
counterpart of the dense layout's "garbage lands in the unused last cell".
"""

from __future__ import annotations


class PageError(RuntimeError):
    """Page-pool bookkeeping violation (double free / unknown page)."""


class PagePool:
    """Free-list allocator with refcounted pages over `num_pages` pages.

    `reserved` pages are pinned at construction and never allocated or
    freed (the trash page). Not threadsafe by design — step-loop only.
    """

    def __init__(self, num_pages: int, *, reserved: tuple[int, ...] = (0,)):
        if num_pages < len(reserved) + 1:
            raise ValueError(
                f"pool of {num_pages} pages cannot reserve {reserved} and "
                "still serve traffic"
            )
        self.num_pages = num_pages
        self.reserved = frozenset(reserved)
        self._refs = [0] * num_pages
        for p in self.reserved:
            self._refs[p] = 1  # pinned forever
        # LIFO free list: recently-freed pages are reused first (their HBM
        # is warm in whatever cache hierarchy the platform has)
        self._free = [p for p in range(num_pages - 1, -1, -1)
                      if p not in self.reserved]

    # ------------------------------------------------------------- inspection

    @property
    def total(self) -> int:
        """Allocatable pages (reserved pages excluded)."""
        return self.num_pages - len(self.reserved)

    def available(self) -> int:
        return len(self._free)

    def used(self) -> int:
        return self.total - len(self._free)

    def refcount(self, page: int) -> int:
        return self._refs[page]

    # ------------------------------------------------------------- allocation

    def alloc(self, n: int) -> list[int] | None:
        """Take `n` pages (refcount 1 each). All-or-nothing: returns None
        without side effects when fewer than `n` pages are free."""
        if n < 0:
            raise ValueError("cannot allocate a negative page count")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def ref(self, page: int) -> None:
        """Add an owner to a live page (prefix-cache pin / zero-copy share)."""
        self._check(page)
        if self._refs[page] <= 0:
            raise PageError(f"ref of free page {page}")
        self._refs[page] += 1

    def unref(self, page: int) -> None:
        """Drop an owner; the page returns to the free list at refcount 0.
        Raises PageError on double free (page already free or reserved)."""
        self._check(page)
        if page in self.reserved:
            raise PageError(f"unref of reserved page {page}")
        if self._refs[page] <= 0:
            raise PageError(f"double free of page {page}")
        self._refs[page] -= 1
        if self._refs[page] == 0:
            self._free.append(page)

    def _check(self, page: int) -> None:
        if not 0 <= page < self.num_pages:
            raise PageError(f"page {page} outside pool of {self.num_pages}")

    def reset(self) -> None:
        """Return every non-reserved page to the free list (engine failure
        path: the device pool is rebuilt, every mapping is void)."""
        for p in range(self.num_pages):
            self._refs[p] = 1 if p in self.reserved else 0
        self._free = [p for p in range(self.num_pages - 1, -1, -1)
                      if p not in self.reserved]
