"""Refcounted radix-tree prefix KV cache over the engine's slot cache.

Production chat traffic is dominated by long shared prefixes (system prompts,
few-shot templates, multi-turn history); re-running prefill for them is the
single largest remaining prefill cost on the TPU path. This module is the
host-side index for reusing that work: a path-compressed radix tree keyed on
prompt token ids whose entries pin completed prefix KV rows in retained
"donor" slots of the static-shape slot cache [L, NUM_SLOTS, CAP, K, D].

Division of labor:
- This module owns the pure bookkeeping — insert/match/refcount/evict over
  token sequences and pinned slot ids. No jax, no device state, no locks
  (all calls happen on the scheduler's step-loop thread; in multihost
  lockstep every host runs the same deterministic sequence of calls, so the
  trees stay mirrored).
- The scheduler (scheduler.py) owns the device side: copying matched rows
  into a fresh slot with one jitted dynamic_update_slice and chunk-prefilling
  only the uncached suffix, plus deciding WHEN to insert (request completion)
  and evict (pinned budget / slot pressure).

Correctness hinges on one property of causal attention: the KV rows for
positions [0, m) depend only on tokens [0, m), so any stored prefix can
donate any of its own prefixes. Entries therefore store the full token
sequence they cover, and a match may use a partial head of an entry (the
longest common prefix with the query), never just exact node boundaries.

Refcounts guard in-flight readers: a hit acquires the entry for the duration
of its suffix prefill (released on activation, cancellation, or engine
failure) and acquired entries are never evicted. Eviction is LRU over a
logical clock bumped on every match/insert/touch.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class PrefixEntry:
    """One cached prefix. Dense layout: `tokens` are resident as KV rows
    [0, len(tokens)) of pinned slot `slot` in the engine's slot cache.
    Paged layout: `pages` names the pool pages holding those rows in order
    (`slot` is -1; the donor slot itself was freed at donation time — a hit
    copies the page ids into the reader's block table with a refcount bump,
    never the KV bytes)."""

    tokens: tuple[int, ...]
    slot: int
    refcount: int = 0
    last_used: int = 0
    pages: tuple[int, ...] | None = None
    node: "_Node | None" = dataclasses.field(default=None, repr=False)
    # key in the cache's entry dict (the slot for dense entries, a unique
    # negative id for paged ones — freed slots recycle their ids, pages don't)
    key: int = dataclasses.field(default=0, repr=False)
    # namespace the entry was inserted under (LoRA adapter or None) — the
    # host-RAM offload tier re-keys spilled entries by (ns, tokens)
    ns: object = dataclasses.field(default=None, repr=False)

    @property
    def length(self) -> int:
        return len(self.tokens)


class _Node:
    """Path-compressed radix node: `edge` is the token run from the parent."""

    __slots__ = ("edge", "children", "entry", "parent")

    def __init__(self, edge: tuple[int, ...], parent: "_Node | None" = None):
        self.edge = edge
        self.children: dict[int, _Node] = {}
        self.entry: PrefixEntry | None = None
        self.parent = parent


def _common_len(a, b) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


class PrefixCache:
    """Radix-tree index of pinned prefix slots. Not threadsafe by design —
    see module docstring (step-loop-thread only)."""

    def __init__(self, *, max_entries: int, min_len: int, align: int):
        if max_entries < 0:
            raise ValueError("max_entries must be >= 0")
        if align < 1 or min_len < 1:
            raise ValueError("align and min_len must be >= 1")
        self.max_entries = max_entries
        self.min_len = min_len
        self.align = align
        # One radix tree per namespace (`ns`): under multi-LoRA serving the
        # prompt KV depends on the adapter's wq/wk/wv deltas, so two
        # adapters sharing a prompt must NEVER share cached KV — an
        # adapter-blind hit would be silent corruption (docs/lora.md). The
        # default ns=None tree is the historical adapter-free cache, bit
        # for bit; budget and LRU stay GLOBAL across namespaces (one donor
        # pool, shared fairly by eviction pressure, like the PR 5 mask
        # cache's single LRU over many schemas).
        self._roots: dict[object, _Node] = {None: _Node(())}
        # keyed by entry.key: the donor slot id for dense entries, a unique
        # negative id for paged (page-backed) entries
        self._by_slot: dict[int, PrefixEntry] = {}
        self._cached_tokens = 0
        self._clock = 0
        self._next_paged_key = -2  # -1 is the scheduler's "no slot" marker

    def _root_for(self, ns) -> "_Node":
        root = self._roots.get(ns)
        if root is None:
            root = self._roots[ns] = _Node(())
        return root

    # ------------------------------------------------------------- inspection
    #
    # __len__ and cached_tokens read single ints / dict size — safe to call
    # from scrape threads (/metrics, /api/health) while the step loop
    # mutates. Everything else, including pinned_slots/entries (they iterate
    # the dict), is step-loop-thread only.

    def __len__(self) -> int:
        return len(self._by_slot)

    def pinned_slots(self) -> frozenset[int]:
        """Donor SLOTS held out of the serving pool — dense entries only
        (page-backed donors pin pages, their slots were freed at donation)."""
        return frozenset(
            e.slot for e in self._by_slot.values() if e.pages is None
        )

    def cached_tokens(self) -> int:
        return self._cached_tokens

    def entries(self) -> list[PrefixEntry]:
        return list(self._by_slot.values())

    # ------------------------------------------------------------------ clock

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # ------------------------------------------------------------------ match

    def _walk(self, tokens, ns=None) -> tuple[int, _Node]:
        """Follow `tokens` as far as they match within namespace `ns`.
        Returns (matched_len, last_node_entered). The last node may be only
        partially matched (mismatch mid-edge); every entry in its subtree
        still shares the first `matched_len` tokens with the query."""
        node = self._root_for(ns)
        matched = 0
        while matched < len(tokens):
            child = node.children.get(tokens[matched])
            if child is None:
                break
            lcp = _common_len(child.edge, tokens[matched:])
            matched += lcp
            node = child
            if lcp < len(child.edge):
                break  # diverged mid-edge; subtree still shares `matched`
        return matched, node

    @staticmethod
    def _any_entry(node: _Node) -> PrefixEntry | None:
        """Any entry at or below `node` (DFS). Every one stores a superset
        of the matched path, so any can donate the matched head."""
        stack = [node]
        while stack:
            n = stack.pop()
            if n.entry is not None:
                return n.entry
            stack.extend(n.children.values())
        return None

    def match(self, tokens, *, max_len: int,
              ns=None) -> tuple[PrefixEntry, int] | None:
        """Longest reusable cached prefix of `tokens`: returns (entry,
        use_len) where entry's slot holds valid KV for rows [0, use_len) and
        use_len is capped at `max_len` (the caller must leave at least one
        suffix token to prefill, so it passes len(tokens) - 1) and aligned
        down to the prefill-bucket quantum. None when nothing aligned and
        >= min_len is cached. Bumps the winning entry's LRU clock."""
        if max_len < self.min_len or not self._by_slot:
            return None
        matched, node = self._walk(tokens, ns)
        if not matched:
            return None
        # pruning keeps every non-empty subtree holding >= 1 entry, so a
        # positive walk always finds a donor covering the matched head
        entry = self._any_entry(node)
        if entry is None:
            return None
        usable = min(matched, max_len)
        usable = (usable // self.align) * self.align
        if usable < self.min_len:
            return None
        entry.last_used = self._tick()
        return entry, usable

    def covers(self, tokens, ns=None) -> bool:
        """True if some entry already holds ALL of `tokens` as its head —
        inserting them again would pin a second slot for no new coverage."""
        matched, node = self._walk(tokens, ns)
        return matched == len(tokens) and self._any_entry(node) is not None

    def touch(self, tokens, ns=None) -> None:
        """Refresh the LRU clock of the entry covering `tokens` (a completed
        request whose prefix was already cached is a use of that entry)."""
        matched, node = self._walk(tokens, ns)
        if matched == len(tokens):
            entry = self._any_entry(node)
            if entry is not None:
                entry.last_used = self._tick()

    # --------------------------------------------------------------- refcount

    def acquire(self, entry: PrefixEntry) -> None:
        entry.refcount += 1

    def release(self, entry: PrefixEntry) -> None:
        if entry.refcount > 0:
            entry.refcount -= 1

    # ----------------------------------------------------------------- insert

    def insert(self, tokens, slot: int,
               pages: tuple[int, ...] | None = None,
               ns=None) -> PrefixEntry | None:
        """Pin a donor for prefix `tokens` in namespace `ns`: slot `slot`
        (dense) or the pool pages `pages` (paged; pass slot=-1). Returns
        the new entry, or None when rejected (budget full, duplicate
        coverage, or a slot already pinned). The caller aligns/filters
        lengths, evicts to make room first, and owns the page refcounts."""
        tokens = tuple(tokens)
        if (not tokens
                or (pages is None and slot in self._by_slot)
                or len(self._by_slot) >= self.max_entries
                or self.covers(tokens, ns)):
            return None
        node = self._root_for(ns)
        pos = 0
        while pos < len(tokens):
            child = node.children.get(tokens[pos])
            if child is None:
                leaf = _Node(tokens[pos:], node)
                node.children[tokens[pos]] = leaf
                node = leaf
                pos = len(tokens)
                break
            lcp = _common_len(child.edge, tokens[pos:])
            if lcp < len(child.edge):
                # split the edge at the divergence point
                mid = _Node(child.edge[:lcp], node)
                node.children[tokens[pos]] = mid
                child.edge = child.edge[lcp:]
                child.parent = mid
                mid.children[child.edge[0]] = child
                node = mid
            else:
                node = child
            pos += lcp
        if pages is None:
            key = slot
        else:
            key = self._next_paged_key
            self._next_paged_key -= 1
        entry = PrefixEntry(tokens=tokens, slot=slot, pages=pages,
                            last_used=self._tick(), node=node, key=key,
                            ns=ns)
        node.entry = entry
        self._by_slot[key] = entry
        self._cached_tokens += entry.length
        return entry

    # ------------------------------------------------------------------ evict

    def evict_subsumed(self, tokens, ns=None) -> list[int]:
        """Remove entries whose tokens are a STRICT prefix of `tokens`,
        returning their freed slots (see evict_subsumed_entries)."""
        return [e.slot for e in self.evict_subsumed_entries(tokens, ns)]

    def evict_subsumed_entries(self, tokens, ns=None) -> list["PrefixEntry"]:
        """Remove entries whose tokens are a STRICT prefix of `tokens` (and
        have no in-flight readers), returning them so the caller can release
        their donor slots / page references. Called before inserting
        `tokens`: any query matching a shorter ancestor also matches through
        the longer entry's subtree, so the ancestor is dead weight — without
        this, each turn of a growing conversation would pin a fresh donor
        until the budget was exhausted."""
        tokens = tuple(tokens)
        victims: list[PrefixEntry] = []
        node = self._root_for(ns)
        pos = 0
        while pos < len(tokens):
            child = node.children.get(tokens[pos])
            if child is None:
                break
            lcp = _common_len(child.edge, tokens[pos:])
            if lcp < len(child.edge):
                break  # diverged mid-edge: nothing deeper is a strict prefix
            node = child
            pos += lcp
            if (node.entry is not None and pos < len(tokens)
                    and node.entry.refcount == 0):
                victims.append(node.entry)
        for entry in victims:
            self._remove(entry)
        return victims

    def evict_lru(self) -> int | None:
        """Remove the least-recently-used entry with no in-flight readers.
        Returns the freed slot id (the scheduler returns it to the free
        pool), or None when every entry is acquired."""
        entry = self.evict_lru_entry()
        return None if entry is None else entry.slot

    def evict_lru_entry(self) -> PrefixEntry | None:
        """evict_lru returning the whole entry — the paged scheduler needs
        the page list to release its references."""
        victim: PrefixEntry | None = None
        for entry in self._by_slot.values():
            if entry.refcount:
                continue
            if victim is None or entry.last_used < victim.last_used:
                victim = entry
        if victim is None:
            return None
        self._remove(victim)
        return victim

    def _remove(self, entry: PrefixEntry) -> None:
        del self._by_slot[entry.key]
        self._cached_tokens -= entry.length
        node = entry.node
        entry.node = None
        if node is None:
            return
        node.entry = None
        # prune now-useless nodes: drop empty leaves, merge single-child
        # pass-through nodes back into their child's edge
        while node is not None and node.parent is not None:
            parent = node.parent
            if node.entry is None and not node.children:
                del parent.children[node.edge[0]]
            elif node.entry is None and len(node.children) == 1:
                (child,) = node.children.values()
                child.edge = node.edge + child.edge
                child.parent = parent
                parent.children[child.edge[0]] = child
            else:
                break
            node = parent

    def clear(self) -> None:
        """Drop everything — the device KV the entries pointed at is gone
        (engine failure path rebuilds the slot cache)."""
        self._roots = {None: _Node(())}
        self._by_slot.clear()
        self._cached_tokens = 0
