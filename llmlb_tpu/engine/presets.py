"""Named model presets for the tpu:// engine.

Presets let the engine start without a checkpoint directory (random weights) for
benches/tests, and pin the architectural config for well-known checkpoints so
serving starts before config.json is even read. Shapes follow the public model
cards; none of this data comes from the reference repo (which stores only
name→engine alias mappings, /root/reference/llmlb/src/models/mapping.rs).
"""

from __future__ import annotations

import jax.numpy as jnp

from llmlb_tpu.models.llama import LlamaConfig
from llmlb_tpu.models.mixtral import MixtralConfig
from llmlb_tpu.ops.rope import RopeScaling

PRESETS: dict[str, LlamaConfig] = {
    # sparse-MoE flagship (BASELINE.json config #5: multi-slice v5e target);
    # served via models/mixtral.py with experts on the mesh ep axis
    "mixtral-8x7b": MixtralConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=1000000.0,
        rms_eps=1e-5, max_position_embeddings=32768,
        num_experts=8, experts_per_token=2,
    ),
    # CI-sized MoE config for unit tests and the multichip dry-run
    "debug-moe-tiny": MixtralConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, dtype=jnp.float32,
        max_position_embeddings=128, num_experts=4, experts_per_token=2,
    ),
    # flagship serving target (BASELINE.json config #2)
    "llama-3-8b": LlamaConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=500000.0,
        rms_eps=1e-5, max_position_embeddings=8192,
    ),
    "llama-3.1-8b": LlamaConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=500000.0,
        rope_scaling=RopeScaling(factor=8.0, low_freq_factor=1.0,
                                 high_freq_factor=4.0, original_max_position=8192),
        rms_eps=1e-5, max_position_embeddings=131072,
    ),
    # 1B-class: fits one v5e chip with headroom; the single-chip bench model
    "tinyllama-1.1b": LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_layers=22, num_heads=32, num_kv_heads=4, rope_theta=10000.0,
        rms_eps=1e-5, max_position_embeddings=2048,
    ),
    "qwen2.5-0.5b": LlamaConfig(
        vocab_size=151936, hidden_size=896, intermediate_size=4864,
        num_layers=24, num_heads=14, num_kv_heads=2, rope_theta=1000000.0,
        rms_eps=1e-6, attention_bias=True, tie_word_embeddings=True,
        max_position_embeddings=32768,
    ),
    # CI-sized config for unit tests and the multichip dry-run
    "debug-tiny": LlamaConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=8, num_kv_heads=4, dtype=jnp.float32,
        max_position_embeddings=512,
    ),
}


def get_preset(name: str) -> LlamaConfig:
    try:
        return PRESETS[name]
    except KeyError:
        raise KeyError(
            f"unknown model preset {name!r}; available: {sorted(PRESETS)}"
        ) from None
