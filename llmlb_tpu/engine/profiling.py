"""On-demand jax.profiler capture for a live engine.

The reference stack has no profiler surface (SURVEY §5: "no flamegraph/pprof
tooling"); on TPU this is how an operator answers "where do my step
milliseconds go" below the step-phase breakdown's resolution — XLA ops,
Pallas kernels, H2D/D2H transfers, per-core timelines, all without
restarting the serving process.

One ProfileManager per engine process guards the GLOBAL jax tracer (two
concurrent start_trace calls would corrupt each other): start → bounded
auto-stop timer → downloadable zip artifact. Captures are strictly opt-in
per request — nothing records until POST /api/profile starts a capture, and
every capture self-terminates at its bounded duration even if the client
never calls stop.

Gating: the engine port is unauthenticated by design (it sits behind the
gateway), so capture access is controlled by LLMLB_PROFILE_TOKEN — when
set, start/stop/artifact require `Authorization: Bearer <token>`. Unset
(dev/bench hosts), the endpoint is open like the rest of the engine API.
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile
import threading
import time
import uuid
import zipfile

log = logging.getLogger("llmlb_tpu.engine.profiling")

MAX_CAPTURE_S = 60.0  # the global tracer buffers in RAM; bound it hard
MAX_KEPT_CAPTURES = 4  # older trace dirs are deleted as new ones land


class ProfileError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class ProfileManager:
    """Start/stop lifecycle around jax.profiler's global tracer plus a
    small ledger of completed captures for artifact download."""

    def __init__(self, trace_root: str | None = None):
        self._lock = threading.Lock()
        self._active: dict | None = None  # {id, dir, started_at, seconds}
        self._timer: threading.Timer | None = None
        self._captures: list[dict] = []  # completed, newest last
        self._root_override = trace_root
        # Set while no capture runs, cleared for the duration of one:
        # wait_idle() parks on it instead of polling status() — set/clear
        # only ever happen with _lock held, so waiters can't miss an edge.
        self._idle = threading.Event()
        self._idle.set()

    # ---------------------------------------------------------------- control

    def start(self, seconds: float) -> dict:
        """Begin a capture with a bounded auto-stop. Raises ProfileError 409
        if one is already running."""
        import jax

        seconds = min(MAX_CAPTURE_S, max(0.1, float(seconds)))
        # Traces always land under a server-controlled root (resolved per
        # capture so LLMLB_TRACE_DIR set after startup is honored) — the
        # engine port is unauthenticated, so a client-supplied path would
        # be an arbitrary directory-write primitive.
        root = (self._root_override or os.environ.get("LLMLB_TRACE_DIR")
                or tempfile.gettempdir())
        with self._lock:
            if self._active is not None:
                raise ProfileError(409, "a profile capture is already running")
            # dir creation inside the lock, AFTER the busy check: a polling
            # client hammering start while a capture runs must not litter
            # the trace root with empty dirs the eviction never sees
            os.makedirs(root, exist_ok=True)
            out_dir = tempfile.mkdtemp(prefix="llmlb-trace-", dir=root)
            # start inside the lock: the tracer is global, and a concurrent
            # start would race the `_active` claim
            try:
                jax.profiler.start_trace(out_dir)
            except Exception as e:
                shutil.rmtree(out_dir, ignore_errors=True)
                raise ProfileError(500, f"profiler failed to start: {e}")
            capture = {
                "capture_id": uuid.uuid4().hex[:12],
                "trace_dir": out_dir,
                "started_at": time.time(),
                "seconds_requested": seconds,
            }
            self._active = capture
            self._idle.clear()
            self._timer = threading.Timer(seconds, self._auto_stop,
                                          args=(capture["capture_id"],))
            self._timer.daemon = True
            self._timer.start()
        log.info("profile capture %s started (%.1fs max) -> %s",
                 capture["capture_id"], seconds, out_dir)
        return {"capture_id": capture["capture_id"], "seconds": seconds,
                "trace_dir": out_dir}

    def stop(self) -> dict:
        """Stop the running capture early. Raises ProfileError 409 when
        nothing is recording."""
        done = self._finish(expected_id=None)
        if done is None:
            raise ProfileError(409, "no profile capture is running")
        return done

    def _auto_stop(self, capture_id: str) -> None:
        try:
            self._finish(expected_id=capture_id)
        except Exception:  # pragma: no cover - defensive: timer thread
            log.exception("profile auto-stop failed")

    def _finish(self, expected_id: str | None) -> dict | None:
        import jax

        # Claim the capture under the lock, but run stop_trace (which
        # SERIALIZES the whole trace — seconds for a long TPU capture) and
        # the size walk OUTSIDE it, so status()/start() callers — and
        # through them the server event loop — never block behind the
        # trace write. The claim (active -> None) makes the stop exclusive:
        # a concurrent stop sees None and 409s.
        with self._lock:
            active = self._active
            if active is None:
                return None
            if expected_id is not None and \
                    active["capture_id"] != expected_id:
                return None  # an explicit stop already closed this capture
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._active = None
            self._idle.set()
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            log.exception("profiler stop failed")
            active["error"] = f"stop failed: {e}"
        active["stopped_at"] = time.time()
        active["duration_s"] = round(
            active["stopped_at"] - active["started_at"], 3
        )
        active["bytes"] = _dir_bytes(active["trace_dir"])
        with self._lock:
            self._captures.append(active)
            # bound disk: drop the oldest trace dirs beyond the keep window
            evicted = []
            while len(self._captures) > MAX_KEPT_CAPTURES:
                evicted.append(self._captures.pop(0))
        for stale in evicted:
            shutil.rmtree(stale["trace_dir"], ignore_errors=True)
            zip_path = stale["trace_dir"].rstrip("/") + ".zip"
            try:
                os.unlink(zip_path)
            except OSError:
                pass
        log.info("profile capture %s stopped after %.2fs (%d bytes)",
                 active["capture_id"], active["duration_s"], active["bytes"])
        return self._public(active)

    # ---------------------------------------------------------------- reading

    @staticmethod
    def _public(capture: dict) -> dict:
        out = dict(capture)
        out["download"] = f"/api/profile/{capture['capture_id']}"
        return out

    def wait_idle(self, timeout_s: float) -> bool:
        """Block (a worker thread — never the event loop) until the running
        capture finishes, waking on the stop itself rather than polling
        status(). True when idle; False when the timeout passed first."""
        return self._idle.wait(timeout_s)

    def status(self) -> dict:
        with self._lock:
            active = dict(self._active) if self._active else None
            captures = [self._public(c) for c in reversed(self._captures)]
        if active is not None:
            active["elapsed_s"] = round(time.time() - active["started_at"], 2)
        return {"recording": active is not None, "active": active,
                "captures": captures}

    def artifact(self, capture_id: str) -> tuple[str, str]:
        """(zip path, download filename) of a completed capture's trace
        directory — the downloadable artifact for `tensorboard --logdir` /
        xprof. The zip is built ON DISK beside the trace dir (TPU captures
        run to hundreds of MB; buffering them in RAM on the serving host is
        not acceptable) and cached for repeat downloads. Call from a worker
        thread — deflate of a large trace takes seconds."""
        with self._lock:
            capture = next((c for c in self._captures
                            if c["capture_id"] == capture_id), None)
        if capture is None:
            raise ProfileError(404, f"no completed capture {capture_id!r}")
        root = capture["trace_dir"].rstrip("/")
        zip_path = root + ".zip"
        filename = f"llmlb-trace-{capture_id}.zip"
        if os.path.isfile(zip_path):
            return zip_path, filename
        # build to a temp name then rename: a concurrent download never
        # sees a half-written zip
        tmp_path = zip_path + ".tmp"
        try:
            names = 0
            with zipfile.ZipFile(tmp_path, "w", zipfile.ZIP_DEFLATED) as zf:
                for dirpath, _dirs, files in os.walk(root):
                    for name in files:
                        full = os.path.join(dirpath, name)
                        zf.write(full, os.path.relpath(full, root))
                        names += 1
            if names == 0:
                raise ProfileError(500, "capture produced no trace files")
            os.replace(tmp_path, zip_path)
        except OSError as e:
            # the eviction in _finish may rmtree this capture's dir while
            # we walk it — report it gone, not a raw 500 traceback
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
            raise ProfileError(
                404, f"capture {capture_id!r} no longer on disk: {e}"
            )
        return zip_path, filename


def _dir_bytes(root: str) -> int:
    total = 0
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                pass
    return total
