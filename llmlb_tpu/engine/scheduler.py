"""Continuous-batching scheduler: prefill/decode split over a slot cache.

JetStream-style serving loop, TPU-first:
- A fixed pool of NUM_SLOTS decode slots. KV lives in one of two layouts:
  paged (default) — a global page pool [L, PAGES, PAGE, K, D] plus a
  per-slot block table (engine/paging.py owns the refcounted allocator), so
  HBM is held per page of tokens actually cached and short requests no
  longer strand slot_capacity rows each; or dense — one static-shape cache
  [L, NUM_SLOTS, CAP, K, D], the original layout, preserved bit for bit
  behind --kv-layout dense. Either way one compiled `decode_step` serves
  every mix of requests — raggedness is masks and tables, never shapes.
- New requests prefill one at a time at bucketed prompt lengths (pow2 buckets ⇒
  a handful of compiles) and scatter straight into a free slot row
  (`prefill_into_slots`), while other slots keep decoding between prefills.
- Sampling params live in device arrays indexed by slot; updated on insert.
- The step loop runs in a dedicated thread; completions stream to waiters
  through per-request queues (asyncio- and thread-friendly).
- Prefix KV reuse (engine/prefix_cache.py): completed requests donate their
  slot to a refcounted radix tree keyed on prompt token ids; a later request
  sharing a prefix copies the cached rows with one device-side slice
  (no recompute) and chunk-prefills only the uncached suffix.
- Speculative decoding (llmlb_tpu/spec, docs/speculative.md): per-slot
  prompt-lookup drafters propose up to K tokens; one batched K+1-token
  verify dispatch through the extend path scores them all, the longest
  prefix matching the model's own samples is accepted (1..K+1 tokens per
  step), rejected suffixes roll back committed length and release
  over-allocated KV pages.

The reference has no equivalent (it proxies to external runtimes, SURVEY.md L0);
this is the in-tree `tpu://` engine of the BASELINE.json north star.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import math
import os
import queue
import threading
import time
import uuid
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from llmlb_tpu.engine.kv_offload import KVOffloadTier
from llmlb_tpu.engine.kv_transfer import (
    KV_WIRE_VERSION, KVPages, KVWireHeader, kv_compat_reason,
    serialize_kv_pages,
)
from llmlb_tpu.engine.metrics import EngineMetrics
from llmlb_tpu.engine.paging import PagePool
from llmlb_tpu.engine.prefix_cache import PrefixCache, PrefixEntry
from llmlb_tpu.engine.flightrec import FlightRecorder, gateway_rid
from llmlb_tpu.engine.stepstats import StepRecorder
from llmlb_tpu.models import family_for
from llmlb_tpu.models.llama import LlamaConfig, Params
from llmlb_tpu.ops.grammar import (
    GrammarTables,
    grammar_advance,
    grammar_bias,
)
from llmlb_tpu.ops.sampling import sample_tokens
from llmlb_tpu.parallel.mesh import MeshConfig, build_mesh, default_tp
from llmlb_tpu.quant import kv_cell_bytes, parse_quant_mode, quantize_params
from llmlb_tpu.spec import PromptLookupDrafter, SpecConfig
from llmlb_tpu.structured.constraint import ConstraintState, TokenConstraint

log = logging.getLogger("llmlb_tpu.engine")

# Process-wide cache for the jit-wrapped fused/burst step builders
# (_build_decode_many and friends). family.decode_step etc. are module-level
# jits every engine shares, but the scan/verify wrappers are built per
# EngineCore — without this cache each engine instance would recompile them
# from scratch, which with fused decode on by default turns a test suite's
# many short-lived CPU engines into a compile storm. Keyed by object
# identity of the closed-over config/family (values keep strong refs so an
# id() can never be recycled into an alias); grow-only for the process
# lifetime, exactly like jit's own executable cache.
_PROGRAM_CACHE: dict[tuple, tuple] = {}
_PROGRAM_CACHE_LOCK = threading.Lock()

# Priority classes (docs/scheduling.md): lower value = more important.
# Dialect-facing names map high/normal/low onto 0/1/2 at the HTTP layer.
PRIORITY_HIGH, PRIORITY_NORMAL, PRIORITY_LOW = 0, 1, 2
PRIORITY_CLASSES = (PRIORITY_HIGH, PRIORITY_NORMAL, PRIORITY_LOW)
PRIORITY_NAMES = {PRIORITY_HIGH: "high", PRIORITY_NORMAL: "normal",
                  PRIORITY_LOW: "low"}


def kv_cache_bytes(cfg, num_slots: int, slot_capacity: int) -> int:
    """HBM footprint of the DENSE contiguous slot cache [L, slots, cap, K, D]
    ×2 (K and V). The serving memory budget is
        weights ≈ 2·n_params bytes (bf16)
        kv      = L · slots · cap · K · D · 2(kv) · itemsize
    e.g. llama-3-8b (L=32, K=8, D=128) at 8×4096: 4.3 GiB — fits v5e-4 tp
    alongside the 16 GiB of weights; tinyllama-1.1b (L=22, K=4, D=64) at
    16×8192: 2.95 GiB on a single chip. The default capacity is sized so a
    4k-token prompt serves out of the box (VERDICT r2 item 5). In paged mode
    (the default) the footprint is kv_pool_bytes instead — every slot shares
    one page pool, so short requests no longer strand `cap` rows each."""
    itemsize = jnp.dtype(cfg.dtype).itemsize
    return (cfg.num_layers * num_slots * slot_capacity
            * cfg.num_kv_heads * cfg.head_dim_ * 2 * itemsize)


def kv_page_bytes(cfg, page_size: int, quantized: bool = False) -> int:
    """HBM bytes ONE page holds across all layers, K and V included. The
    bf16 cell is D·2 bytes per (token, head); the int8 cell is D·1 plus one
    f32 scale (llmlb_tpu/quant.kv_cell_bytes) — the per-page figure the
    kv gauges report so capacity math stays honest under quantization."""
    itemsize = jnp.dtype(cfg.dtype).itemsize
    cell = kv_cell_bytes(cfg.head_dim_, quantized, itemsize)
    return int(cfg.num_layers * page_size * cfg.num_kv_heads * 2 * cell)


def kv_pool_bytes(cfg, num_pages: int, page_size: int,
                  quantized: bool = False) -> int:
    """HBM footprint of the PAGED KV pool [L, pages, page_size, K, D] ×2
    (K and V; int8 pools add their f32 scale arrays). At the default sizing
    (num_pages = slots · cap/page_size + 1) this matches the dense footprint
    within one trash page — the occupancy win comes from admitting MORE
    slots against the same pool, not from a smaller pool. Quantized pools
    hold ~(D+4)/2D of the bf16 bytes per page, so the same HBM budget holds
    nearly twice the pages."""
    return num_pages * kv_page_bytes(cfg, page_size, quantized)


@partial(jax.jit, donate_argnames=("cache_k", "cache_v"))
def _scatter_kv_row(cache_k, cache_v, k_all, v_all, slot_id):
    """Land a context-parallel prefill's KV [L, 1, T, K, D] in row `slot_id`
    of the slot cache [L, SLOTS, CAP, K, D] (one in-place dynamic slice; the
    caches are donated so no copy of the full cache is made)."""
    zero = jnp.int32(0)
    start = (zero, slot_id, zero, zero, zero)
    return (
        jax.lax.dynamic_update_slice(cache_k, k_all.astype(cache_k.dtype), start),
        jax.lax.dynamic_update_slice(cache_v, v_all.astype(cache_v.dtype), start),
    )


@partial(jax.jit, donate_argnames=("cache_k", "cache_v"))
def _scatter_kv_row_paged(cache_k, cache_v, k_all, v_all, table_row):
    """Paged counterpart of _scatter_kv_row: land a context-parallel
    prefill's KV [L, 1, T, K, D] in the pool pages named by `table_row`
    [PPN] (positions past the allocated pages hit the trash page — padding
    garbage, same contract as the dense scatter's cells past the valid
    length). Quantized pools ({"q","s"} pairs) quantize per vector on the
    way in, scales landing at the same cells."""
    from llmlb_tpu.models.llama import kv_pool_values
    from llmlb_tpu.quant import quantize_kv

    t = k_all.shape[2]
    ps = kv_pool_values(cache_k).shape[2]
    pos = jnp.arange(t, dtype=jnp.int32)
    page = table_row[jnp.minimum(pos // ps, table_row.shape[0] - 1)]
    off = pos % ps

    def scatter(pool, kv_all):
        kv = kv_all[:, 0]  # [L, T, K, D]
        if isinstance(pool, dict):
            q, s = quantize_kv(kv)
            return {"q": pool["q"].at[:, page, off].set(q),
                    "s": pool["s"].at[:, page, off].set(s)}
        return pool.at[:, page, off].set(kv.astype(pool.dtype))

    return scatter(cache_k, k_all), scatter(cache_v, v_all)


@partial(jax.jit, donate_argnames=("cache_k", "cache_v"))
def _write_kv_pages(cache_k, cache_v, k_new, v_new, page_idx):
    """Land shipped/offloaded KV pages [L, P', PS, K, D] into pool pages
    `page_idx` [P'] — the H2D half of the page-transfer path (kv_transfer).
    Quantized pools take pre-quantized {"q","s"} pairs verbatim: the bytes
    on the wire are bit-exact donor pool cells, so no re-quantization (and
    no numerics drift) happens on the way in. Callers pad `page_idx` (and
    the sections) to the next power of two by repeating the last page —
    duplicate scatter of identical data — so the jit cache stays at
    log2(pool) variants."""

    def scatter(pool, new):
        if isinstance(pool, dict):
            return {"q": pool["q"].at[:, page_idx].set(new["q"]),
                    "s": pool["s"].at[:, page_idx].set(new["s"])}
        return pool.at[:, page_idx].set(new.astype(pool.dtype))

    return scatter(cache_k, k_new), scatter(cache_v, v_new)


@partial(jax.jit, donate_argnames=("cache_k", "cache_v"),
         static_argnames=("rows",))
def _copy_kv_prefix(cache_k, cache_v, src_slot, dst_slot, rows):
    """Prefix-cache hit: copy the first `rows` KV rows of pinned donor row
    `src_slot` into target row `dst_slot` — one device-side
    dynamic_update_slice per cache, no recompute, no host round trip.
    `rows` is static (the caller pads the matched length to the next power
    of two, bounding the jit cache at log2(capacity) variants); rows copied
    beyond the matched prefix are overwritten by the suffix prefill or sit
    past the valid length where every attention masks them."""
    zero = jnp.int32(0)
    layers, _, _, kv_heads, head_dim = cache_k.shape
    size = (layers, 1, rows, kv_heads, head_dim)
    src = (zero, src_slot, zero, zero, zero)
    dst = (zero, dst_slot, zero, zero, zero)
    blk_k = jax.lax.dynamic_slice(cache_k, src, size)
    blk_v = jax.lax.dynamic_slice(cache_v, src, size)
    return (
        jax.lax.dynamic_update_slice(cache_k, blk_k, dst),
        jax.lax.dynamic_update_slice(cache_v, blk_v, dst),
    )


def _sample_chunk(logits, key, temps, top_ps, top_ks, seeds, mask, start_pos):
    """Per-position sampling for a verify chunk: [B, T, V] logits sampled as
    B*T independent rows with each slot's params repeated per position and
    the seed fold stepped by GLOBAL position (start + offset) — so a seeded
    row draws the exact same key at sequence position p whether p was
    reached by plain decode or inside a verify chunk (spec on/off produce
    bit-identical seeded streams). `mask` is an optional [B*T, V] additive
    grammar bias (per-position FSM lookahead rows)."""
    b, t, v = logits.shape
    flat = logits.reshape(b * t, v)

    def rep(x):
        return jnp.repeat(x, t)

    steps = (start_pos[:, None]
             + jnp.arange(t, dtype=jnp.int32)[None, :]).reshape(-1)
    toks = sample_tokens(flat, key, rep(temps), rep(top_ps), rep(top_ks),
                         mask, rep(seeds), steps)
    return toks.reshape(b, t)


@dataclasses.dataclass
class SamplingParams:
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    max_tokens: int = 128
    # Per-request deterministic sampling: rows with a seed draw from
    # fold_in(PRNGKey(seed), position) instead of the shared batch key, so
    # the token sequence reproduces regardless of batch composition.
    seed: int | None = None
    # Grammar constraint spec (llmlb_tpu/structured.spec_regex forms) —
    # JSON-safe, so it rides the multihost plan wire as-is. The compiled
    # token-DFA travels separately on Request.compiled_constraint.
    constraint: dict | None = None
    # Speculative decoding knobs (llmlb_tpu/spec): {"enabled": bool,
    # "max_draft_tokens": int} — absent keys fall back to the engine
    # defaults, max_draft_tokens clamps into the engine's verify width.
    # JSON-safe, rides the plan wire like `constraint`.
    speculative: dict | None = None
    # Priority class (docs/scheduling.md): 0=high, 1=normal, 2=low. The
    # scheduler admits strictly by class (FIFO within a class) and may
    # PREEMPT a lower-class decoding slot under slot/page pressure — the
    # parked request resumes later, token-identical (greedy/seeded).
    # Plain int so it rides the multihost plan wire as-is.
    priority: int = 1
    # Relative deadline in milliseconds from submission (None = none). A
    # request still queued past its deadline is shed before it burns a
    # prefill; the gateway propagates client deadlines via the
    # X-Request-Deadline-Ms header.
    deadline_ms: float | None = None
    # LoRA adapter name (docs/lora.md): selected by the `lora` field or the
    # `model:adapter` suffix on both dialects. A plain string so it rides
    # the multihost plan wire, the /v1/handoff disagg wire, and /v1/resume
    # replay for free (test_plan_wire/test_handoff_wire auto-probe it).
    # Resolution to a pool row happens at submit (EngineCore.prepare_lora);
    # park/resume re-prefills with the same adapter so resumed streams stay
    # token-identical.
    lora: str | None = None


@dataclasses.dataclass
class ParkedState:
    """Everything a preempted request needs to resume token-identical: the
    tokens it already committed (prompt KV is rebuilt by a chunk-prefill of
    prompt + these), its generation progress, and the host-side cursors that
    must NOT re-walk from scratch — the grammar FSM cursor (a fresh
    ConstraintState would mask as if at string start) and the prompt-lookup
    drafter index (cheap to rebuild, but reusing it preserves behavior
    exactly). Sampling determinism needs no state here: seeded rows fold
    PRNGKey(seed) by absolute position, so the resumed chunk-prefill's
    activation sample IS the next uninterrupted sample."""

    generated: int
    tokens: list[int]
    constraint: ConstraintState | None = None
    drafter: PromptLookupDrafter | None = None
    spec_k: int = 0


@dataclasses.dataclass
class Request:
    prompt_ids: list[int]
    sampling: SamplingParams
    request_id: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex)
    # events: ("token", token_id) ... ("done", finish_reason) | ("error", msg)
    events: queue.SimpleQueue = dataclasses.field(default_factory=queue.SimpleQueue)
    submitted_at: float = dataclasses.field(default_factory=time.monotonic)
    first_token_at: float | None = None
    finished_at: float | None = None
    # Set by the consumer (stop hit / client gone); the step loop frees the slot
    # at its next emit for this request. Plain bool write — atomic under the GIL.
    cancelled: bool = False
    # Compiled token-DFA for sampling.constraint (llmlb_tpu/structured).
    # The service pre-compiles it off the step loop; multihost followers and
    # direct core submitters get it compiled at insert via the core's
    # constraint_compiler. Never serialized — followers rebuild from the spec.
    compiled_constraint: TokenConstraint | None = None
    # Preemption (docs/scheduling.md): set by _park_slot when this request is
    # parked under slot/page pressure, consumed at re-activation. While set,
    # insert paths prefill prompt_ids + parked.tokens and restore the
    # generation cursor instead of starting over. Host-local — never crosses
    # the plan wire (every host parks/resumes its own mirror identically).
    parked: ParkedState | None = None
    # KV page shipping (engine/kv_transfer.py, docs/kv-cache.md). export_kv
    # asks _emit's finish path to serialize this request's KV pages D2H
    # before they are freed (set by the handoff-prefill path); the payload
    # lands in kv_export for the caller. kv_restore carries a parsed
    # inbound payload (wire or offload tier) that _insert_restored lands
    # H2D, activating the slot with zero prefill dispatches; cleared on
    # first use whether or not the restore succeeds (one-shot — a failed
    # restore falls back to chunk-prefill replay). All three are host-local
    # and never cross the plan wire.
    export_kv: bool = False
    kv_export: dict | None = None
    kv_restore: "KVPages | None" = None

    def cancel(self) -> None:
        self.cancelled = True

    def deadline_expired(self, now: float | None = None) -> bool:
        dl = self.sampling.deadline_ms
        if dl is None:
            return False
        return ((now if now is not None else time.monotonic())
                > self.submitted_at + float(dl) / 1000.0)


@dataclasses.dataclass
class _Slot:
    request: Request | None = None
    generated: int = 0
    eos_id: int = -1
    # Chunked-prefill progress: tokens of the prompt already in the KV cache.
    # While prefilling is True the slot is excluded from decode emission and
    # its device seq_len is parked at capacity-1 so the batched decode step's
    # garbage writes land in the (unused) last cell, never inside the region
    # the chunks are filling.
    prefilling: bool = False
    prefill_pos: int = 0
    # Prefix-cache entry this slot is reading (hit path): acquired for the
    # suffix prefill so the donor cannot be evicted mid-copy-window; released
    # on activation, cancellation, or engine failure.
    cache_entry: PrefixEntry | None = None
    last_emit_at: float = 0.0  # inter-token latency tracking
    # The first token is sampled on-device at activation and emitted with the
    # NEXT decode fetch instead of its own host readback — per-insert syncs
    # cost a full host↔device round trip each (93 ms over the axon tunnel)
    # and serialized TTFT under bursty load.
    first_pending: bool = False
    # Grammar-constraint cursor (llmlb_tpu/structured.ConstraintState),
    # advanced host-side on every emitted token; its bias row is this slot's
    # stripe of the [B, V] decode mask.
    constraint: ConstraintState | None = None
    # Fused decode (ops/grammar.py): absolute row offset of this slot's
    # schema in the device grammar table, -1 when the schema is not
    # device-resident (fused off, or table budget exceeded — the slot then
    # takes the legacy host-mask path). The device cursor for a step is
    # gram_offset + constraint.state; the host FSM stays source of truth.
    gram_offset: int = -1
    # Speculative decoding (llmlb_tpu/spec): the per-request prompt-lookup
    # index, fed every emitted token; None when this request does not
    # speculate. spec_k is the request's draft budget per verify step.
    drafter: PromptLookupDrafter | None = None
    spec_k: int = 0
    # Every token emitted so far, in order (EOS excluded — a finished
    # request is never parked). Preemption needs the committed sequence to
    # rebuild KV via chunk-prefill; bounded by max_tokens per slot.
    out_tokens: list[int] = dataclasses.field(default_factory=list)
    # Split-mode handoff (llmlb_tpu/disagg/split.py): a prefill-pool slot
    # whose prompt KV is fully landed and is waiting for a decode slot to
    # adopt it. `handoff_logits` holds the final prefill dispatch's logits
    # row ([1, V] device array) so the first token samples at adoption.
    handoff_ready: bool = False
    handoff_logits: object | None = None
    handoff_ready_at: float = 0.0


@dataclasses.dataclass(frozen=True)
class EngineStats:
    num_slots: int
    active_slots: int
    queued: int
    total_requests: int
    total_tokens: int
    uptime_s: float


class EngineCore:
    """The compute side of the engine: owns params, cache, and the step loop."""

    def __init__(
        self,
        cfg: LlamaConfig,
        params: Params | None = None,
        *,
        num_slots: int = 8,
        slot_capacity: int = 512,
        prefill_buckets: tuple[int, ...] = (32, 64, 128, 256, 512),
        mesh_config: MeshConfig | None = None,
        eos_id: int = -1,
        seed: int = 0,
        decode_burst: int | None = None,
        fused_decode: bool | None = None,
        prefix_cache: bool | None = None,
        prefix_cache_slots: int | None = None,
        min_prefix_len: int | None = None,
        kv_layout: str | None = None,
        kv_page_size: int | None = None,
        kv_pages: int | None = None,
        kv_ship: bool | None = None,
        kv_offload_bytes: int | None = None,
        spec_decode: bool | None = None,
        spec_max_draft: int | None = None,
        spec_ngram: int | None = None,
        quantize: str | None = None,
        prefill_chunk_budget: int | None = None,
        role: str | None = None,
        disagg_prefill_slots: int | None = None,
        lora_dir: str | None = None,
        lora_max_adapters: int | None = None,
        lora_rank_cap: int | None = None,
    ):
        self.cfg = cfg
        # Serving role (docs/disaggregation.md): "both" (default) is the
        # classic combined loop; "split" runs a prefill pool and a decode
        # pool as two step loops over one shared PagePool (in-process
        # disaggregation — built at the end of __init__ once slots exist);
        # "prefill"/"decode" keep the combined loop and only change what the
        # server layer advertises and accepts (cross-process roles).
        from llmlb_tpu.disagg import normalize_role

        if role is None:
            role = os.environ.get("LLMLB_ROLE")
        self.role = normalize_role(role)
        self._disagg_prefill_slots_arg = disagg_prefill_slots
        self.split = None  # SplitRuntime in split mode
        # Family module (llama / mixtral) supplying the serving fns — one
        # shared contract, so dense and MoE models run the same loop.
        self.family = family_for(cfg)
        self.num_slots = num_slots
        self.slot_capacity = min(slot_capacity, cfg.max_position_embeddings)
        self.prefill_buckets = tuple(
            b for b in sorted(prefill_buckets) if b <= self.slot_capacity
        )
        self.eos_id = eos_id

        # KV layout: "paged" (default) backs every slot with a shared page
        # pool + per-slot block table, so HBM is held per token actually
        # cached instead of slot_capacity rows per request; "dense" keeps the
        # contiguous [L, slots, cap, K, D] block and the original code paths
        # bit for bit (every paged branch below gates on `self.page_pool`).
        if kv_layout is None:
            kv_layout = os.environ.get("LLMLB_KV_LAYOUT", "paged")
        if kv_layout not in ("paged", "dense"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'dense', got {kv_layout!r}"
            )
        if kv_layout == "paged" and not hasattr(self.family,
                                                "prefill_into_pages"):
            log.warning(
                "model family %s has no paged serving path; falling back to "
                "the dense slot cache", self.family.__name__,
            )
            kv_layout = "dense"
        self.kv_layout = kv_layout

        # Int8 quantization (llmlb_tpu/quant, docs/quantization.md): two
        # independent knobs — per-output-channel int8 projection weights
        # and int8 KV pages — resolved from `--quantize`/LLMLB_QUANTIZE.
        # OFF by default; with both knobs off every path below is the
        # pre-quantization engine bit for bit (tier-1 guarded).
        self.quant = parse_quant_mode(quantize)
        if self.quant.kv and self.kv_layout != "paged":
            log.warning(
                "int8 KV quantization requires the paged layout; the dense "
                "slot cache stays bf16 (weights quantization, if requested, "
                "still applies)"
            )
            self.quant = dataclasses.replace(self.quant, kv=False)

        # Page size: TPU-friendly default of 128 tokens (one flash block),
        # clamped into the slot capacity. docs/kv-cache.md discusses the
        # waste-vs-overhead tradeoff of other sizes.
        self.kv_page_size = max(1, min(kv_page_size or 128,
                                       self.slot_capacity))
        self.pages_per_slot = -(-self.slot_capacity // self.kv_page_size)
        # Pool size resolves after the mesh exists (the per-device default
        # depends on the dp degree); 0 until the paged cache-init block runs.
        self._kv_pages_arg = kv_pages
        self.kv_num_pages = 0
        # Dense-mode prefix hits dispatch a device-side row copy; paged hits
        # must never (zero-copy page sharing). Exposed so tests/benches can
        # assert the paged hit path stays copy-free.
        self.kv_copy_dispatches = 0

        # Prefix KV cache: completed requests may donate their slot to a
        # radix tree keyed on prompt token ids; later requests sharing a
        # prefix copy the cached rows device-side and prefill only the
        # suffix. Disabled (None) the scheduler behaves exactly as before —
        # every new branch below is gated on `self.prefix_cache is not None`.
        if prefix_cache is None:
            prefix_cache = os.environ.get(
                "LLMLB_PREFIX_CACHE", "1"
            ).lower() not in ("0", "false", "off", "no")
        # Matched lengths are aligned DOWN to the smallest prefill bucket so
        # the uncached suffix always starts on a bucket boundary (chunked
        # prefill then runs at its existing compiled sizes). Paged mode
        # additionally aligns to whole pages: only FULL pages can be shared
        # zero-copy (a partially-shared page would mix two requests' rows),
        # so the quantum is lcm(bucket, page_size).
        self.prefix_align = self.prefill_buckets[0] if self.prefill_buckets else 0
        if self.kv_layout == "paged" and self.prefix_align:
            self.prefix_align = math.lcm(self.prefix_align, self.kv_page_size)
        self.min_prefix_len = (
            max(1, int(min_prefix_len)) if min_prefix_len is not None
            else self.prefix_align
        )
        if prefix_cache_slots is None:
            prefix_cache_slots = max(1, num_slots // 2)
        # pinned donors must always leave at least one slot serving traffic
        budget = max(0, min(int(prefix_cache_slots), num_slots - 1))
        self.prefix_cache: PrefixCache | None = (
            PrefixCache(max_entries=budget, min_len=self.min_prefix_len,
                        align=self.prefix_align)
            if prefix_cache and budget > 0 and self.prefix_align > 0
            else None
        )

        devices = jax.devices()
        if mesh_config is None:
            # Size the latency-critical axes (ep, tp) within ONE slice/host —
            # in a multi-process cluster their per-layer collectives must
            # ride ICI, never DCN; dp (independent requests) spans hosts.
            n_local = (jax.local_device_count()
                       if jax.process_count() > 1 else len(devices))
            ep = 1
            if getattr(cfg, "num_experts", 0) > 1:
                # MoE default: give experts as much of the mesh as divides both
                # the device count and the expert count, tp/dp with the rest.
                ep = math.gcd(n_local, cfg.num_experts)
            tp = default_tp(n_local // ep, cfg.num_heads, cfg.num_kv_heads)
            mesh_config = MeshConfig(
                dp=n_local // (ep * tp), ep=ep, tp=tp
            )
        if jax.process_count() > 1:
            from llmlb_tpu.parallel.distributed import build_hybrid_mesh

            # dp multiplies across slices over DCN; sp/ep/tp stay inside
            self.mesh = build_hybrid_mesh(
                mesh_config, dcn_dp=jax.process_count(), devices=devices
            )
        else:
            self.mesh = build_mesh(mesh_config, devices=devices)

        if params is None:
            params = self.family.init_params(cfg, jax.random.PRNGKey(seed))
        if self.quant.weights:
            # Idempotent: checkpoints quantized at load time (streaming,
            # engine/weights.py) pass through; random-init / caller-supplied
            # bf16 pytrees quantize here so every construction path serves
            # the same int8 layout.
            params = quantize_params(params)

        # Multi-LoRA serving (llmlb_tpu/lora, docs/lora.md): a device-resident
        # adapter pool rides the param pytree as `<name>_lora_a/_lora_b`
        # companions (zeros at boot; hot-loaded rows overwrite in place), and
        # every dispatch carries per-row adapter indices. OFF by default —
        # with no pool in the pytree every forward compiles the original
        # program bit for bit (the quantize-off contract, tier-1 pinned).
        # Adapter deltas stay bf16 on top of (possibly int8) base weights:
        # the delta adds to the projection OUTPUT, so the dequant-on-read
        # path above is untouched.
        if lora_dir is None:
            lora_dir = os.environ.get("LLMLB_LORA_DIR") or None
        self.lora = None
        # one-time CP→chunked prefill fallback warning (satellite of the
        # fused-decode PR; the counter keeps counting after the first)
        self._lora_cp_warned = False
        if lora_dir:
            from llmlb_tpu.lora import LoraManager

            if jax.process_count() > 1:
                raise ValueError(
                    "--lora-dir is single-host only for now: followers have "
                    "no deterministic mirror of the leader's adapter pool "
                    "slot assignment"
                )
            if lora_max_adapters is None:
                lora_max_adapters = int(os.environ.get(
                    "LLMLB_LORA_MAX_ADAPTERS", "8"))
            if lora_rank_cap is None:
                lora_rank_cap = int(os.environ.get(
                    "LLMLB_LORA_RANK_CAP", "16"))
            # MoE families serve attention-target adapters only (no pools
            # over the routed expert FFNs).
            targets = (("wq", "wk", "wv", "wo")
                       if getattr(cfg, "num_experts", 0) > 1
                       else ("wq", "wk", "wv", "wo", "wg", "wu", "wd"))
            self.lora = LoraManager(
                cfg, lora_dir=lora_dir, max_adapters=lora_max_adapters,
                rank_cap=lora_rank_cap, targets=targets,
            )
            pool_leaves = self.lora.init_pool_leaves(np.dtype(cfg.dtype))
            params = {**params, **pool_leaves}
            log.info(
                "lora: pool of %d adapter slots at rank cap %d over %s "
                "(%d adapter(s) discovered in %s)",
                self.lora.max_adapters, self.lora.rank_cap,
                "/".join(targets), len(self.lora.available), lora_dir,
            )
        shardings = self.family.param_shardings(cfg, self.mesh)
        self.params = {
            k: jax.device_put(v, shardings[k]) for k, v in params.items()
        }
        if self.lora is not None:
            self.lora.attach(self)
        if self.quant.weights:
            log.info(
                "weights: int8 per-output-channel (%d quantized leaves), "
                "%.2f GiB on device",
                sum(1 for k in self.params if k.endswith("_scale")),
                sum(v.size * v.dtype.itemsize
                    for v in self.params.values()) / 2**30,
            )

        # Paged-mode host state: the page allocator, per-slot page lists, and
        # the block tables (host numpy mirror + device array refreshed before
        # the next dispatch whenever a table row changes).
        self.page_pool: PagePool | None = None
        self._slot_pages: list[list[int]] = [[] for _ in range(num_slots)]
        self._block_tables = np.zeros((num_slots, self.pages_per_slot),
                                      np.int32)
        self._d_block_tables = jnp.asarray(self._block_tables)
        self._tables_dirty = False
        # A request popped from pending that the pool cannot yet cover waits
        # here (retried first), preserving arrival order without re-queueing.
        self._held_request: Request | None = None
        # Page REFERENCES held by prefix-cache entries (int: GIL-atomic so
        # scrape threads can read it while the step loop mutates the cache).
        # Entries sharing head pages each count their reference; the pool's
        # used() figure is the distinct-page truth.
        self._prefix_pinned_pages = 0

        if self.kv_layout == "paged":
            # Default pool: the dense PER-DEVICE footprint plus the reserved
            # trash page. The dense cache shards its slot axis over dp while
            # the page pool replicates (pages are shared by every slot, so
            # they must be co-resident) — sizing from the full slot count on
            # a dp>1 mesh would multiply per-device KV HBM by dp and OOM a
            # deployment that fit the dense layout.
            dp = self.mesh.shape.get("dp", 1)
            default_pages = (
                -(-num_slots // dp) * self.pages_per_slot + 1
            )
            self.kv_num_pages = max(int(self._kv_pages_arg or default_pages),
                                    self.pages_per_slot + 1)
            if dp > 1:
                log.info(
                    "paged KV pool replicates over dp=%d; defaulting to the "
                    "per-device dense budget (%d pages) — raise --kv-pages "
                    "to trade HBM for aggregate capacity", dp,
                    self.kv_num_pages,
                )
            self.page_pool = PagePool(self.kv_num_pages)
            ck, cv = self.family.init_kv_pages(cfg, self.kv_num_pages,
                                               self.kv_page_size,
                                               quantized=self.quant.kv)
            ck_sh, cv_sh = self.family.kv_pages_shardings(
                cfg, self.mesh, quantized=self.quant.kv
            )
            self.cache_k = jax.device_put(ck, ck_sh)
            self.cache_v = jax.device_put(cv, cv_sh)
            log.info(
                "KV cache: paged%s, %d pages x %d tokens (%d slots, %d "
                "pages/slot) = %.2f GiB in HBM",
                " int8" if self.quant.kv else "",
                self.kv_num_pages, self.kv_page_size, num_slots,
                self.pages_per_slot,
                kv_pool_bytes(cfg, self.kv_num_pages, self.kv_page_size,
                              quantized=self.quant.kv) / 2**30,
            )
        else:
            ck, cv = self.family.init_kv_cache(cfg, num_slots,
                                               self.slot_capacity)
            ck_sh, cv_sh = self.family.kv_cache_shardings(cfg, self.mesh)
            self.cache_k = jax.device_put(ck, ck_sh)
            self.cache_v = jax.device_put(cv, cv_sh)
            log.info(
                "KV cache: dense, %d slots x %d capacity = %.2f GiB in HBM",
                num_slots, self.slot_capacity,
                kv_cache_bytes(cfg, num_slots, self.slot_capacity) / 2**30,
            )

        # Context-parallel prefill (ring attention over the mesh sp axis):
        # built lazily per padded length; fills a long prompt's KV in ONE
        # distributed pass instead of many sequential chunks.
        self._cp_prefill_fn = None
        self._use_cp_prefill = self.mesh.shape.get("sp", 1) > 1
        self._prefill_rr = 0  # fair rotation among concurrently-prefilling slots

        # Multi-host lockstep (engine/multihost.py): with the model sharded
        # across processes every step is a collective, so the leader
        # broadcasts each tick's plan and all hosts run identical scheduler
        # logic on mirrored state. Device scalars/tokens are replicated
        # before host fetches (a cross-host shard is not addressable).
        self.coordinator = None
        self._replicate = None
        self._stop_requested = False
        # Graceful drain (docs/deployment.md): while draining the step loop
        # admits nothing new — in-flight decodes run to completion under the
        # server's grace window; `request_drain_park` then asks the NEXT
        # loop iteration (slot state is loop-thread-owned) to park every
        # decoding slot through the PR 10 park path so the gateway's
        # mid-stream resume can move those streams to another engine.
        self.draining = False
        self._drain_park_requested = False
        self._drain_flush_requested = False
        # Park-on-demand (gateway rebalancer, docs/resilience.md): gateway
        # request ids whose slots should park + export at the next loop
        # iteration — the migration analogue of request_drain_park, scoped
        # to single streams instead of the whole engine.
        self._park_rids: set[str] = set()
        # Cancellations take effect ONLY via the plan in multihost mode: the
        # live .cancelled flag flips at arbitrary times on the leader (HTTP
        # thread), and acting on it directly would make hosts dispatch
        # different collectives and deadlock the cluster. Single-host reads
        # the live flag; the discard on the emit paths still touches the set.
        self._cancelled_effective: set[str] = set()
        if jax.process_count() > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            from llmlb_tpu.engine.multihost import StepCoordinator

            self.coordinator = StepCoordinator()
            self._replicate = jax.jit(
                lambda x: x,
                out_shardings=NamedSharding(self.mesh, PartitionSpec()),
            )
            # leader-only intake; mirrored into self.pending via the plan
            self._intake: queue.SimpleQueue[Request] = queue.SimpleQueue()
            self._plan_backlog: list[Request] = []  # budget-spilled, FIFO
            log.info(
                "multihost lockstep: %s of %d hosts",
                "leader" if self.coordinator.is_leader else "follower",
                self.coordinator.num_hosts,
            )

        # Host-side slot bookkeeping (lengths mirror device state for stop
        # checks without D2H); sampling params + tokens live ON DEVICE and are
        # only touched at insert time — the decode hot loop does zero H2D.
        self.slots = [_Slot() for _ in range(num_slots)]
        self._seq_lens = np.zeros((num_slots,), np.int32)
        self._d_seq_lens = jnp.zeros((num_slots,), jnp.int32)
        self._d_temps = jnp.ones((num_slots,), jnp.float32)
        self._d_top_ps = jnp.ones((num_slots,), jnp.float32)
        self._d_top_ks = jnp.zeros((num_slots,), jnp.int32)
        self._d_last_tokens = jnp.zeros((num_slots,), jnp.int32)
        # Per-slot sampling seeds (-1 = shared batch key); always passed to
        # sample_tokens — unseeded rows are bit-identical to the pre-seed
        # path, so goldens hold.
        self._d_seeds = jnp.full((num_slots,), -1, jnp.int32)
        # Per-slot LoRA adapter pool rows (0 = identity/no adapter),
        # scattered at activation like the sampling params so the decode
        # hot loop does zero per-step H2D. Only consulted when self.lora
        # is set — LoRA-free engines pass lora_idx=None to every dispatch
        # (the original compiled programs, bit for bit).
        self._d_lora_idx = jnp.zeros((num_slots,), jnp.int32)
        self._key = jax.random.PRNGKey(seed)

        # Grammar-constraint mask: one float32 [slots, V] additive bias
        # (0 allowed / -1e30 blocked), host-mutated as slot FSMs advance and
        # re-shipped before the next masked dispatch. Lazily allocated — an
        # engine that never sees a constrained request never pays the HBM or
        # the H2D, and sample_tokens gets mask_bias=None (the original
        # compiled path, bit for bit). Compiler is installed by the service
        # layer (it owns the tokenizer); direct-core users may leave it None
        # and pre-compile Request.compiled_constraint themselves.
        self.constraint_compiler = None
        self._mask_bias: np.ndarray | None = None
        self._d_mask: jnp.ndarray | None = None
        # Rows changed since the last device sync: one FSM advance dirties
        # ONE row, and shipping only those keeps the per-token H2D at
        # rows×V·4B instead of slots×V·4B (32 MiB/token at 64×128k).
        self._mask_dirty_rows: set[int] = set()
        self._constrained_count = 0

        # Speculative decoding (llmlb_tpu/spec): prompt-lookup drafting +
        # batched K+1-token verification. `spec_decode` sets the DEFAULT for
        # requests that do not carry their own `speculative` knob (a request
        # may opt in on an engine defaulting off, and vice versa); the
        # engine-level max_draft_tokens bounds the verify chunk width, so
        # there is exactly one verify compile per window bucket. OFF by
        # default: with no drafter attached anywhere the decode path is
        # bit-identical to the pre-speculation engine.
        if spec_decode is None:
            spec_decode = os.environ.get(
                "LLMLB_SPEC_DECODE", "0"
            ).lower() in ("1", "true", "on", "yes")
        if spec_max_draft is None:
            spec_max_draft = int(os.environ.get("LLMLB_SPEC_MAX_DRAFT", "4"))
        if spec_ngram is None:
            spec_ngram = int(os.environ.get("LLMLB_SPEC_NGRAM", "3"))
        self.spec = SpecConfig(
            enabled=bool(spec_decode),
            max_draft_tokens=max(1, min(int(spec_max_draft), 16)),
            max_ngram=max(1, int(spec_ngram)),
            min_ngram=1,
        )
        self._spec_available = hasattr(
            self.family,
            "verify_step_paged" if self.kv_layout == "paged" else "verify_step",
        )
        # jitted verify wrappers per context-window bucket (verify fn +
        # per-position sampling fused into one dispatch, like _decode_many)
        self._verify_fns: dict[int, Callable] = {}
        # Per-position verify mask: a persistent [slots, K+1, V] device
        # buffer (lazily allocated — spec-free and constraint-free engines
        # never pay the HBM), refreshed per step ONLY for rows that are
        # masked now or were last step (the lookahead states change every
        # step, but unconstrained rows stay zero and never ship) — the
        # verify-path analogue of the decode mask's dirty-row H2D contract.
        self._d_spec_mask: jnp.ndarray | None = None
        self._spec_masked_prev: set[int] = set()

        # Decode burst: number of decode+sample steps fused into ONE device
        # dispatch (lax.scan with on-device token feedback) per host readback.
        # The per-step host sync is pure latency — tokens/sec scales ~k× when
        # the host↔device round trip dominates the step (measured 93 ms RTT
        # vs 3 ms compute through the axon tunnel; even on local PCIe the
        # sync is several× the dispatch). Auto: 8 on TPU, 1 elsewhere (CPU
        # tests keep single-step token-for-token goldens). Emission becomes
        # k-token bursts; EOS/max_tokens mid-burst are trimmed host-side.
        if decode_burst is None:
            env = os.environ.get("LLMLB_DECODE_BURST")
            if env:
                try:
                    decode_burst = max(1, int(env))
                except ValueError:
                    log.warning(
                        "LLMLB_DECODE_BURST=%r is not an integer; using the "
                        "auto default", env,
                    )
            if decode_burst is None:
                decode_burst = 8 if jax.default_backend() == "tpu" else 1
        self.decode_burst = max(1, int(decode_burst))
        self._decode_many: dict[int, Callable] = {}  # per context window
        # get-or-build under a lock: the prewarm thread and the step loop
        # must share ONE jit wrapper per window (two wrappers for the same
        # signature would compile twice; one wrapper lets jax's internal
        # compile lock dedup concurrent callers)
        self._decode_many_lock = threading.Lock()

        # Fused decode (docs/fused-decode.md): serve every decode step as
        # ONE device program — the burst scan (even at k=1) with sampling
        # inside, grammar masking via the device-resident transition table
        # (ops/grammar.py), and verify steps with in-program mask columns,
        # last-token splice, and accept counting. Default auto: on for the
        # paged layout, off for dense; LLMLB_FUSED_DECODE=0 keeps every
        # legacy path bit for bit (tier-1 pinned).
        if fused_decode is None:
            env = os.environ.get("LLMLB_FUSED_DECODE", "").strip().lower()
            if env in ("1", "true", "on", "yes"):
                fused_decode = True
            elif env in ("0", "false", "off", "no"):
                fused_decode = False
            elif env:
                log.warning(
                    "LLMLB_FUSED_DECODE=%r is not a boolean; using the "
                    "auto default", env,
                )
        if fused_decode is None:
            fused_decode = self.kv_layout == "paged"
        self.fused_decode = bool(fused_decode)
        # Device grammar tables: one concatenated [rows, V] int32 next-state
        # array shared by every resident schema (row 0 = the free row).
        # Allocated only when fused decode is on — legacy engines keep the
        # host [slots, V] mask mirror below and never pay the table bytes.
        self._grammar_tables: GrammarTables | None = (
            GrammarTables(cfg.vocab_size) if self.fused_decode else None
        )
        self._grammar_warned = False
        # Flips (one-way) when a schema cannot go device-resident: host
        # mask rows are then maintained for every constrained slot so the
        # legacy fallback path masks mixed batches correctly.
        self._grammar_fallback = False
        # Fused program caches: the grammar-masked burst scan per window
        # (separate from _decode_many, whose int keys tier-1 pins), and the
        # fused verify per (window, grammar?) pair.
        self._decode_many_gram: dict[int, Callable] = {}
        self._verify_fused: dict[tuple[int, bool], Callable] = {}

        # Context-window buckets (pow2, up to capacity): every decode reads
        # only the smallest bucket covering all active sequences, so
        # attention HBM traffic scales with the context in use instead of
        # the slot capacity (a 2048-cap cache at 300-token contexts was
        # spending ~85% of its cache bandwidth on empty cells).
        buckets = []
        w = 256
        while w < self.slot_capacity:
            buckets.append(w)
            w *= 2
        buckets.append(self.slot_capacity)
        self._window_buckets = tuple(buckets)

        # queue.Queue (not SimpleQueue): the multihost plan collector
        # snapshots .queue to find cancelled-but-still-queued requests;
        # in that mode the loop thread is both producer and consumer.
        self.pending: queue.Queue[Request] = queue.Queue()
        # Priority admission (docs/scheduling.md): the step loop drains
        # `pending` (the thread-safe intake) into per-class deques and
        # always serves the most important non-empty class, FIFO within a
        # class. Preempted requests re-enter at the FRONT of their class —
        # they already held a slot once. Step-loop-private state, so every
        # multihost host mirrors it deterministically from the plan order.
        self._class_queues: dict[int, collections.deque] = {
            p: collections.deque() for p in PRIORITY_CLASSES
        }
        # Chunked-prefill decode budget: max prompt tokens prefilled per
        # step-loop iteration WHILE other slots are decoding (0 = no cap).
        # Bounds the decoders' ITL regardless of arriving prompt size: a
        # long prompt runs as budget-sized chunks with decode steps between.
        if prefill_chunk_budget is None:
            try:
                prefill_chunk_budget = int(os.environ.get(
                    "LLMLB_PREFILL_CHUNK_BUDGET", "0") or 0)
            except ValueError:
                log.warning("LLMLB_PREFILL_CHUNK_BUDGET is not an integer; "
                            "budget disabled")
                prefill_chunk_budget = 0
        self.prefill_chunk_budget = max(0, int(prefill_chunk_budget))
        if (self.prefill_chunk_budget and self.prefill_buckets
                and self.prefill_chunk_budget < self.prefill_buckets[0]):
            # chunks must be compiled bucket sizes, so a budget below the
            # smallest bucket cannot be honored exactly
            log.warning(
                "prefill chunk budget %d is below the smallest prefill "
                "bucket; effective per-chunk floor is %d tokens",
                self.prefill_chunk_budget, self.prefill_buckets[0],
            )
        # Prompt tokens already dispatched to prefill in the CURRENT step-loop
        # iteration (_try_insert's one-shot batches). _advance_prefill only
        # spends what remains, so an iteration that both inserted a batch and
        # feeds a chunk stays bounded by the budget (+ at most one
        # minimum-bucket rounding) instead of paying each path a full budget.
        self._prefill_spent_iter = 0
        self.metrics = EngineMetrics()
        if self.lora is not None:
            self.lora.metrics = self.metrics
        # Step introspection (engine/stepstats.py): per-step phase records,
        # slow-step anomalies, and the sliding decode window live MFU math
        # reads. Always on — the recorder is a few clock reads per step
        # (< 1% of step time, guarded by test_step_introspection).
        self.step_stats = StepRecorder()
        # Per-request flight recorder (engine/flightrec.py): one event per
        # lifecycle edge, keyed by the gateway's X-Request-Id, served at
        # /api/requests/{id}/timeline and joined cross-process by the
        # gateway's /api/traces/{id}?view=timeline. LLMLB_FLIGHTREC=0
        # disables it (emit() returns before its first clock read).
        self.flightrec = FlightRecorder()
        # KV page shipping (engine/kv_transfer.py, docs/kv-cache.md): move
        # serialized pages instead of chunk-prefill replay on handoff and
        # resume. ON by default but inert until a peer actually offers or
        # requests a payload; requires the paged layout (dense has no page
        # identity to ship) and a single-host combined loop — split mode
        # moves pages in-process by block-table exchange already, and a
        # multihost restore would desync followers whose plan wire carries
        # no page bytes. LLMLB_KV_SHIP=0 restores today's replay-only
        # behavior bit for bit (tier-1 pinned).
        if kv_ship is None:
            kv_ship = os.environ.get(
                "LLMLB_KV_SHIP", "1"
            ).lower() not in ("0", "false", "off", "no")
        self.kv_ship = (bool(kv_ship) and self.page_pool is not None
                        and self.coordinator is None
                        and self.role != "split")
        # Serialized exports captured at drain-park time, keyed by gateway
        # request id, served via POST /v1/kv/export so the gateway can move
        # a mid-stream request's KV to the adopting engine instead of
        # replaying. Bounded by num_slots per drain; entries are consumed on
        # fetch and dropped wholesale on shutdown.
        self._kv_exports: dict[str, dict] = {}
        # Tiered host-RAM offload (engine/kv_offload.py): cold prefix-cache
        # evictions and parked-slot pages spill D2H into a bounded LRU tier
        # and restore H2D on re-hit/resume. Default 0 = off — no spill, no
        # restore, no behavior change (tier-1 pinned).
        if kv_offload_bytes is None:
            try:
                kv_offload_bytes = int(os.environ.get(
                    "LLMLB_KV_OFFLOAD_BYTES", "0") or 0)
            except ValueError:
                log.warning("LLMLB_KV_OFFLOAD_BYTES is not an integer; "
                            "offload disabled")
                kv_offload_bytes = 0
        self.kv_offload: KVOffloadTier | None = (
            KVOffloadTier(kv_offload_bytes)
            if (kv_offload_bytes and kv_offload_bytes > 0
                and self.page_pool is not None and self.coordinator is None
                and self.role != "split")
            else None
        )
        if self.kv_offload is not None:
            log.info("KV offload tier: %.1f MiB host-RAM budget",
                     self.kv_offload.budget_bytes / 2**20)
        # plan/insert time accrued since the last dispatched step; the next
        # step record absorbs it (admission happens between dispatches)
        self._pending_plan_s = 0.0
        # static per-token cost base for perf_info(): parameter count of the
        # served model (device arrays are cheap to .size). Scale leaves are
        # bookkeeping, not parameters — excluded from the FLOP count, as are
        # the LoRA pool leaves (mostly-empty adapter slots; the rank-R delta
        # FLOPs are noise next to the base matmuls); the measured byte
        # footprint (param_bytes) includes both so the HBM accounting stays
        # honest under int8 weights and resident adapters.
        self.n_params = sum(
            int(v.size) for k, v in self.params.items()
            if not (k.endswith("_scale") or "_lora_" in k)
        )
        self.param_bytes = sum(
            int(v.size) * jnp.dtype(v.dtype).itemsize
            for v in self.params.values()
        )
        self._running = False
        self._thread: threading.Thread | None = None
        self._started_at = time.monotonic()
        self.total_requests = 0
        self.total_tokens = 0
        self._lock = threading.Lock()

        # Which step loop this thread belongs to ("main" for the combined
        # loop; split mode tags its two threads "prefill"/"decode" and the
        # adoption path "handoff") — drives the per-loop prefill-dispatch
        # ledger below, the tier-1 proof that in split mode ZERO prefill
        # dispatches ever execute on the decode pool's loop.
        self._tls = threading.local()
        self.prefill_dispatch_by_loop: dict[str, int] = {
            "main": 0, "prefill": 0, "decode": 0, "handoff": 0,
        }
        # Decode-side companion ledger: device dispatches issued by
        # decode/verify steps, per loop. The fused-decode invariant —
        # exactly ONE dispatch per decode-loop step — is asserted over this
        # dict plus the per-step `dispatches` field on stepstats records
        # (scripts/check_fused_dispatch.py).
        self.decode_dispatch_by_loop: dict[str, int] = {
            "main": 0, "prefill": 0, "decode": 0, "handoff": 0,
        }
        if self.role == "split":
            from llmlb_tpu.disagg.split import SplitRuntime

            if self.page_pool is None:
                raise ValueError(
                    "--role split requires the paged KV layout: the handoff "
                    "is a block-table page-id exchange"
                )
            if self.coordinator is not None:
                raise ValueError(
                    "--role split is single-host only (multihost lockstep "
                    "broadcasts one plan per combined step loop)"
                )
            self.split = SplitRuntime(self, self._disagg_prefill_slots_arg)

    # ------------------------------------------------------------------ public

    def _loop_tag(self) -> str:
        return getattr(self._tls, "tag", "main")

    def _note_prefill_dispatch(self) -> None:
        """Ledger every prefill dispatch by the loop that ran it. Split
        mode's acceptance invariant — the decode loop NEVER runs prefill —
        is asserted over this dict in tier-1."""
        self.prefill_dispatch_by_loop[self._loop_tag()] += 1

    def start(self) -> None:
        self._running = True
        if self.split is not None:
            self.split.start()
        else:
            self._thread = threading.Thread(
                target=self._loop, name="engine-step-loop", daemon=True
            )
            self._thread.start()
        if len(self._window_buckets) > 1:
            # Pre-compile every window-bucket variant off-thread: the first
            # sequence to cross a bucket boundary must not stall every
            # in-flight stream behind a multi-second XLA compile.
            threading.Thread(
                target=self._prewarm_windows, name="engine-prewarm",
                daemon=True,
            ).start()

    def _prewarm_windows(self) -> None:
        def sharded(x):
            # Shardings are part of jax's executable cache key: a prewarm
            # lowered without them compiles a different (unsharded) variant
            # and the real dispatch would still stall on a fresh compile.
            # Only the explicitly device_put arrays (params, caches) carry
            # one — the uncommitted scalar vectors must stay unspecified, or
            # their incidental single-device placement conflicts with the
            # mesh sharding at lowering time.
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)

        def plain(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        param_shapes = {k: sharded(v) for k, v in self.params.items()}
        paged = self.page_pool is not None
        # the caches may be quantized {"q","s"} pytrees — map per leaf
        cache_k_shapes = jax.tree.map(sharded, self.cache_k)
        cache_v_shapes = jax.tree.map(sharded, self.cache_v)
        args = [
            param_shapes,
            plain(self._d_last_tokens),
            plain(self._d_seq_lens),
            cache_k_shapes, cache_v_shapes,
        ]
        if paged:
            args.append(plain(self._d_block_tables))
        args += [
            plain(self._d_temps), plain(self._d_top_ps),
            plain(self._d_top_ks), plain(self._d_seeds),
            plain(self._key),  # split keys keep this shape/dtype
        ]
        for w in self._window_buckets:
            if not self._running:
                return
            try:
                if self.decode_burst > 1 or self.fused_decode:
                    # fused engines dispatch the burst scan even at k == 1;
                    # grammar/fused-verify variants compile on first use
                    # (their tables don't exist until a schema registers)
                    self._decode_many_for(w).lower(*args).compile()
                elif paged:
                    self.family.decode_step_paged.lower(
                        param_shapes, self.cfg, plain(self._d_last_tokens),
                        plain(self._d_seq_lens), cache_k_shapes,
                        cache_v_shapes, plain(self._d_block_tables),
                        self.mesh, window=w,
                    ).compile()
                else:
                    # single-step mode compiles decode_step per window too
                    self.family.decode_step.lower(
                        param_shapes, self.cfg, plain(self._d_last_tokens),
                        plain(self._d_seq_lens), cache_k_shapes,
                        cache_v_shapes, self.mesh, window=w,
                    ).compile()
            except Exception:  # pragma: no cover - best-effort warmup
                log.exception("window %d prewarm failed (will compile "
                              "on first use)", w)

    def stop(self) -> None:
        if self.coordinator is not None and self.coordinator.is_leader:
            # broadcast the shutdown through the tick plan so followers
            # leave their loops too (flipping _running here would strand
            # them blocked in the next exchange)
            self._stop_requested = True
        else:
            self._running = False
        if self.split is not None:
            self.split.join(timeout=30)
        if self._thread:
            self._thread.join(timeout=30)
        self._running = False
        # terminal events for everything still in flight so waiters unblock
        self._fail_all("engine shutting down")

    def submit(self, request: Request) -> Request:
        n = len(request.prompt_ids)
        if n == 0:
            self._release_lora(request)  # service may have pre-pinned
            raise ValueError("prompt must contain at least one token")
        if not self.prefill_buckets:
            self._release_lora(request)
            raise ValueError(
                "engine has no prefill buckets (slot capacity smaller than "
                "every configured bucket)"
            )
        # Prompts beyond the largest one-shot bucket run through chunked
        # prefill (prefill_extend_slots); the only hard cap is slot capacity.
        if n + 1 >= self.slot_capacity:
            # a refused submit must not leak a pin the service layer's
            # prepare_lora already took for this request
            self._release_lora(request)
            raise ValueError(
                f"prompt of {n} tokens does not fit the slot capacity "
                f"({self.slot_capacity}) with room to generate"
            )
        # LoRA: pin (and hot-load) the adapter BEFORE the request can reach
        # a slot — the step loop must never block on disk I/O, and eviction
        # must see queued/parked requests as active. Idempotent: the service
        # layer may have prepared off-loop already. Raises ValueError for
        # unknown/invalid adapters (the server maps it to a 400 naming the
        # 'lora' field).
        self.prepare_lora(request)
        with self._lock:
            self.total_requests += 1
        self._fr_emit(request, "admitted", prompt_tokens=n,
                      queue_depth=self.pending.qsize())
        if self.coordinator is not None:
            # multihost: requests enter via the tick plan so every host
            # mirrors the same queue in the same order
            self._intake.put(request)
        else:
            self.pending.put(request)
        return request

    def prepare_lora(self, request: Request) -> None:
        """Resolve + pin a request's adapter (hot-loading it if cold).
        Callable off-loop (service layer) or from submit; idempotent per
        request. Raises ValueError when the request names an adapter this
        engine cannot serve."""
        name = request.sampling.lora
        if not name:
            return
        if self.lora is None:
            raise ValueError(
                "'lora' adapters are not enabled on this engine "
                "(start it with --lora-dir)"
            )
        t0 = time.perf_counter()
        self.lora.acquire(name, request.request_id)
        # fires once per acquire call; the submit-time re-acquire of a
        # service-prepared adapter shows as a second event with ~0 wait
        self._fr_emit(request, "lora_acquire", adapter=name,
                      wait_s=round(time.perf_counter() - t0, 6))

    def _release_lora(self, request: Request) -> None:
        """Unpin a request's adapter at its terminal event (idempotent —
        some paths fire twice for one request). Every site that records
        record_request_done pairs with one of these."""
        if self.lora is not None and request.sampling.lora:
            self.lora.release(request.request_id)

    def _fr_emit(self, request: Request, event: str, **attrs) -> None:
        """One flight-recorder event for a request. Every terminal path
        (finish / error / shed / park) must call this next to its event-queue
        put — statically enforced by scripts/check_lifecycle_events.py."""
        if self.flightrec.enabled:
            self.flightrec.emit(request.request_id, event, **attrs)

    def _lora_rows(self, requests) -> "np.ndarray":
        """Adapter pool rows for an ordered request list — the per-row
        index vector a prefill dispatch carries (activation then scatters
        the same rows into the per-slot device mirror)."""
        return np.asarray(
            [self.lora.slot_of(r.sampling.lora) for r in requests],
            np.int32,
        )

    def stats(self) -> EngineStats:
        active = sum(1 for s in self.slots if s.request is not None)
        queued = self.pending.qsize()
        queued += sum(len(q) for q in self._class_queues.values())
        if self._held_request is not None:
            queued += 1  # parked on page-pool pressure, still queued work
        if self.coordinator is not None:
            # Multihost: requests sitting in the leader's intake queue or
            # spilled to the next tick's plan backlog are queued work the
            # gateway's telemetry-aware placement must see (reading only
            # self.pending undercounted them).
            queued += self._intake.qsize() + len(self._plan_backlog)
        return EngineStats(
            num_slots=self.num_slots,
            active_slots=active,
            queued=queued,
            total_requests=self.total_requests,
            total_tokens=self.total_tokens,
            uptime_s=time.monotonic() - self._started_at,
        )

    # ------------------------------------------------------------------- loop

    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"no prefill bucket for prompt of {n} tokens")

    # ------------------------------------------------------- multihost plans

    def _is_cancelled(self, request: Request) -> bool:
        """Deterministic cancellation check. Single-host reads the live flag;
        multihost reads the plan-mirrored set so every host sees the
        cancellation on the same tick."""
        if self.coordinator is None:
            return request.cancelled
        return request.request_id in self._cancelled_effective

    def _collect_plan(self) -> dict:
        """Leader: drain intake + gather cancellations into this tick's plan.
        Requests cancelled before ever entering a plan are finished here
        directly — no host (including this one) runs device ops for them.
        The plan payload is bounded here, at collection: a too-large batch
        spills to the next tick and an impossibly large single request is
        failed with a terminal event — never by raising mid-broadcast, which
        would desync the lockstep cluster."""
        from llmlb_tpu.engine.multihost import _MAX_PLAN_BYTES

        budget = _MAX_PLAN_BYTES // 8  # ~int32 tokens, pickled with overhead
        candidates = self._plan_backlog
        self._plan_backlog = []
        while True:
            try:
                candidates.append(self._intake.get_nowait())
            except queue.Empty:
                break
        new = []
        tokens = 0
        for idx, req in enumerate(candidates):
            if req.cancelled:
                req.events.put(("done", "cancelled"))
                self.metrics.record_request_done("cancelled")
                self._fr_emit(req, "finished", reason="cancelled")
                self._release_lora(req)
                continue
            if req.deadline_expired():
                # deadline shedding must be deterministic across hosts, so
                # multihost sheds HERE (leader-only, before the plan) — a
                # shed request never reaches any host's queue
                req.events.put(("error", "deadline exceeded before prefill"))
                self.metrics.record_request_done("error")
                self.metrics.record_deadline_shed()
                self._fr_emit(req, "shed", reason="deadline")
                self._release_lora(req)
                continue
            n = len(req.prompt_ids)
            if n > budget:
                req.events.put(("error", "prompt too large for a tick plan"))
                self.metrics.record_request_done("error")
                self._fr_emit(req, "errored",
                              message="prompt too large for a tick plan")
                self._release_lora(req)
                continue
            if tokens + n > budget:
                # spill THIS and everything behind it to the next tick's
                # backlog — arrival order is preserved, no starvation
                self._plan_backlog = candidates[idx:]
                break
            tokens += n
            new.append(req)
        cancelled = []
        in_flight = [s.request for s in self.slots if s.request is not None]
        if self._held_request is not None:
            in_flight.append(self._held_request)  # parked on the page pool
        # snapshot under the queue's own mutex — iterating .queue while a
        # concurrent put() mutates the deque is undefined; the lock makes the
        # snapshot atomic regardless of which thread produces into pending
        with self.pending.mutex:
            in_flight += list(self.pending.queue)
        in_flight += self._queued_requests()  # drained into class deques
        for req in in_flight:
            if req.cancelled and req.request_id not in self._cancelled_effective:
                cancelled.append(req.request_id)
        return {
            "new": new,  # leader keeps real objects; followers get payloads
            "cancelled": cancelled,
            "stop": self._stop_requested,
        }

    def _plan_wire(self, plan: dict) -> dict:
        """Wire form of a plan (shadow payloads instead of Request objects)."""
        return {
            "new": [
                {
                    "request_id": r.request_id,
                    "prompt_ids": list(r.prompt_ids),
                    "sampling": dataclasses.asdict(r.sampling),
                }
                for r in plan["new"]
            ],
            "cancelled": plan["cancelled"],
            "stop": plan["stop"],
        }

    def _apply_plan(self, plan: dict, local: dict | None) -> None:
        """Every host: enqueue this tick's requests in plan order (the leader
        re-queues its real Request objects, followers build shadows whose
        event queues simply go unread) and mirror cancellations."""
        if local is not None:  # leader
            for req in local["new"]:
                self.pending.put(req)
        else:
            for payload in plan["new"]:
                self.pending.put(Request(
                    prompt_ids=payload["prompt_ids"],
                    sampling=SamplingParams(**payload["sampling"]),
                    request_id=payload["request_id"],
                ))
        self._cancelled_effective |= set(plan["cancelled"])
        if plan["stop"]:
            self._running = False

    def _lockstep_tick(self) -> None:
        local = None
        if self.coordinator.is_leader:
            local = self._collect_plan()
            wire = self._plan_wire(local)
        else:
            wire = None
        plan = self.coordinator.exchange(wire)
        self._apply_plan(plan, local)

    def _fetch_tokens(self, tokens_dev) -> np.ndarray:
        """D2H that works when the array spans non-addressable devices."""
        if self._replicate is not None:
            tokens_dev = self._replicate(tokens_dev)
        return np.asarray(tokens_dev)

    def drain_active(self) -> bool:
        """True while the engine refuses new admissions (graceful drain)."""
        return self.draining

    def begin_drain(self) -> None:
        """Stop admitting new work; in-flight slots keep decoding. One-way —
        the draining process exits or is restarted by its supervisor."""
        self.draining = True

    def request_drain_park(self) -> None:
        """Ask the step loop to park every decoding slot at its next
        iteration (the drain grace expired). Thread-safe: a plain bool write
        consumed by the loop thread, like Request.cancelled."""
        self._drain_park_requested = True

    def request_park(self, gateway_id: str) -> None:
        """Ask the step loop to park ONE stream (by gateway request id) at
        its next iteration and spill its KV for export — a proactive
        migration is pulling the stream to another engine while this one
        keeps serving everyone else. Thread-safe the same way as
        request_drain_park: the set is only consumed by the loop thread."""
        self._park_rids.add(gateway_id)

    def request_drain_flush(self) -> None:
        """Ask the step loop to terminal-error everything still queued
        (parked-for-drain work included). Called AFTER the drain aborted
        the in-flight connections: the committed tokens live on in the
        gateway's replay ledger, but the HTTP handlers blocked on these
        requests' event queues must unblock or they would pin executor
        threads (and the server's shutdown) forever."""
        self._drain_flush_requested = True

    def _drain_flush_all(self) -> None:
        """Loop thread only (queues are loop-thread-owned)."""
        self._drain_pending()
        flushed: list[Request] = []
        for p in PRIORITY_CLASSES:
            q = self._class_queues[p]
            while q:
                flushed.append(q.popleft())
        if self._held_request is not None:
            flushed.append(self._held_request)
            self._held_request = None
        for request in flushed:
            request.events.put(("error", "engine draining"))
            self.metrics.record_request_done("error")
            self._fr_emit(request, "errored", message="engine draining")
            self._release_lora(request)
        if flushed:
            log.info("drain flushed %d queued request(s)", len(flushed))

    def _drain_park_all(self) -> None:
        """Park every parkable decoding slot (loop thread only). Prefilling
        and first_pending slots cannot park (incomplete KV / device-only
        last token) — their connections are aborted by the server instead,
        and the gateway resumes them from its own replay ledger."""
        for i, slot in enumerate(self.slots):
            if (slot.request is not None and not slot.prefilling
                    and not slot.first_pending and not slot.handoff_ready):
                self._park_slot(i, reason="drain")
                self.metrics.record_drain_park()

    def _park_requested(self, rids: set[str]) -> None:
        """Park the slots serving these gateway request ids (loop thread
        only) — the per-stream migration park. Unparkable states (prefill
        in flight, first token device-only) and ids not decoding here are
        dropped: the gateway's export fetch times out and the migration
        aborts with the origin stream untouched."""
        for i, slot in enumerate(self.slots):
            if (slot.request is not None and not slot.prefilling
                    and not slot.first_pending and not slot.handoff_ready
                    and gateway_rid(slot.request.request_id) in rids):
                self._park_slot(i, reason="migrate")

    def _loop(self) -> None:
        while self._running:
            did_work = False
            try:
                if self.coordinator is not None:
                    self._lockstep_tick()
                    if not self._running:
                        break
                if self._drain_park_requested:
                    self._drain_park_requested = False
                    self._drain_park_all()
                if self._park_rids:
                    rids = self._park_rids
                    self._park_rids = set()
                    self._park_requested(rids)
                if self._drain_flush_requested:
                    self._drain_flush_requested = False
                    self._drain_flush_all()
                did_work |= self._try_insert()
                # At most ONE prefill chunk per iteration: decode steps run
                # between chunks, so active slots keep emitting tokens during
                # a long prompt's prefill (prefill/decode interleaving).
                did_work |= self._advance_prefill()
                did_work |= self._decode_active()
            except Exception:  # pragma: no cover - defensive: fail loud, keep serving
                log.exception("engine step failed; resetting engine state")
                self._fail_all("engine step error")
                # prefill/decode donate the caches: after a failed dispatch the
                # buffers may already be consumed — rebuild before serving again.
                self._reset_caches()
            if not did_work:
                time.sleep(0.001)

    def _reset_caches(self) -> None:
        if self.page_pool is not None:
            ck, cv = self.family.init_kv_pages(self.cfg, self.kv_num_pages,
                                               self.kv_page_size,
                                               quantized=self.quant.kv)
            ck_sh, cv_sh = self.family.kv_pages_shardings(
                self.cfg, self.mesh, quantized=self.quant.kv
            )
            # every page mapping is void with the rebuilt pool
            self.page_pool.reset()
            self._slot_pages = [[] for _ in range(self.num_slots)]
            self._block_tables[:] = 0
            self._d_block_tables = jnp.asarray(self._block_tables)
            self._tables_dirty = False
        else:
            ck, cv = self.family.init_kv_cache(self.cfg, self.num_slots,
                                               self.slot_capacity)
            ck_sh, cv_sh = self.family.kv_cache_shardings(self.cfg, self.mesh)
        self.cache_k = jax.device_put(ck, ck_sh)
        self.cache_v = jax.device_put(cv, cv_sh)
        self._seq_lens[:] = 0
        self._d_seq_lens = jnp.zeros((self.num_slots,), jnp.int32)
        self._d_last_tokens = jnp.zeros((self.num_slots,), jnp.int32)
        if self.prefix_cache is not None:
            # the rebuilt cache holds zeros; every pinned prefix is gone
            self.prefix_cache.clear()
        self._prefix_pinned_pages = 0

    def _record_step(self, kind: str, phases: dict[str, float], *,
                     active_slots: int = 0, tokens: int = 0,
                     slots: "list[int] | None" = None,
                     dispatches: int = 0, fused: bool = False) -> None:
        """Finalize one step record: absorb plan/insert time accrued since
        the previous dispatch, feed the ring buffer + anomaly detector, and
        mirror the phase durations into the Prometheus histograms. `slots`
        names the slot ids this dispatch touched: their requests' gateway
        ids land on the StepRecord (so /api/steps?slow=1 names the victims)
        and a flagged step writes a slow_step event into each victim's
        flight record. `dispatches` is the honest device-program count this
        step issued (decode/verify kinds feed the per-loop dispatch ledger
        and the fused-decode "exactly one" invariant); `fused` marks steps
        served by the single-program path."""
        if self._pending_plan_s > 0.0:
            phases["plan"] = phases.get("plan", 0.0) + self._pending_plan_s
            self._pending_plan_s = 0.0
        request_ids: dict[str, str] | None = None
        if slots:
            request_ids = {}
            for i in slots:
                r = self.slots[i].request
                if r is not None:
                    request_ids[str(i)] = gateway_rid(r.request_id)
        if kind in ("decode", "verify") and dispatches > 0:
            self.decode_dispatch_by_loop[self._loop_tag()] += dispatches
            self.metrics.record_decode_dispatches(dispatches, fused=fused)
        slow = self.step_stats.observe(kind, phases,
                                       active_slots=active_slots,
                                       tokens=tokens,
                                       request_ids=request_ids,
                                       dispatches=dispatches)
        self.metrics.record_step_phases(phases, slow=slow)
        if slow and request_ids and self.flightrec.enabled:
            total = round(sum(phases.values()), 6)
            seq = self.step_stats.seq
            for rid in request_ids.values():
                self.flightrec.emit(rid, "slow_step", kind=kind,
                                    total_s=total, step_seq=seq)

    # Same-bucket pending prompts prefill TOGETHER in one dispatch (padded to
    # a power-of-two group so the jit cache stays at log2 sizes). Bounded so
    # a deep backlog cannot starve decode for longer than one group's
    # prefill; the loop comes back around for the rest.
    MAX_PREFILL_GROUP = 8

    def _free_slots(self) -> list[int]:
        """Slots available for new requests: unoccupied and not pinned as
        prefix-cache donors (dense mode only — paged donors pin pages, not
        slots, so pinned_slots() is empty there and every idle slot serves).
        Split mode admits only into the prefill pool (the decode pool fills
        exclusively through handoff adoption)."""
        if self.split is not None:
            return self.split.free_prefill_slots()
        pinned = (self.prefix_cache.pinned_slots()
                  if self.prefix_cache is not None else ())
        return [
            i for i, s in enumerate(self.slots)
            if s.request is None and i not in pinned
        ]

    # ------------------------------------------ priority classes / preemption

    @staticmethod
    def _priority_of(request: Request) -> int:
        try:
            p = int(request.sampling.priority)
        except (TypeError, ValueError):
            p = PRIORITY_NORMAL
        return min(PRIORITY_LOW, max(PRIORITY_HIGH, p))

    def _effective_prompt(self, request: Request) -> list[int]:
        """The token sequence an insert must land in KV: the prompt, plus —
        for a preempted request resuming — every token it already emitted.
        Chunk-prefilling the committed sequence puts each token's KV at the
        exact position the uninterrupted run had it, and the activation
        sample (step = len-1) draws the exact PRNG fold the next decode
        token would have used, so the resumed stream is token-identical."""
        if request.parked is not None:
            return list(request.prompt_ids) + request.parked.tokens
        return request.prompt_ids

    def _drain_pending(self) -> None:
        while True:
            try:
                r = self.pending.get_nowait()
            except queue.Empty:
                return
            cls = self._priority_of(r)
            self._class_queues[cls].append(r)
            self._fr_emit(r, "queued", cls=PRIORITY_NAMES[cls],
                          position=len(self._class_queues[cls]) - 1)

    def _queued_requests(self) -> list[Request]:
        out: list[Request] = []
        for p in PRIORITY_CLASSES:
            out.extend(self._class_queues[p])
        return out

    def _pop_request(self) -> Request | None:
        """Next request to admit: strictly by class. The held (page-starved)
        request keeps its place at the FRONT of its own class — but a
        MORE-important class still pops first, else a low-priority request
        wedged on the page pool would block the very arrival whose
        page-pressure preemption could unwedge it (priority inversion)."""
        held = self._held_request
        held_prio = self._priority_of(held) if held is not None else None
        for p in PRIORITY_CLASSES:
            if held_prio is not None and p >= held_prio:
                break
            q = self._class_queues[p]
            if q:
                return q.popleft()
        if held is not None:
            self._held_request = None
            return held
        for p in PRIORITY_CLASSES:
            q = self._class_queues[p]
            if q:
                return q.popleft()
        return None

    def _head_priority(self) -> int | None:
        """Priority of the next request _pop_request would return."""
        best: int | None = None
        if self._held_request is not None:
            best = self._priority_of(self._held_request)
        for p in PRIORITY_CLASSES:
            if self._class_queues[p]:
                return p if best is None else min(best, p)
        return best

    def _hold_on_pool(self, request: Request) -> None:
        """Queue a page-starved request for the next tick's retry. Only one
        hold slot exists; a request popped PAST a still-held one (a
        more-important class, see _pop_request) must not overwrite it —
        the overwritten request's event queue would never answer."""
        if self._held_request is None:
            self._held_request = request
        else:
            self._class_queues[self._priority_of(request)].appendleft(request)

    def _preempt_candidates(self, prio: int) -> list[int]:
        """Decoding slots a class-`prio` request may park, least important
        first, then least committed tokens (cheapest re-prefill), then slot
        id — a deterministic order every multihost mirror computes
        identically. Prefilling slots are never parked (their KV is
        incomplete), and first_pending slots' last token is device-only, so
        parking one would lose it."""
        out = [
            i for i, s in enumerate(self.slots)
            if (s.request is not None and not s.prefilling
                and not s.first_pending
                and self._priority_of(s.request) > prio)
        ]
        out.sort(key=lambda i: (-self._priority_of(self.slots[i].request),
                                int(self._seq_lens[i]), i))
        return out

    def _finish_slot(self, slot_id: int, reason: str) -> None:
        """Terminal teardown of an occupied slot outside the decode-emit
        path (prefill-time cancellation, split-mode staged drops): terminal
        event + accounting, cache entry / KV pages / constraint released,
        and EVERY slot field reset. One copy of the invariant — a new _Slot
        field (the handoff_* trio being the cautionary tale) has exactly
        one place to be cleared."""
        slot = self.slots[slot_id]
        request = slot.request
        assert request is not None
        request.finished_at = time.monotonic()
        request.events.put(("done", reason))
        self.metrics.record_request_done(reason)
        self._fr_emit(request, "finished", reason=reason,
                      generated=slot.generated)
        self._release_lora(request)
        self._cancelled_effective.discard(request.request_id)
        self._release_cache_entry(slot)
        self._free_slot_kv(slot_id)
        self._clear_constraint(slot_id)
        slot.request = None
        slot.generated = 0
        slot.prefilling = False
        slot.prefill_pos = 0
        slot.handoff_ready = False
        slot.handoff_logits = None
        slot.handoff_ready_at = 0.0
        slot.last_emit_at = 0.0
        slot.first_pending = False
        slot.drafter = None
        slot.spec_k = 0
        slot.out_tokens = []

    def _park_slot(self, slot_id: int, reason: str = "preempt") -> None:
        """Preempt one decoding slot: release its KV (pages back to the pool
        — parking is cheap BECAUSE the layout is paged), capture resume
        state on the request, and requeue it at the front of its class. The
        grammar cursor and drafter park WITH the request; a resume must
        never re-walk the FSM from its start state. `reason` tags the flight
        record: preempt (priority arrival) | drain | pages (pool
        exhaustion)."""
        slot = self.slots[slot_id]
        request = slot.request
        assert request is not None and not slot.prefilling
        request.parked = ParkedState(
            generated=slot.generated,
            tokens=list(slot.out_tokens),
            constraint=slot.constraint,
            drafter=slot.drafter,
            spec_k=slot.spec_k,
        )
        # KV leaves the device BEFORE the pool reclaims it: a draining
        # engine records the wire payload for /v1/kv/export, the offload
        # tier keeps it for a local restore (docs/kv-cache.md)
        self._spill_parked_slot(slot_id, request, reason)
        self._release_cache_entry(slot)
        self._free_slot_kv(slot_id)
        if slot.constraint is not None:
            # cursor parked above — tear down only the live mask row
            self._constrained_count -= 1
            if self._mask_bias is not None:
                self._mask_bias[slot_id] = 0.0
                self._mask_dirty_rows.add(slot_id)
            slot.constraint = None
        slot.request = None
        slot.generated = 0
        slot.last_emit_at = 0.0
        slot.first_pending = False
        slot.prefilling = False
        slot.prefill_pos = 0
        slot.out_tokens = []
        slot.drafter = None
        slot.spec_k = 0
        self.metrics.record_preemption()
        self._fr_emit(request, "parked", reason=reason,
                      generated=len(request.parked.tokens))
        log.info("preempted request %s at %d committed tokens (priority %s)",
                 request.request_id, len(request.parked.tokens),
                 PRIORITY_NAMES[self._priority_of(request)])
        self._class_queues[self._priority_of(request)].appendleft(request)

    def _preempt_for_pages(self, prio: int) -> bool:
        """Page pressure: park one less-important slot that actually holds
        pages, so the reservation retry can succeed. False when no eligible
        victim exists (the caller then holds the request as before)."""
        for i in self._preempt_candidates(prio):
            if self._slot_pages[i]:
                self._park_slot(i, reason="pages")
                return True
        return False

    def _shed_expired(self, request: Request) -> bool:
        """Deadline shedding at admission (single-host only: clocks differ
        across hosts, so multihost sheds at the leader's plan collection
        instead). Never sheds a resumed request — the client already holds
        part of its stream."""
        if (self.coordinator is not None or request.parked is not None
                or not request.deadline_expired()):
            return False
        request.events.put(("error", "deadline exceeded before prefill"))
        self.metrics.record_request_done("error")
        self.metrics.record_deadline_shed()
        self._fr_emit(request, "shed", reason="deadline")
        self._release_lora(request)
        return True

    def _prefill_budget_now(self) -> int:
        """Prompt tokens this step-loop iteration may spend on prefill
        (0 = uncapped). The cap applies only while some slot is decoding —
        an idle engine prefills at full width."""
        b = self.prefill_chunk_budget
        if b <= 0:
            return 0
        if not any(s.request is not None and not s.prefilling
                   for s in self.slots):
            return 0
        return b

    def _budget_chunk_len(self, budget: int) -> int:
        """Largest prefill bucket within the budget (floor: the smallest
        bucket — chunks must be a compiled size)."""
        best = self.prefill_buckets[0]
        for bkt in self.prefill_buckets:
            if bkt <= budget:
                best = bkt
        return best

    def queue_class_depths(self) -> dict[str, int]:
        """Queued requests per priority class (held request included) for
        /metrics and the sched info block."""
        depths = {PRIORITY_NAMES[p]: len(self._class_queues[p])
                  for p in PRIORITY_CLASSES}
        held = self._held_request
        if held is not None:
            depths[PRIORITY_NAMES[self._priority_of(held)]] += 1
        return depths

    def sched_info(self) -> dict:
        """Scheduling block for /api/system, /api/health, and /metrics:
        priority-queue depths plus the overload-protection counters."""
        m = self.metrics
        info = {
            "prefill_chunk_budget": self.prefill_chunk_budget,
            "queued_by_class": self.queue_class_depths(),
            "preemptions_total": m.preemptions_total,
            "preempt_resumes_total": m.preempt_resumes_total,
            "deadline_shed_total": m.deadline_shed_total,
        }
        if self.split is not None:
            # role-labeled queue depths (docs/disaggregation.md): work still
            # waiting for a prefill slot vs prefilled work waiting for a
            # decode slot to adopt it (the handoff backlog)
            info["queued_by_role"] = {
                "prefill": sum(info["queued_by_class"].values()),
                "decode": self.split.backlog(),
            }
        return info

    def disagg_info(self) -> dict:
        """Disaggregation block for /api/system and /api/health: the served
        role, split-pool sizes, and the handoff counters every consumer of
        the docs/disaggregation.md surfaces reads."""
        m = self.metrics
        info = {
            "role": self.role,
            "split": self.split is not None,
            "handoff_total": dict(m.handoff_total),
            "handoff_backlog": m.handoff_backlog,
        }
        if self.split is not None:
            info["prefill_slots"] = len(self.split.prefill_pool)
            info["decode_slots"] = len(self.split.decode_pool)
        return info

    # -------------------------------------------------------------- page pool

    def _pages_for_tokens(self, n: int) -> int:
        return -(-n // self.kv_page_size)

    def _try_reserve_pages(self, count: int) -> list[int] | None:
        """Alloc `count` fresh pages, evicting prefix-cache pages LRU under
        pool pressure. None (no side effects beyond the evictions) when the
        pool still cannot cover the request."""
        if count <= 0:
            return []
        while True:
            pages = self.page_pool.alloc(count)
            if pages is not None:
                return pages
            if self.prefix_cache is None or not self._evict_one_prefix():
                return None

    def _assign_slot_pages(self, slot_id: int, shared, fresh) -> None:
        """Install a slot's block-table row: `shared` donor pages first
        (zero-copy prefix reuse — the slot takes a reference on each, no KV
        bytes move), then `fresh` pages (refcount 1 from alloc, owned)."""
        for p in shared:
            self.page_pool.ref(p)
        row = list(shared) + list(fresh)
        self._slot_pages[slot_id] = row
        self._block_tables[slot_id, :] = 0
        self._block_tables[slot_id, :len(row)] = row
        self._tables_dirty = True

    def _extend_slot_pages(self, slot_id: int, fresh: list[int]) -> None:
        row = self._slot_pages[slot_id]
        start = len(row)
        row.extend(fresh)
        self._block_tables[slot_id, start:start + len(fresh)] = fresh
        self._tables_dirty = True

    def _free_slot_kv(self, slot_id: int) -> None:
        """Return a slot's pages to the pool (shared prefix pages just drop
        this slot's reference; the donor entry keeps them alive) and point
        its table row at the trash page so the batched decode step's ongoing
        garbage writes for the freed row can never land in a page a new
        owner holds."""
        if self.page_pool is None:
            return
        pages = self._slot_pages[slot_id]
        if pages:
            for p in pages:
                self.page_pool.unref(p)
            self._slot_pages[slot_id] = []
            self._block_tables[slot_id, :] = 0
            self._tables_dirty = True

    def _sync_block_tables(self) -> None:
        """Refresh the device block tables before a dispatch that reads them
        (one small H2D, only when a row changed since the last sync)."""
        if self._tables_dirty:
            self._d_block_tables = jnp.asarray(self._block_tables)
            self._tables_dirty = False

    def _ensure_decode_pages(self, active: list[int], k: int,
                             per_row: dict[int, int] | None = None
                             ) -> list[int]:
        """Alloc-on-extend before a decode dispatch: grow each active row's
        page list to cover the k tokens the dispatch writes (`per_row`
        overrides k per slot — the verify dispatch writes a different chunk
        per row, and padded positions beyond a row's allocation land on the
        trash page, so over-allocating for them would just churn pages).
        Under pool exhaustion prefix-cache pages are evicted first; if the
        pool STILL cannot cover a row, that request finishes with 'length' —
        the step loop must never crash or deadlock on a full pool. Returns
        the rows that remain active."""
        kept = []
        for i in active:
            slot = self.slots[i]
            if slot.request is None:
                # parked by a page-pressure preemption earlier in this walk
                continue
            kk = per_row.get(i, k) if per_row is not None else k
            target = min(int(self._seq_lens[i]) + kk + 1, self.slot_capacity)
            need = self._pages_for_tokens(target) - len(self._slot_pages[i])
            if need > 0:
                fresh = self._try_reserve_pages(need)
                # a more important row may park less important decoders
                # before giving up (their pages come back to the pool)
                while fresh is None and self._preempt_for_pages(
                        self._priority_of(slot.request)):
                    fresh = self._try_reserve_pages(need)
                if fresh is None:
                    request = slot.request
                    if not slot.first_pending and len(active) > 1:
                        # Park rather than force-finish: the pre-preemption
                        # engine cut the request off at 'length' here; now
                        # it resumes token-identical once pages free up.
                        log.warning(
                            "page pool exhausted mid-decode; parking request "
                            "%s at %d tokens", request.request_id,
                            int(self._seq_lens[i]),
                        )
                        self._park_slot(i, reason="pages")
                        continue
                    log.warning(
                        "page pool exhausted mid-decode; finishing request "
                        "%s at %d tokens", request.request_id,
                        int(self._seq_lens[i]),
                    )
                    request.finished_at = time.monotonic()
                    request.events.put(("done", "length"))
                    self.metrics.record_request_done("length")
                    self._fr_emit(request, "finished", reason="length",
                                  generated=slot.generated, cause="pages")
                    self._release_lora(request)
                    self._cancelled_effective.discard(request.request_id)
                    self._free_slot_kv(i)
                    if slot.constraint is not None:
                        # only an UNaccepted grammar cut short is a violation
                        # (same rule as the length path in _emit)
                        if not slot.constraint.is_accepting:
                            self.metrics.record_constraint_violation()
                        self._clear_constraint(i)
                    slot.request = None
                    slot.generated = 0
                    slot.last_emit_at = 0.0
                    slot.first_pending = False
                    slot.drafter = None
                    slot.spec_k = 0
                    slot.out_tokens = []
                    continue
                self._extend_slot_pages(i, fresh)
            kept.append(i)
        return kept

    # ------------------------------------------------------------ kv transfer

    def _kv_dtype_name(self) -> str:
        return "int8" if self.quant.kv else str(jnp.dtype(self.cfg.dtype))

    def _kv_header(self, tokens: int, num_pages: int) -> KVWireHeader:
        return KVWireHeader(
            version=KV_WIRE_VERSION,
            layers=self.cfg.num_layers,
            page_size=self.kv_page_size,
            num_kv_heads=self.cfg.num_kv_heads,
            head_dim=self.cfg.head_dim_,
            kv_dtype=self._kv_dtype_name(),
            tokens=tokens,
            num_pages=num_pages,
        )

    def kv_restore_reason(self, header: KVWireHeader) -> str | None:
        """None when an inbound payload can land in THIS pool verbatim,
        else the fallback-counter reason (dtype | page_size | geometry)."""
        return kv_compat_reason(
            header,
            layers=self.cfg.num_layers,
            page_size=self.kv_page_size,
            num_kv_heads=self.cfg.num_kv_heads,
            head_dim=self.cfg.head_dim_,
            kv_dtype=self._kv_dtype_name(),
        )

    def _gather_kv_sections(self, pages: list[int]) -> dict[str, np.ndarray]:
        """D2H gather of the named pool pages into wire-section arrays
        [L, P', PS, K, D] (int8 pools gather {codes, scales} per cache).
        A plain read — the pool is untouched, so gathering before a free
        is always safe."""
        idx = jnp.asarray(pages, jnp.int32)
        sections: dict[str, np.ndarray] = {}
        if self.quant.kv:
            sections["k_q"] = np.asarray(self.cache_k["q"][:, idx])
            sections["k_s"] = np.asarray(self.cache_k["s"][:, idx])
            sections["v_q"] = np.asarray(self.cache_v["q"][:, idx])
            sections["v_s"] = np.asarray(self.cache_v["s"][:, idx])
        else:
            sections["k"] = np.asarray(self.cache_k[:, idx])
            sections["v"] = np.asarray(self.cache_v[:, idx])
        return sections

    def _capture_kv(self, pages: list[int], tokens: int) -> KVPages:
        return KVPages(header=self._kv_header(tokens, len(pages)),
                       sections=self._gather_kv_sections(pages))

    def _kv_export_payload(self, slot_id: int,
                           request: Request) -> dict | None:
        """Serialize the pages covering this slot's valid KV rows into a
        wire payload (the /v1/handoff pages attachment). None when there is
        nothing shippable."""
        tokens = int(self._seq_lens[slot_id])
        if tokens <= 0 or not self._slot_pages[slot_id]:
            return None
        pages = self._slot_pages[slot_id][: self._pages_for_tokens(tokens)]
        t0 = time.monotonic()
        kvp = self._capture_kv(pages, tokens)
        payload = serialize_kv_pages(kvp.header, kvp.sections)
        dt = time.monotonic() - t0
        self.metrics.record_kv_ship(kvp.nbytes, dt)
        self._fr_emit(request, "kv_shipped", tokens=tokens,
                      pages=len(pages), bytes=kvp.nbytes,
                      seconds=round(dt, 6))
        return payload

    def take_kv_export(self, gateway_id: str) -> dict | None:
        """Consume a drain-park export (POST /v1/kv/export): the gateway
        fetches the parked stream's serialized pages from the draining
        origin and attaches them to /v1/resume on the adopter. One-shot —
        the payload is handed over exactly once."""
        with self._lock:
            return self._kv_exports.pop(gateway_id, None)

    def _land_kv_pages(self, kvp: KVPages, fresh: list[int]) -> None:
        """H2D: land the first len(fresh) shipped pages into pool pages
        `fresh` via the donated scatter. The page-index vector (and the
        sections) pad to the next power of two by repeating the last page —
        a duplicate scatter of identical bytes — so the jit cache stays at
        log2(pool) variants, the same discipline as _copy_kv_prefix's
        static rows."""
        n = len(fresh)
        pad = 1
        while pad < n:
            pad *= 2

        def padded(name: str) -> jnp.ndarray:
            a = kvp.sections[name][:, :n]
            if pad > n:
                a = np.concatenate(
                    [a, np.repeat(a[:, -1:], pad - n, axis=1)], axis=1
                )
            return jnp.asarray(a)

        def side(prefix: str):
            if self.quant.kv:
                return {"q": padded(prefix + "_q"),
                        "s": padded(prefix + "_s")}
            return padded(prefix)

        idx = np.asarray(fresh + [fresh[-1]] * (pad - n), np.int32)
        self.cache_k, self.cache_v = _write_kv_pages(
            self.cache_k, self.cache_v, side("k"), side("v"),
            jnp.asarray(idx),
        )

    def _insert_restored(self, slot_id: int, request: Request,
                         prompt: list[int], n: int) -> bool:
        """Page-transfer activation (docs/kv-cache.md): land a shipped KV
        payload into freshly reserved pool pages and enter decode directly
        — ZERO prefill dispatches. The device row restores to
        seq_len = n-1 with committed[-1] pending: the next ordinary decode
        dispatch rewrites position n-1's KV (identical bytes — that row
        shipped too) and samples with the pre-increment fold n-1, exactly
        the dispatch the uninterrupted stream ran at this position, so
        greedy and seeded continuations stay token-identical on bf16 and
        int8 pools alike. Any refusal drops the payload, counts a
        reason-labeled fallback, and returns False — the caller continues
        into the chunk-prefill replay path; a bad payload never fails the
        request."""
        kvp = request.kv_restore
        request.kv_restore = None  # one-shot either way
        st = request.parked
        need_tokens = n - 1
        if (kvp is None or st is None or not st.tokens or need_tokens < 1
                or kvp.header.tokens < need_tokens):
            self.metrics.record_kv_ship_fallback("capacity")
            return False
        need_pages = self._pages_for_tokens(need_tokens)
        if need_pages > kvp.header.num_pages:
            self.metrics.record_kv_ship_fallback("capacity")
            return False
        fresh = self._try_reserve_pages(need_pages)
        while fresh is None and self._preempt_for_pages(
                self._priority_of(request)):
            fresh = self._try_reserve_pages(need_pages)
        if fresh is None:
            self.metrics.record_kv_ship_fallback("capacity")
            return False
        t0 = time.monotonic()
        self._land_kv_pages(kvp, fresh)
        self._assign_slot_pages(slot_id, (), fresh)

        slot = self.slots[slot_id]
        slot.request = request
        # parked cursors first: _attach_constraint/_attach_spec read
        # request.parked for the FSM cursor (already advanced over the
        # committed tokens) and the drafter index
        self._attach_constraint(slot_id, request)
        s = request.sampling
        seed = -1 if s.seed is None else (s.seed & 0x7FFFFFFF)
        self._d_temps = self._d_temps.at[slot_id].set(float(s.temperature))
        self._d_top_ps = self._d_top_ps.at[slot_id].set(float(s.top_p))
        self._d_top_ks = self._d_top_ks.at[slot_id].set(int(s.top_k))
        self._d_seeds = self._d_seeds.at[slot_id].set(seed)
        if self.lora is not None:
            self._d_lora_idx = self._d_lora_idx.at[slot_id].set(
                int(self._lora_rows([request])[0])
            )
        self._d_seq_lens = self._d_seq_lens.at[slot_id].set(need_tokens)
        self._d_last_tokens = self._d_last_tokens.at[slot_id].set(
            int(prompt[-1])
        )
        self._seq_lens[slot_id] = need_tokens
        slot.generated = st.generated
        slot.out_tokens = list(st.tokens)
        slot.prefilling = False
        slot.prefill_pos = 0
        slot.last_emit_at = 0.0
        # NOT first_pending: the next decode fetch's step row IS this
        # stream's next token (the deferred-first row is for activation
        # samples, which never happened here)
        slot.first_pending = False
        request.parked = None
        self.metrics.record_resume()
        self.metrics.record_kv_restore(kvp.nbytes)
        self._fr_emit(request, "kv_restored", source=kvp.source,
                      kind="stream", tokens=need_tokens, pages=need_pages,
                      bytes=kvp.nbytes,
                      seconds=round(time.monotonic() - t0, 6))
        self._fr_emit(request, "resumed", generated=st.generated,
                      via="kv_restore")
        log.info(
            "kv restore: request %s re-entered decode at %d tokens from %s "
            "(%d pages, %.1f KiB, zero prefill dispatches)",
            request.request_id, need_tokens, kvp.source, need_pages,
            kvp.nbytes / 1024,
        )
        return True

    def _spill_parked_slot(self, slot_id: int, request: Request,
                           reason: str) -> None:
        """Park-time D2H capture with two consumers: a DRAINING engine
        records a wire payload for the gateway's /v1/kv/export fetch (the
        mid-stream resume then moves bytes instead of replaying), and the
        offload tier keeps the pages host-side so a local re-activation
        restores instead of re-prefilling. Skips first_pending parks: with
        zero committed tokens the faithful resume is the replay path."""
        if self.page_pool is None or not self._slot_pages[slot_id]:
            return
        slot = self.slots[slot_id]
        if slot.first_pending or not slot.out_tokens:
            return
        tokens = int(self._seq_lens[slot_id])
        if tokens <= 0:
            return
        pages = self._slot_pages[slot_id][: self._pages_for_tokens(tokens)]
        nbytes = len(pages) * kv_page_bytes(self.cfg, self.kv_page_size,
                                            quantized=self.quant.kv)
        # exports serve two callers: a draining engine spills EVERY park for
        # the gateway's resume fetch; a healthy engine spills only parks the
        # rebalancer explicitly requested (reason="migrate")
        want_export = self.kv_ship and (self.draining or reason == "migrate")
        tier = self.kv_offload
        want_tier = tier is not None and tier.would_admit(nbytes)
        if not (want_export or want_tier):
            return
        t0 = time.monotonic()
        kvp = self._capture_kv(pages, tokens)
        kvp.source = "offload"
        self.metrics.record_kv_ship(kvp.nbytes, time.monotonic() - t0)
        dest = []
        if want_export:
            payload = serialize_kv_pages(kvp.header, kvp.sections)
            with self._lock:
                self._kv_exports[gateway_rid(request.request_id)] = payload
            dest.append("export")
        if want_tier and tier.put_parked(request.request_id, kvp):
            dest.append("offload")
        if dest:
            self._fr_emit(request, "kv_spilled", reason=reason,
                          tokens=tokens, bytes=kvp.nbytes,
                          dest=",".join(dest))

    def _spill_prefix_entry(self, entry: PrefixEntry) -> None:
        """Prefix-cache eviction under page pressure: gather the entry's
        pages D2H into the offload tier before their references drop —
        the cold prefix stays warm in host RAM instead of vanishing.
        Request-anonymous, so this counts in metrics but not the flight
        record."""
        tier = self.kv_offload
        if tier is None or not entry.pages:
            return
        nbytes = len(entry.pages) * kv_page_bytes(
            self.cfg, self.kv_page_size, quantized=self.quant.kv
        )
        if not tier.would_admit(nbytes):
            return
        t0 = time.monotonic()
        kvp = self._capture_kv(list(entry.pages), len(entry.tokens))
        kvp.source = "offload"
        self.metrics.record_kv_ship(kvp.nbytes, time.monotonic() - t0)
        tier.put_prefix(entry.ns, entry.tokens, kvp)

    def _maybe_restore_prefix(self, request: Request, n: int) -> None:
        """Admission-time H2D promotion: if the offload tier holds a longer
        usable prefix of this prompt than the live radix cache, land it
        into fresh pages and re-insert it as a live entry — the ordinary
        zero-copy match below then serves it and only the suffix prefills.
        Failure is never fatal: pages unref'd, the cold path proceeds."""
        tier = self.kv_offload
        cache = self.prefix_cache
        if tier is None or cache is None:
            return
        ns = request.sampling.lora
        hit = tier.match_prefix(ns, request.prompt_ids, n - 1)
        if hit is None:
            return
        stored, kvp = hit
        # Usable head: capped at n-1 (one suffix token must prefill),
        # aligned down to the cache grain so the re-inserted entry obeys
        # the same alignment every live donation does. Pages are
        # position-independent, so slicing a long stored entry is free.
        usable = min(len(stored), n - 1)
        usable = (usable // self.prefix_align) * self.prefix_align
        if usable < cache.min_len:
            return
        tokens = tuple(stored[:usable])
        if cache.covers(tokens, ns) or self.kv_restore_reason(
                kvp.header) is not None:
            # live cache already serves it, or the payload was spilled by
            # an incompatible earlier config — drop silently (the tier
            # popped it; bytes free up either way)
            return
        pages_needed = usable // self.kv_page_size
        if pages_needed <= 0 or pages_needed > kvp.header.num_pages:
            return
        fresh = self._try_reserve_pages(pages_needed)
        if fresh is None:
            return  # pool pressure: re-prefill is the honest fallback
        t0 = time.monotonic()
        self._land_kv_pages(kvp, fresh)
        for stale in cache.evict_subsumed_entries(tokens, ns):
            self._release_entry_pages(stale)
        if len(cache) >= cache.max_entries and not self._evict_one_prefix():
            for p in fresh:
                self.page_pool.unref(p)
            return
        if cache.insert(tokens, -1, pages=tuple(fresh), ns=ns) is None:
            for p in fresh:
                self.page_pool.unref(p)
            return
        # unlike _maybe_cache_prefix's co-ownership, the cache is the SOLE
        # owner of these freshly alloc'd pages (refcount 1 from alloc) —
        # no extra ref, balancing _release_entry_pages' single unref
        self._prefix_pinned_pages += pages_needed
        self.metrics.record_kv_restore(kvp.nbytes)
        self.metrics.record_prefix_insert(len(tokens))
        self._fr_emit(request, "kv_restored", source="offload",
                      kind="prefix", tokens=len(tokens),
                      pages=pages_needed, bytes=kvp.nbytes,
                      seconds=round(time.monotonic() - t0, 6))

    def kv_transfer_info(self) -> dict:
        """KV movement block for /api/health and /api/system: the shipping
        knob, transfer/fallback counters, and the host-RAM offload tier's
        occupancy (docs/kv-cache.md)."""
        m = self.metrics
        return {
            "ship_enabled": self.kv_ship,
            "ship_total": m.kv_ship_total,
            "ship_bytes_total": m.kv_ship_bytes_total,
            "restored_total": m.kv_restored_total,
            "restored_bytes_total": m.kv_restored_bytes_total,
            "ship_fallback_total": dict(m.kv_ship_fallback_total),
            "pending_exports": len(self._kv_exports),
            "offload": (self.kv_offload.info()
                        if self.kv_offload is not None
                        else {"enabled": False}),
        }

    def _try_insert(self) -> bool:
        if self.draining:
            # graceful drain: nothing new is admitted or re-activated —
            # parked work stays queued for the gateway's resume to collect
            return False
        plan_start = time.perf_counter()
        self._prefill_spent_iter = 0  # first call of every loop iteration
        self._drain_pending()
        queued = (sum(len(q) for q in self._class_queues.values())
                  + (1 if self._held_request is not None else 0))
        free = self._free_slots()
        if (not free and self.page_pool is None
                and self.prefix_cache is not None and len(self.prefix_cache)):
            # Slot pressure (dense only): live traffic beats cached prefixes —
            # evict the LRU donor so a queued request is never starved by the
            # cache. Paged donors never pin slots, so evicting here could not
            # free one and would just drain the warm cache for nothing; paged
            # PAGE pressure has its own eviction path in _try_reserve_pages.
            if queued > 0 and self._evict_one_prefix():
                free = self._free_slots()
        if not free and queued > 0 and self.split is None:
            # Slot-pressure preemption: a queued request of a MORE important
            # class than some decoding slot parks the least important victim
            # (docs/scheduling.md). Same-class work always waits its turn.
            # Split mode skips this: parking a decode-pool victim cannot free
            # a PREFILL slot — its preemption point is handoff adoption
            # (disagg/split.py acquire_decode_slot) instead.
            head = self._head_priority()
            if head is not None:
                cands = self._preempt_candidates(head)
                if cands:
                    self._park_slot(cands[0])
                    free = self._free_slots()
        if not free:
            return False
        max_oneshot = self.prefill_buckets[-1] if self.prefill_buckets else 0
        # Chunked-prefill decode budget: while decoders are active, at most
        # `budget` prompt tokens prefill this iteration — larger prompts run
        # through the chunked path and one-shot batches stop accumulating at
        # the budget, so decode steps interleave (bounded ITL).
        budget = self._prefill_budget_now()
        long_cutoff = max_oneshot
        if budget:
            long_cutoff = min(max_oneshot, self._budget_chunk_len(budget))
        handled = False
        inserted = 0  # long inserts count toward the group cap too
        batch: list[tuple[int, Request, int]] = []  # (slot_id, request, n)
        batch_tokens = 0
        while free and len(batch) + inserted < self.MAX_PREFILL_GROUP:
            request = self._pop_request()
            if request is None:
                break
            if self._is_cancelled(request):
                if self.kv_offload is not None:
                    # a cancelled request's parked spill is dead bytes
                    self.kv_offload.drop_parked(request.request_id)
                request.events.put(("done", "cancelled"))
                self.metrics.record_request_done("cancelled")
                self._fr_emit(request, "finished", reason="cancelled")
                self._release_lora(request)
                self._cancelled_effective.discard(request.request_id)
                handled = True
                continue
            if self._shed_expired(request):
                handled = True
                continue
            # Resumed (preempted) requests prefill their COMMITTED sequence
            # (prompt + emitted tokens) — see _effective_prompt.
            prompt = self._effective_prompt(request)
            n = len(prompt)
            # Cap generation so the slot cache can hold prompt + output.
            if self.slot_capacity - n - 1 <= 0:
                if request.parked is not None:
                    # a request parked at the capacity edge has no room left
                    # to decode: finish it cleanly rather than erroring a
                    # stream the client is already consuming
                    request.finished_at = time.monotonic()
                    request.events.put(("done", "length"))
                    self.metrics.record_request_done("length")
                    self._fr_emit(request, "finished", reason="length",
                                  cause="capacity_edge_resume")
                    self._release_lora(request)
                    handled = True
                    continue
                request.events.put(
                    ("error", "prompt does not fit slot capacity")
                )
                self.metrics.record_request_done("error")
                self._fr_emit(request, "errored",
                              message="prompt does not fit slot capacity")
                self._release_lora(request)
                handled = True
                continue
            try:
                self._prepare_constraint(request)
            except Exception as e:
                request.events.put(("error", f"constraint rejected: {e}"))
                self.metrics.record_request_done("error")
                self._fr_emit(request, "errored",
                              message=f"constraint rejected: {e}")
                self._release_lora(request)
                handled = True
                continue
            # Page-transfer re-activation (docs/kv-cache.md): a parked
            # request whose KV travelled as bytes — a /v1/resume wire
            # payload, or a spill into the host-RAM offload tier — lands
            # its pages and re-enters decode directly. No prefill dispatch
            # runs and no decode-budget tokens are charged: nothing
            # prefills. Any refusal falls through to the ordinary
            # chunk-prefill replay below.
            if request.parked is not None:
                if request.kv_restore is None and self.kv_offload is not None:
                    request.kv_restore = self.kv_offload.pop_parked(
                        request.request_id
                    )
                if request.kv_restore is not None:
                    slot_id = free.pop(0)
                    if self._insert_restored(slot_id, request, prompt, n):
                        handled = True
                        inserted += 1
                        continue
                    free.insert(0, slot_id)
            if (budget and batch_tokens + min(n, long_cutoff) > budget
                    and (batch or inserted)):
                # the decode budget for this iteration is spent: the request
                # keeps its place at the front of its class for the next one
                self._class_queues[self._priority_of(request)].appendleft(
                    request
                )
                break
            # Prompts that cannot possibly match (too short for min_prefix_len
            # after reserving one suffix token) bypass the cache silently —
            # counting them as misses would page the hit-rate-collapse alert
            # on workloads with nothing cacheable in them. Resumed requests
            # bypass it too: their committed tokens are not a shareable
            # prompt, and their own prompt head may already be donated.
            if (self.prefix_cache is not None and request.parked is None
                    and n - 1 >= self.min_prefix_len):
                # Host-RAM tier promotion first: a spilled cold prefix lands
                # back into fresh pages and re-enters the live cache, so the
                # ordinary zero-copy match below serves it (docs/kv-cache.md)
                self._maybe_restore_prefix(request, n)
                # Longest cached prefix, capped at n-1 (at least one suffix
                # token must prefill to produce the first sampled logits).
                # Namespaced by adapter id (docs/lora.md): under LoRA the
                # prompt KV depends on the adapter's wq/wk/wv deltas, so an
                # adapter-blind hit would be silent corruption.
                hit = self.prefix_cache.match(request.prompt_ids,
                                              max_len=n - 1,
                                              ns=request.sampling.lora)
                if hit is not None and not self._prefer_cp_over(hit[1], n):
                    entry, use_len = hit
                    fresh: list[int] | None = None
                    if self.page_pool is not None:
                        # zero-copy hit: the shared head rides the donor's
                        # pages; only the suffix needs fresh ones. The donor
                        # must be pinned ACROSS the reservation — its LRU
                        # eviction inside _try_reserve_pages would free the
                        # very pages we are about to share (and could hand
                        # them back as the "fresh" suffix pages).
                        self.prefix_cache.acquire(entry)
                        shared = use_len // self.kv_page_size
                        fresh = self._try_reserve_pages(
                            self._pages_for_tokens(n) - shared
                        )
                        self.prefix_cache.release(entry)
                        if fresh is None:
                            self._hold_on_pool(request)
                            break
                        # no eviction point between the release above and
                        # _insert_cached's re-acquire (same thread, no pool
                        # ops in between), so the donor cannot vanish here
                    self._insert_cached(free.pop(0), request, entry, use_len,
                                        fresh)
                    handled = True
                    inserted += 1
                    continue
                self.metrics.record_prefix_miss()
            pages: list[int] | None = None
            if self.page_pool is not None:
                need = self._pages_for_tokens(n)
                pages = self._try_reserve_pages(need)
                # Page-pressure preemption: a more important request may
                # park less important decoders (their pages free) until the
                # reservation covers — the paged layout makes this a
                # refcount walk, no KV bytes move.
                while pages is None and self._preempt_for_pages(
                        self._priority_of(request)):
                    pages = self._try_reserve_pages(need)
                if pages is None:
                    self._hold_on_pool(request)
                    break
            slot_id = free.pop(0)
            if self.page_pool is not None:
                self._assign_slot_pages(slot_id, (), pages)
            if n > long_cutoff:
                heavy = self._insert_long(slot_id, request, n)
                handled = True
                inserted += 1
                if heavy:
                    # a context-parallel prefill is a full synchronous pass;
                    # get back to decode before taking another
                    break
                continue
            # Claim the slot BEFORE any dispatch: a failed prefill then
            # reaches these requests through _fail_all instead of leaving
            # their event queues silent forever.
            self.slots[slot_id].request = request
            self.slots[slot_id].generated = 0
            self._attach_constraint(slot_id, request)
            batch.append((slot_id, request, n))
            batch_tokens += n

        if not batch:
            if handled:
                # admission work with no prefill dispatch of its own (cached
                # inserts, long-prompt claims): the next step record absorbs
                # it as its plan phase
                self._pending_plan_s += time.perf_counter() - plan_start
            return handled

        # plan ends where dispatch begins; the prefill records below absorb
        # the accrued time via _record_step
        self._pending_plan_s += time.perf_counter() - plan_start
        self._prefill_spent_iter = batch_tokens
        # one prefill dispatch per length bucket present in the batch
        by_bucket: dict[int, list[tuple[int, Request, int]]] = {}
        for entry in batch:
            by_bucket.setdefault(self._bucket_for(entry[2]), []).append(entry)
        for bucket, group in by_bucket.items():
            self._prefill_group(bucket, group)
        return True

    def _insert_long(self, slot_id: int, request: Request, n: int) -> bool:
        """Claim a slot for a prompt beyond the largest one-shot bucket.
        Returns True when it ran a heavy synchronous prefill (CP path)."""
        slot = self.slots[slot_id]
        cp_capable = self._use_cp_prefill and hasattr(
            self.family, "make_context_parallel_prefill"
        )
        lora_request = self.lora is not None and request.sampling.lora
        if cp_capable and not lora_request:
            # Ring-attention prefill: one distributed pass over the mesh
            # sp axis fills the whole prompt's KV (per-chip sequence cost
            # ~n/sp), then scatters into the slot row.
            self._cp_prefill_into_slot(slot_id, request, n)
            return True
        if cp_capable and lora_request:
            # LoRA requests take the chunked path even on an sp>1 mesh: the
            # ring-attention prefill closure carries no adapter indices (a
            # sharded bgmv inside shard_map is future work — docs/lora.md).
            # Counted + warned once: this prompt pays single-chip prefill
            # latency, which an operator sizing the mesh should see.
            self.metrics.record_lora_cp_fallback()
            if not self._lora_cp_warned:
                self._lora_cp_warned = True
                log.warning(
                    "LoRA request %s (adapter %r, %d prompt tokens) fell "
                    "back from context-parallel to chunked prefill: the CP "
                    "pass carries no adapter indices. Further fallbacks "
                    "count on llmlb_engine_lora_cp_fallback_total.",
                    request.request_id, request.sampling.lora, n,
                )
        # Single-chip long prompt: chunked prefill. Claim the slot, park
        # its device seq_len at capacity-1 (batched decode's garbage
        # writes for this row land in the unused last cell), and let
        # _advance_prefill feed chunks between decode steps.
        slot.request = request
        slot.generated = 0
        self._attach_constraint(slot_id, request)
        slot.prefilling = True
        slot.prefill_pos = 0
        self._seq_lens[slot_id] = 0
        self._d_seq_lens = self._d_seq_lens.at[slot_id].set(
            self.slot_capacity - 1
        )
        return False

    # ----------------------------------------------------------- prefix cache

    def _prefer_cp_over(self, use_len: int, n: int) -> bool:
        """On a context-parallel mesh (sp > 1), a long prompt prefills in ONE
        distributed ring-attention pass (~n/sp per chip), while a cache hit
        routes the suffix through sequential single-chip chunks. A small hit
        on a huge prompt would make the request slower than a clean miss —
        only take the hit when the cache covers at least half the prompt."""
        return (
            self._use_cp_prefill
            and hasattr(self.family, "make_context_parallel_prefill")
            and n > (self.prefill_buckets[-1] if self.prefill_buckets else 0)
            and use_len < n // 2
        )

    def _insert_cached(self, slot_id: int, request: Request,
                       entry: PrefixEntry, use_len: int,
                       fresh_pages: list[int] | None = None) -> None:
        """Prefix-cache hit insert, then _advance_prefill chunk-prefills only
        the uncached suffix (prefill_pos starts at use_len).

        Paged mode is ZERO-COPY: the donor's page ids for the matched head go
        straight into this slot's block table with a refcount bump
        (`fresh_pages`, reserved by the caller, cover the suffix) — no device
        dispatch at all. Dense mode copies `use_len` KV rows from the pinned
        donor slot with one device-side dynamic_update_slice per cache.

        The entry stays acquired until activation/cancellation so the donor
        cannot be evicted and reused mid-flight (paged hits hold their own
        page references too, but the acquire keeps eviction accounting
        identical across layouts)."""
        # Claim the slot BEFORE any dispatch (same invariant as the batch
        # path): a failed dispatch then reaches this request through
        # _fail_all — which also releases cache_entry — instead of leaving
        # its event queue silent forever.
        slot = self.slots[slot_id]
        slot.request = request
        slot.generated = 0
        self._attach_constraint(slot_id, request)
        slot.prefilling = True
        slot.prefill_pos = use_len
        slot.cache_entry = entry
        self.prefix_cache.acquire(entry)
        self._seq_lens[slot_id] = 0
        # park device seq_len like any prefilling slot: batched decode's
        # garbage writes land in the unused last cell
        self._d_seq_lens = self._d_seq_lens.at[slot_id].set(
            self.slot_capacity - 1
        )
        if self.page_pool is not None:
            shared = entry.pages[: use_len // self.kv_page_size]
            self._assign_slot_pages(slot_id, shared, fresh_pages or [])
        else:
            rows = 1
            while rows < use_len:
                rows *= 2
            rows = min(rows, self.slot_capacity)
            self.cache_k, self.cache_v = _copy_kv_prefix(
                self.cache_k, self.cache_v,
                jnp.int32(entry.slot), jnp.int32(slot_id), rows,
            )
            self.kv_copy_dispatches += 1
        self.metrics.record_prefix_hit(use_len)
        # the uncached suffix prefills via _advance_prefill (its own
        # prefill_chunk events); this event records the reused head
        self._fr_emit(request, "prefill_chunk", tokens=0,
                      cached_tokens=use_len)

    # ------------------------------------------------------------ constraints

    def _prepare_constraint(self, request: Request) -> None:
        """Ensure a constrained request carries its compiled token-DFA before
        a slot is claimed. The service layer pre-compiles off the step loop;
        this is the fallback for multihost followers (which only receive the
        JSON spec over the plan wire) and direct core submitters. Raises for
        uncompilable specs — the caller turns that into a terminal event.

        Known cost: on a follower a COLD schema compiles here, on the step
        loop, stalling decode for the compile (large vocabularies: seconds).
        The leader stalls identically at its own service-level compile and
        the LRU makes it once-per-schema, so lockstep stays aligned — but a
        multihost fleet serving many distinct cold schemas pays it per
        schema (docs/structured-outputs.md)."""
        if (request.compiled_constraint is None
                and request.sampling.constraint is not None):
            if self.constraint_compiler is None:
                raise ValueError(
                    "request carries a constraint but the engine has no "
                    "constraint compiler"
                )
            request.compiled_constraint = self.constraint_compiler.compile_spec(
                request.sampling.constraint
            )

    def _attach_constraint(self, slot_id: int, request: Request) -> None:
        """Install the per-request FSM cursor and its initial mask stripe at
        slot-claim time (every insert path funnels through here) — plus the
        speculative drafter, which needs exactly the same claim-time hook."""
        self._attach_spec(slot_id, request)
        if request.compiled_constraint is None:
            return
        parked = request.parked
        if parked is not None and parked.constraint is not None:
            # Preemption resume: the FSM cursor parked WITH the request —
            # re-walking a fresh ConstraintState from the start state would
            # mask the continuation as if at the beginning of the string.
            state = parked.constraint
        else:
            state = ConstraintState(request.compiled_constraint)
            self.metrics.record_structured_request()
        slot = self.slots[slot_id]
        slot.constraint = state
        slot.gram_offset = -1
        self._constrained_count += 1
        if self._grammar_tables is not None:
            # Fused decode: make the schema device-resident so this slot's
            # masks and cursor advances run in-program. Registration is
            # per-schema (idempotent); failure = table budget exceeded —
            # the slot then keeps the legacy host-mask path, and every
            # already-registered slot regrows a host row so a mixed batch's
            # legacy fallback masks ALL constrained rows.
            off = self._grammar_tables.register(request.compiled_constraint)
            if off is not None:
                slot.gram_offset = off
            elif not self._grammar_fallback:
                self._grammar_fallback = True
                if not self._grammar_warned:
                    self._grammar_warned = True
                    log.warning(
                        "grammar table budget exceeded "
                        "(LLMLB_GRAMMAR_TABLE_MB=%d MiB); constrained "
                        "decoding falls back to the host-mask path",
                        self._grammar_tables.budget_bytes >> 20,
                    )
                for j, s in enumerate(self.slots):
                    if s.constraint is not None and j != slot_id:
                        self._set_mask_row(j, s.constraint, force=True)
        self._set_mask_row(slot_id, state)

    def _set_mask_row(self, slot_id: int, state: ConstraintState, *,
                      force: bool = False) -> None:
        if (not force and self.fused_decode and not self._grammar_fallback
                and self.slots[slot_id].gram_offset >= 0):
            # device-resident schema: the fused program derives this row
            # from the grammar table in-program — no host mirror to keep
            return
        if self._mask_bias is None:
            self._mask_bias = np.zeros(
                (self.num_slots, self.cfg.vocab_size), np.float32
            )
        self._mask_bias[slot_id] = state.bias_row()
        self._mask_dirty_rows.add(slot_id)

    def _clear_constraint(self, slot_id: int) -> None:
        slot = self.slots[slot_id]
        slot.gram_offset = -1
        if slot.constraint is None:
            return
        slot.constraint = None
        self._constrained_count -= 1
        if self._mask_bias is not None:
            self._mask_bias[slot_id] = 0.0
            self._mask_dirty_rows.add(slot_id)

    def _sync_mask(self) -> jnp.ndarray:
        """Device mirror of the mask, refreshed per DIRTY ROW (same
        small-H2D contract as the paged block tables — an FSM advance
        touches one row, so only that row ships)."""
        if self._d_mask is None:
            self._d_mask = jnp.asarray(self._mask_bias)
            self._mask_dirty_rows.clear()
        elif self._mask_dirty_rows:
            rows = sorted(self._mask_dirty_rows)
            self._d_mask = self._d_mask.at[jnp.asarray(rows, jnp.int32)].set(
                jnp.asarray(self._mask_bias[rows])
            )
            self._mask_dirty_rows.clear()
        return self._d_mask

    # ---------------------------------------------------- speculative decode

    def _attach_spec(self, slot_id: int, request: Request) -> None:
        """Install the per-request prompt-lookup drafter at slot-claim time.
        Per-request `speculative` knobs override the engine default; the
        draft budget clamps into the engine verify width so the chunk shape
        (and therefore the jit cache) never varies per request."""
        slot = self.slots[slot_id]
        slot.drafter = None
        slot.spec_k = 0
        if not self._spec_available:
            return
        parked = request.parked
        if parked is not None and parked.drafter is not None:
            # resume the parked index: it already holds prompt + emitted
            # tokens, exactly what a rebuild over the committed sequence
            # would produce
            slot.drafter = parked.drafter
            slot.spec_k = parked.spec_k
            return
        knobs = request.sampling.speculative
        knobs = knobs if isinstance(knobs, dict) else {}
        enabled = bool(knobs.get("enabled", self.spec.enabled))
        if not enabled:
            return
        try:
            k = int(knobs.get("max_draft_tokens")
                    or self.spec.max_draft_tokens)
        except (TypeError, ValueError):
            k = self.spec.max_draft_tokens
        slot.spec_k = max(1, min(k, self.spec.max_draft_tokens))
        slot.drafter = PromptLookupDrafter(
            request.prompt_ids,
            max_ngram=self.spec.max_ngram, min_ngram=self.spec.min_ngram,
        )

    def _fused_step_ok(self, active: list[int]) -> bool:
        """True when this step can run as ONE fused device program: fused
        mode on and every active constrained slot's schema device-resident
        (a slot whose schema missed the grammar-table budget drags the
        whole step back to the legacy multi-dispatch path — correctness
        over dispatch count)."""
        if not self.fused_decode:
            return False
        return all(
            self.slots[i].constraint is None
            or self.slots[i].gram_offset >= 0
            for i in active
        )

    def _collect_drafts(
        self, active: list[int], fused: bool = False
    ) -> tuple[dict[int, list[int]], dict[int, list[int]]]:
        """Per-slot draft proposals for this step (empty for slots that are
        not speculating, have no n-gram match, or no room to speculate), plus
        each constrained slot's FSM-state path along its kept drafts — the
        lookahead that builds the per-position verify masks. With `fused`
        the host pre-walk is skipped entirely: the fused verify program
        derives every mask column from the device grammar table, and a
        disallowed draft simply fails its (masked) acceptance comparison at
        the same position the truncation would have cut."""
        drafts: dict[int, list[int]] = {}
        lookahead: dict[int, list[int]] = {}
        for i in active:
            slot = self.slots[i]
            d: list[int] = []
            # first_pending slots' last token is still device-only, so the
            # drafter has not seen it — their proposal would continue the
            # wrong suffix; they join the verify batch with a plain 1-token
            # chunk and speculate from the next step.
            if slot.drafter is not None and not slot.first_pending:
                request = slot.request
                room = self.slot_capacity - 2 - int(self._seq_lens[i])
                budget = request.sampling.max_tokens - slot.generated - 1
                k = min(slot.spec_k, room, budget)
                if k > 0:
                    d = slot.drafter.propose(k)
                if d and slot.constraint is not None and not fused:
                    d, states = self._constrained_draft_prefix(
                        slot.constraint, d
                    )
                    lookahead[i] = states
            drafts[i] = d
        return drafts, lookahead

    @staticmethod
    def _constrained_draft_prefix(
        state: ConstraintState, drafts: list[int]
    ) -> tuple[list[int], list[int]]:
        """Truncate a draft proposal at the first token the grammar FSM
        disallows, walking a lookahead copy of the cursor (the live cursor
        only advances on EMITTED tokens, in _emit). Returns (kept drafts,
        FSM states after each kept draft, starting with the current state).
        EOS never drafts: acceptance-to-stop is the model's call."""
        tc = state.tc
        s = state.state
        kept: list[int] = []
        states = [s]
        if state.violated:
            return kept, states
        for t in drafts:
            if (t == tc.eos_id or not 0 <= t < tc.allowed.shape[1]
                    or not tc.allowed[s, t]):
                break
            nxt = tc.advance(s, t)
            if nxt is None:
                break
            kept.append(t)
            s = nxt
            states.append(s)
        return kept, states

    def _trim_slot_pages(self, slot_id: int, keep_tokens: int) -> None:
        """Rejected-draft rollback: release the trailing pages a verify
        dispatch allocated beyond what the accepted length needs (kept:
        enough to cover keep_tokens). Trailing pages are always this slot's
        own fresh allocations — shared prefix pages sit at the front of the
        row and committed length never rolls back below the prompt — so one
        unref per page is exactly right and the pool's double-free guard
        stays armed."""
        if self.page_pool is None:
            return
        keep = self._pages_for_tokens(keep_tokens)
        row = self._slot_pages[slot_id]
        if len(row) <= keep:
            return
        for p in row[keep:]:
            self.page_pool.unref(p)
        del row[keep:]
        self._block_tables[slot_id, keep:] = 0
        self._tables_dirty = True

    def _build_verify(self, window: int) -> Callable:
        """Jit one fused verify dispatch for a context-window bucket: the
        K+1-token extend (family verify step) plus per-position sampling —
        one device program, one host readback per verify step. Returns
        [B, K+2] tokens: column 0 echoes the input last-token column (the
        deferred-first-emission ride-along, same contract as decode's
        first_in row), columns 1.. are the model's samples per position."""
        family, cfg, mesh = self.family, self.cfg, self.mesh

        if self.page_pool is not None:
            def run(params, ids, chunk_lens, start_pos, tables,
                    cache_k, cache_v, temps, top_ps, top_ks, seeds, mask,
                    key, lora_idx=None):
                logits, cache_k, cache_v = family.verify_step_paged(
                    params, cfg, ids, chunk_lens, start_pos, tables,
                    cache_k, cache_v, mesh, window=window,
                    lora_idx=lora_idx,
                )
                toks = _sample_chunk(logits, key, temps, top_ps, top_ks,
                                     seeds, mask, start_pos)
                return (jnp.concatenate([ids[:, :1], toks], axis=1),
                        cache_k, cache_v)

            return jax.jit(run, donate_argnums=(5, 6))

        def run(params, ids, chunk_lens, start_pos,
                cache_k, cache_v, temps, top_ps, top_ks, seeds, mask, key,
                lora_idx=None):
            slot_ids = jnp.arange(ids.shape[0], dtype=jnp.int32)
            logits, cache_k, cache_v = family.verify_step(
                params, cfg, ids, chunk_lens, start_pos, slot_ids,
                cache_k, cache_v, mesh, window=window, lora_idx=lora_idx,
            )
            toks = _sample_chunk(logits, key, temps, top_ps, top_ks,
                                 seeds, mask, start_pos)
            return (jnp.concatenate([ids[:, :1], toks], axis=1),
                    cache_k, cache_v)

        return jax.jit(run, donate_argnums=(4, 5))

    def _verify_for(self, window: int) -> Callable:
        with self._decode_many_lock:
            fn = self._verify_fns.get(window)
            if fn is None:
                fn = self._build_verify(window)
                self._verify_fns[window] = fn
            return fn

    def _build_verify_fused(self, window: int, grammar: bool) -> Callable:
        """Jit the FUSED verify step: everything the legacy verify path did
        across several device programs — last-token splice into column 0,
        per-position grammar masks (device transition-table walk instead of
        the host FSM lookahead), the K+1-token extend, per-position
        sampling, accept counting, and the seq-len/last-token advance —
        compiled into ONE dispatch. Output tokens are [B, K+3]: column 0
        echoes the input last token, columns 1..K+1 the samples, and the
        final column the in-program accepted-prefix count per row."""
        family, cfg, mesh = self.family, self.cfg, self.mesh
        k1 = self.spec.max_draft_tokens + 1

        def gram_mask(gram_table, gram_state, ids):
            # Column j's mask is the grammar state after consuming drafts
            # 1..j — the device analogue of the host pre-walk. A disallowed
            # draft clamps (grammar_advance), replicating the last live
            # state's row exactly like the legacy stripe padding; its
            # sample can then never equal the draft, so acceptance stops
            # at the same position the host truncation would have cut.
            s = gram_state
            biases = [grammar_bias(gram_table, s)]
            for j in range(1, k1):
                s = grammar_advance(gram_table, s, ids[:, j])
                biases.append(grammar_bias(gram_table, s))
            return jnp.stack(biases, axis=1).reshape(
                ids.shape[0] * k1, -1
            )

        def finish(ids, toks, chunk_lens, start_pos, lens, last_tokens,
                   active_mask):
            # accepted = longest prefix of drafts matching the model's own
            # samples — the same comparison the host emit loop walks
            # (tokens[i, 1+j] == d[j]), vectorized as a cumprod
            b = ids.shape[0]
            cols = jnp.arange(1, k1, dtype=jnp.int32)[None, :]
            matches = ((toks[:, :-1] == ids[:, 1:])
                       & (cols < chunk_lens[:, None]))
            accepted = jnp.sum(
                jnp.cumprod(matches.astype(jnp.int32), axis=1), axis=1
            ).astype(jnp.int32)
            # Active rows advance by accepted + 1 (the correction/bonus
            # sample); every other row — prefilling slots parked at
            # capacity-1, free slots — must keep its lens/last untouched,
            # which the host-side scatter got for free by only writing
            # surviving rows.
            new_lens = jnp.where(active_mask,
                                 start_pos + accepted + 1, lens)
            new_last = jnp.where(
                active_mask,
                toks[jnp.arange(b, dtype=jnp.int32), accepted],
                last_tokens,
            )
            out = jnp.concatenate(
                [ids[:, :1], toks, accepted[:, None]], axis=1
            )
            return out, new_last, new_lens

        if self.page_pool is not None:
            def run(params, ids, chunk_lens, start_pos, tables,
                    cache_k, cache_v, temps, top_ps, top_ks, seeds, key,
                    last_tokens, active_mask, lens,
                    gram_table=None, gram_state=None, lora_idx=None):
                ids = ids.at[:, 0].set(last_tokens)
                mask = (gram_mask(gram_table, gram_state, ids)
                        if grammar else None)
                logits, cache_k, cache_v = family.verify_step_paged(
                    params, cfg, ids, chunk_lens, start_pos, tables,
                    cache_k, cache_v, mesh, window=window,
                    lora_idx=lora_idx,
                )
                toks = _sample_chunk(logits, key, temps, top_ps, top_ks,
                                     seeds, mask, start_pos)
                out, new_last, new_lens = finish(
                    ids, toks, chunk_lens, start_pos, lens, last_tokens,
                    active_mask,
                )
                return out, new_last, new_lens, cache_k, cache_v

            return jax.jit(run, donate_argnums=(5, 6))

        def run(params, ids, chunk_lens, start_pos,
                cache_k, cache_v, temps, top_ps, top_ks, seeds, key,
                last_tokens, active_mask, lens,
                gram_table=None, gram_state=None, lora_idx=None):
            ids = ids.at[:, 0].set(last_tokens)
            mask = (gram_mask(gram_table, gram_state, ids)
                    if grammar else None)
            slot_ids = jnp.arange(ids.shape[0], dtype=jnp.int32)
            logits, cache_k, cache_v = family.verify_step(
                params, cfg, ids, chunk_lens, start_pos, slot_ids,
                cache_k, cache_v, mesh, window=window, lora_idx=lora_idx,
            )
            toks = _sample_chunk(logits, key, temps, top_ps, top_ks,
                                 seeds, mask, start_pos)
            out, new_last, new_lens = finish(
                ids, toks, chunk_lens, start_pos, lens, last_tokens,
                active_mask,
            )
            return out, new_last, new_lens, cache_k, cache_v

        return jax.jit(run, donate_argnums=(4, 5))

    def _verify_fused_for(self, window: int, grammar: bool) -> Callable:
        with self._decode_many_lock:
            key = (window, grammar)
            fn = self._verify_fused.get(key)
            if fn is None:
                fn = self._shared_program(
                    "verify_fused",
                    (self.spec.max_draft_tokens, window, grammar),
                    lambda: self._build_verify_fused(window, grammar))
                self._verify_fused[key] = fn
            return fn

    def _verify_active(self, active: list[int], drafts: dict[int, list[int]],
                       lookahead: dict[int, list[int]],
                       draft_s: float, fused: bool = False) -> bool:
        """One speculative verify step: dispatch every active slot's last
        token + drafts as a K+1-token chunk through the extend path, sample
        every position, accept the longest prefix of drafts matching the
        model's own samples, emit accepted + 1 tokens per slot, roll back
        rejected-suffix state (committed length + over-allocated pages).
        With `fused` the whole step is ONE device program (mask columns,
        last-token splice, accept counts, and the lens/last advance all
        in-program); the host emit loop is unchanged either way."""
        k1 = self.spec.max_draft_tokens + 1
        step_start = time.monotonic()
        t_sync = time.perf_counter()
        if self.page_pool is not None:
            per_row = {i: len(drafts.get(i, ())) + 1 for i in active}
            active = self._ensure_decode_pages(active, 1, per_row)
            if not active:
                self.metrics.set_batch_occupancy(0)
                return True
            self._sync_block_tables()

        # Chunk arrays: active rows carry [last, d1..dm]; every other row
        # (prefilling/parked/free) degenerates to a 1-token chunk writing
        # garbage at its clamped last cell / trash page — exactly decode's
        # garbage contract for non-active rows.
        b = self.num_slots
        ids = np.zeros((b, k1), np.int32)
        chunk_lens = np.ones((b,), np.int32)
        start_pos = np.full((b,), self.slot_capacity - 1, np.int32)
        for i in active:
            d = drafts.get(i, ())
            ids[i, 1:1 + len(d)] = d
            chunk_lens[i] = 1 + len(d)
            start_pos[i] = self._seq_lens[i]

        masked = [i for i in active if self.slots[i].constraint is not None]
        mask = None
        dispatches = 0
        if fused:
            # Fused step: no host mask stripes, no persistent spec-mask
            # buffer — the program derives every mask column from the
            # device grammar table. Host ships only the per-row grammar
            # cursors (offset + FSM state) and the active-row mask.
            grammar = bool(masked)
            gs = np.zeros((b,), np.int32)
            act = np.zeros((b,), bool)
            for i in active:
                act[i] = True
                state = self.slots[i].constraint
                if state is not None:
                    gs[i] = self.slots[i].gram_offset + state.state
            if grammar:
                self.metrics.record_masked_decode_step()
        else:
            # Per-position grammar masks: column 0 is the live cursor's
            # row, later columns the FSM lookahead along the
            # (pre-validated) drafts. Only rows masked this step or last
            # (stale rows zero out) are built host-side and scattered into
            # the persistent device buffer.
            if masked or self._spec_masked_prev:
                rows_upd = sorted(set(masked) | self._spec_masked_prev)
                v = self.cfg.vocab_size
                if self._d_spec_mask is None:
                    self._d_spec_mask = jnp.zeros((b, k1, v), jnp.float32)
                stripes = np.zeros((len(rows_upd), k1, v), np.float32)
                for n, i in enumerate(rows_upd):
                    state = self.slots[i].constraint
                    if state is None:
                        continue  # left the masked set: zero stripe clears
                    stripes[n, 0] = state.bias_row()
                    states = lookahead.get(i, [state.state])
                    for j, s in enumerate(states[1:], start=1):
                        # tc.bias_row handles dead-end states with the same
                        # EOS-only fallback as the live cursor
                        stripes[n, j] = state.tc.bias_row(s)
                    for j in range(max(1, len(states)), k1):
                        stripes[n, j] = stripes[n, len(states) - 1]
                self._d_spec_mask = self._d_spec_mask.at[
                    jnp.asarray(rows_upd, jnp.int32)
                ].set(jnp.asarray(stripes))
                self._spec_masked_prev = set(masked)
                dispatches += 1  # the stripe scatter
            if masked:
                mask = self._d_spec_mask.reshape(b * k1, -1)
                self.metrics.record_masked_decode_step()
        sync_s = time.perf_counter() - t_sync

        self._key, sk = jax.random.split(self._key)
        window = self._window_for(active, k1)
        t_dispatch = time.perf_counter()
        lora_idx = self._d_lora_idx if self.lora is not None else None
        if fused:
            fn = self._verify_fused_for(window, grammar)
            gram_args = ({"gram_table": self._grammar_tables.device(),
                          "gram_state": jnp.asarray(gs)} if grammar else {})
            # jnp.asarray is an H2D transfer, not a device program; the
            # column-0 last-token splice happens in-program
            if self.page_pool is not None:
                (toks_dev, new_last, new_lens,
                 self.cache_k, self.cache_v) = fn(
                    self.params, jnp.asarray(ids), jnp.asarray(chunk_lens),
                    jnp.asarray(start_pos), self._d_block_tables,
                    self.cache_k, self.cache_v,
                    self._d_temps, self._d_top_ps, self._d_top_ks,
                    self._d_seeds, sk, self._d_last_tokens,
                    jnp.asarray(act), self._d_seq_lens,
                    lora_idx=lora_idx, **gram_args,
                )
            else:
                (toks_dev, new_last, new_lens,
                 self.cache_k, self.cache_v) = fn(
                    self.params, jnp.asarray(ids), jnp.asarray(chunk_lens),
                    jnp.asarray(start_pos),
                    self.cache_k, self.cache_v,
                    self._d_temps, self._d_top_ps, self._d_top_ks,
                    self._d_seeds, sk, self._d_last_tokens,
                    jnp.asarray(act), self._d_seq_lens,
                    lora_idx=lora_idx, **gram_args,
                )
            self._d_last_tokens = new_last
            self._d_seq_lens = new_lens
            dispatches = 1
        else:
            # column 0 is the on-device last token per row — newly
            # activated slots' first tokens never round-tripped through
            # the host
            ids_dev = jnp.asarray(ids).at[:, 0].set(self._d_last_tokens)
            fn = self._verify_for(window)
            if self.page_pool is not None:
                toks_dev, self.cache_k, self.cache_v = fn(
                    self.params, ids_dev, jnp.asarray(chunk_lens),
                    jnp.asarray(start_pos), self._d_block_tables,
                    self.cache_k, self.cache_v,
                    self._d_temps, self._d_top_ps, self._d_top_ks,
                    self._d_seeds, mask, sk, lora_idx=lora_idx,
                )
            else:
                toks_dev, self.cache_k, self.cache_v = fn(
                    self.params, ids_dev, jnp.asarray(chunk_lens),
                    jnp.asarray(start_pos),
                    self.cache_k, self.cache_v,
                    self._d_temps, self._d_top_ps, self._d_top_ks,
                    self._d_seeds, mask, sk, lora_idx=lora_idx,
                )
            dispatches += 2  # the ids splice + the verify program
        t_compute = time.perf_counter()
        jax.block_until_ready(toks_dev)
        t_fetch = time.perf_counter()
        tokens = self._fetch_tokens(toks_dev)  # [B, K+2]: input col + samples
        t_emit = time.perf_counter()
        step_s = time.monotonic() - step_start

        drafted = sum(len(drafts.get(i, ())) for i in active)
        accepted_total = 0
        emitted_total = 0  # every token delivered (all slots; MFU/throughput)
        spec_emitted = 0  # tokens from SPECULATING slots (accepted + 1 each)
        rows: list[int] = []
        new_lens: list[int] = []
        new_lasts: list[int] = []
        # (request_id, drafted, accepted) per speculating slot — the slot's
        # request may finish inside the emit loop, so capture the id up front
        spec_accepts: list[tuple[str, int, int]] = []
        for i in active:
            slot = self.slots[i]
            if slot.first_pending and slot.request is not None:
                slot.first_pending = False
                self._emit(i, int(tokens[i, 0]), first=True)
            if slot.request is None or slot.prefilling:
                continue
            rid_i = slot.request.request_id
            d = drafts.get(i, [])
            # expected emission span (matches until first mismatch, +1 for
            # the correction/bonus sample) — the amortized per-token pacing
            # for this slot's ITL before finish conditions can trim it
            span = 1
            for j, dj in enumerate(d):
                if int(tokens[i, 1 + j]) == dj and dj != self.eos_id:
                    span += 1
                else:
                    break
            itl = step_s / span
            j = 0
            emitted_i = 0
            while True:
                tok = int(tokens[i, 1 + j])
                self._seq_lens[i] += 1
                emitted_i += 1
                matched = j < len(d) and tok == d[j]
                self._emit(i, tok, itl=itl)
                if matched:
                    j += 1
                if slot.request is None or not matched:
                    break
            accepted_total += j
            emitted_total += emitted_i
            if d:
                spec_emitted += emitted_i
                spec_accepts.append((rid_i, len(d), j))
            if slot.request is not None and not slot.prefilling:
                rows.append(i)
                new_lens.append(int(self._seq_lens[i]))
                # the last emitted sample is the next dispatch's input token
                new_lasts.append(int(tokens[i, emitted_i]))
                # rejected-suffix rollback: keep pages covering the
                # committed length + the next token's write, release the rest
                self._trim_slot_pages(i, int(self._seq_lens[i]) + 1)
        if rows and not fused:
            # fused: the program already advanced lens/last in-program for
            # active rows (bit-equal to these host-computed values for
            # every surviving slot; freed slots' device rows are garbage
            # under the same free-slot contract as the legacy skip)
            idx = jnp.asarray(rows, jnp.int32)
            self._d_seq_lens = self._d_seq_lens.at[idx].set(
                jnp.asarray(new_lens, jnp.int32)
            )
            self._d_last_tokens = self._d_last_tokens.at[idx].set(
                jnp.asarray(new_lasts, jnp.int32)
            )
            dispatches += 2  # the two post-emit scatters

        mean_span = emitted_total / max(1, len(active))
        self.metrics.record_decode_step(step_s / max(1.0, mean_span),
                                        len(active))
        self.metrics.record_spec_step(drafted, accepted_total, spec_emitted)
        if self.flightrec.enabled:
            for rid_i, n_drafted, n_accepted in spec_accepts:
                self.flightrec.emit(rid_i, "spec_accept",
                                    drafted=n_drafted, accepted=n_accepted)
        self._record_step(
            "verify",
            {"draft": draft_s,
             "host_sync": sync_s,
             "dispatch": t_compute - t_dispatch,
             "compute": t_fetch - t_compute,
             "fetch": t_emit - t_fetch,
             "emit": time.perf_counter() - t_emit},
            active_slots=len(active), tokens=emitted_total,
            slots=active, dispatches=dispatches, fused=fused,
        )
        return True

    def spec_info(self) -> dict:
        """Speculative-decoding block for /api/system, /api/health, and
        /metrics consumers: config + live acceptance figures."""
        m = self.metrics
        drafted = m.spec_draft_tokens_total
        return {
            "enabled": self.spec.enabled,
            "available": self._spec_available,
            "max_draft_tokens": self.spec.max_draft_tokens,
            "ngram": [self.spec.min_ngram, self.spec.max_ngram],
            "verify_steps_total": m.spec_verify_steps_total,
            "draft_tokens_total": drafted,
            "accepted_tokens_total": m.spec_accepted_tokens_total,
            "emitted_tokens_total": m.spec_emitted_tokens_total,
            "acceptance_rate": (
                round(m.spec_accepted_tokens_total / drafted, 4)
                if drafted else None
            ),
        }

    def lora_info(self) -> dict:
        """Multi-LoRA block for /api/system, /api/health, and /metrics
        consumers: pool config + live residency/eviction figures
        (docs/lora.md)."""
        if self.lora is None:
            return {"enabled": False}
        info = self.lora.info()
        # CP-mesh prefill fallbacks (docs/lora.md): LoRA prompts that paid
        # single-chip chunked prefill because the ring-attention pass
        # carries no adapter indices — surfaced here so /api/system shows
        # the same figure the counter exports.
        info["cp_fallback_total"] = self.metrics.lora_cp_fallback_total
        return info

    def _release_cache_entry(self, slot: _Slot) -> None:
        if slot.cache_entry is not None:
            if self.prefix_cache is not None:
                self.prefix_cache.release(slot.cache_entry)
            slot.cache_entry = None

    def _release_entry_pages(self, entry: PrefixEntry) -> None:
        """Drop the prefix cache's page references of a removed entry."""
        if self.page_pool is not None and entry.pages:
            for p in entry.pages:
                self.page_pool.unref(p)
            self._prefix_pinned_pages -= len(entry.pages)

    def _evict_one_prefix(self) -> bool:
        entry = self.prefix_cache.evict_lru_entry()
        if entry is None:
            return False  # every donor has an in-flight reader
        # page-pressure demotion, not destruction: the cold prefix moves to
        # the host-RAM tier (when enabled) before its pages free
        self._spill_prefix_entry(entry)
        self._release_entry_pages(entry)
        self.metrics.record_prefix_eviction()
        return True

    def _maybe_cache_prefix(self, slot_id: int, request: Request) -> None:
        """On request completion: donate this request's prompt KV when the
        aligned head is long enough and not already covered. Dense mode pins
        the whole slot (it leaves the serving pool until eviction); paged
        mode pins only the PAGES covering the head — the slot itself frees
        immediately, which is the occupancy win of the paged layout."""
        cache = self.prefix_cache
        n = len(request.prompt_ids)
        length = (n // cache.align) * cache.align
        if length < cache.min_len:
            return
        tokens = tuple(request.prompt_ids[:length])
        # Donations are namespaced by adapter id like matches: two adapters
        # sharing a prompt text donate to DISJOINT trees (docs/lora.md).
        ns = request.sampling.lora
        if cache.covers(tokens, ns):
            cache.touch(tokens, ns)  # a re-served prefix is a use: refresh LRU
            return
        # A longer prefix subsumes its ancestors (any match they could serve
        # routes through this entry's subtree) — reclaim their donor slots
        # first, or each turn of a growing conversation pins a fresh slot.
        # NOT counted as evictions: coverage is preserved, and on healthy
        # multi-turn traffic this fires once per turn — charging it to
        # evictions_total would make the donor-churn signal operators alert
        # on track plain insertion rate.
        for stale in cache.evict_subsumed_entries(tokens, ns):
            self._release_entry_pages(stale)
        if len(cache) >= cache.max_entries and not self._evict_one_prefix():
            return
        if self.page_pool is not None:
            pages = tuple(
                self._slot_pages[slot_id][: length // self.kv_page_size]
            )
            if not pages:
                return
            if cache.insert(tokens, -1, pages=pages, ns=ns) is not None:
                for p in pages:  # the cache is now a co-owner of the head
                    self.page_pool.ref(p)
                self._prefix_pinned_pages += len(pages)
                self.metrics.record_prefix_insert(length)
            return
        if cache.insert(tokens, slot_id, ns=ns) is not None:
            self.metrics.record_prefix_insert(length)

    def prefix_cache_info(self) -> dict:
        """One JSON-safe block for /api/health, /api/system, and /metrics."""
        if self.prefix_cache is None:
            return {"enabled": False}
        pinned = len(self.prefix_cache)
        info = {
            "enabled": True,
            "entries": pinned,
            "budget_slots": self.prefix_cache.max_entries,
            "cached_tokens": self.prefix_cache.cached_tokens(),
            "min_prefix_len": self.min_prefix_len,
            "align": self.prefix_align,
        }
        if self.page_pool is not None:
            # zero-copy donors pin pages, never slots; HBM held is per page
            info["pinned_slots"] = 0
            info["pinned_pages"] = self._prefix_pinned_pages
            info["pinned_hbm_bytes"] = (
                self._prefix_pinned_pages
                * kv_page_bytes(self.cfg, self.kv_page_size,
                                quantized=self.quant.kv)
            )
        else:
            # a pinned donor holds its whole slot row out of the serving pool
            info["pinned_slots"] = pinned
            info["pinned_hbm_bytes"] = (
                pinned * kv_cache_bytes(self.cfg, 1, self.slot_capacity)
            )
        return info

    def structured_info(self) -> dict:
        """Structured-output block for /api/system, /api/health, /metrics:
        the constraint compiler's mask-cache figures plus live load."""
        if self.constraint_compiler is None:
            return {"enabled": False}
        info = self.constraint_compiler.info()
        info["active_constrained_slots"] = self._constrained_count
        return info

    def kv_cache_info(self) -> dict:
        """KV memory block for /api/system, /api/health, and /metrics: the
        dense footprint, or live page-pool utilization when paged. Gauge
        reads are approximate under concurrent step-loop mutation (same
        stance as every other scrape-time figure)."""
        if self.page_pool is None:
            return {
                "layout": "dense",
                "kv_dtype": str(jnp.dtype(self.cfg.dtype)),
                # what the cache ACTUALLY serves: --quantize kv on the dense
                # layout downgrades to bf16 with only a boot-time log line,
                # so dashboards must read the effective dtype, not the
                # requested knob (the HBM math differs 2x)
                "effective_kv_dtype": str(jnp.dtype(self.cfg.dtype)),
                "num_slots": self.num_slots,
                "slot_capacity": self.slot_capacity,
                "hbm_bytes": kv_cache_bytes(self.cfg, self.num_slots,
                                            self.slot_capacity),
            }
        pool = self.page_pool
        active = 0
        active_pages = 0
        waste = 0
        for i, s in enumerate(self.slots):
            if s.request is None:
                continue
            active += 1
            held = len(self._slot_pages[i])
            active_pages += held
            used = s.prefill_pos if s.prefilling else int(self._seq_lens[i])
            waste += max(0, held * self.kv_page_size - used)
        return {
            "layout": "paged",
            # derived from the ACTUAL pool dtype — implied-bf16 accounting
            # would be 2x wrong under int8 (the gauges below feed capacity
            # planning and the Grafana KV panels)
            "kv_dtype": ("int8" if self.quant.kv
                         else str(jnp.dtype(self.cfg.dtype))),
            "effective_kv_dtype": ("int8" if self.quant.kv
                                   else str(jnp.dtype(self.cfg.dtype))),
            "page_size": self.kv_page_size,
            "num_slots": self.num_slots,
            "slot_capacity": self.slot_capacity,
            "pages_total": pool.total,
            "pages_free": pool.available(),
            "pages_active": active_pages,
            "pages_pinned": self._prefix_pinned_pages,
            "utilization": round(pool.used() / max(1, pool.total), 4),
            # allocated-but-unfilled cells of occupied rows: the internal
            # fragmentation the --kv-page-size knob trades against
            "fragmentation": round(
                waste / max(1, active_pages * self.kv_page_size), 4
            ),
            "waste_tokens_mean": (round(waste / active, 1) if active else 0.0),
            "bytes_per_page": kv_page_bytes(self.cfg, self.kv_page_size,
                                            quantized=self.quant.kv),
            "hbm_bytes": kv_pool_bytes(self.cfg, self.kv_num_pages,
                                       self.kv_page_size,
                                       quantized=self.quant.kv),
        }

    def quant_info(self) -> dict:
        """Quantization block for /api/system, /api/health, and /metrics:
        the resolved knobs plus the honest byte footprints they produce."""
        itemsize = jnp.dtype(self.cfg.dtype).itemsize
        return {
            "mode": self.quant.mode,
            "weights_int8": self.quant.weights,
            "kv_int8": self.quant.kv,
            # the dtype the KV cache actually stores, post any silent
            # layout downgrade (--kv-layout dense + --quantize kv serves
            # bf16): self.quant.kv is already False in that case, so this
            # reads the same source of truth as the pool allocation
            "effective_kv_dtype": ("int8" if self.quant.kv
                                   else str(jnp.dtype(self.cfg.dtype))),
            "param_bytes": self.param_bytes,
            "param_bytes_bf16": self.n_params * itemsize,
            "kv_cell_bytes": kv_cell_bytes(self.cfg.head_dim_,
                                           self.quant.kv, itemsize),
        }

    def perf_info(self) -> dict:
        """Live roofline block for /api/system and /metrics: model-derived
        static FLOPs/bytes per token divided by measured busy-time
        throughput against the chip's peak specs (engine/telemetry.py
        CHIP_SPECS, keyed off device_kind). `available` is False on chips
        outside the table (CPU included) or before any decode traffic —
        the gauges are then absent, never wrong."""
        from llmlb_tpu.engine.telemetry import (
            chip_spec_for,
            model_bytes_per_token,
            model_flops_per_token,
        )

        devices = jax.local_devices()
        kind = (getattr(devices[0], "device_kind", "unknown")
                if devices else "none")
        n_chips = max(1, len(devices))
        spec = chip_spec_for(kind)
        busy_s, toks = self.step_stats.window_throughput()
        tok_per_s = toks / busy_s if busy_s > 0 else 0.0
        # mean live context + batch across active decode slots; the window
        # figures already average over recent steps, so a point-in-time
        # read of the live state is the matching granularity
        contexts = [
            int(self._seq_lens[i]) for i, s in enumerate(self.slots)
            if s.request is not None and not s.prefilling
        ]
        mean_ctx = (sum(contexts) / len(contexts)) if contexts else 0.0
        batch = max(1, len(contexts))
        itemsize = jnp.dtype(self.cfg.dtype).itemsize
        flops_tok = model_flops_per_token(self.cfg, self.n_params)
        # quantization-honest byte accounting: the measured param footprint
        # (int8 values + f32 scales when weights quantize) and the actual
        # KV cell size (D·1 + 4-byte scale under int8 KV) — the implied
        # bf16 math would double-count HBM traffic quantization removed
        bytes_tok = model_bytes_per_token(
            self.cfg, self.n_params, mean_ctx, batch=batch,
            weight_bytes=self.param_bytes,
            kv_cell_bytes=kv_cell_bytes(self.cfg.head_dim_, self.quant.kv,
                                        itemsize),
        )
        info = {
            "device_kind": str(kind),
            "n_chips": n_chips,
            "n_params": self.n_params,
            "quantize": self.quant.mode,
            "flops_per_token": flops_tok,
            "bytes_per_token": round(bytes_tok, 1),
            "mean_context_tokens": round(mean_ctx, 1),
            "window_tokens": toks,
            "window_busy_s": round(busy_s, 4),
            "tokens_per_sec_busy": round(tok_per_s, 2),
            "available": spec is not None and tok_per_s > 0,
        }
        if spec is not None:
            info["chip"] = {
                "generation": spec.generation,
                "peak_flops": spec.peak_flops,
                "peak_flops_int8": spec.int8_flops,
                "peak_hbm_bw": spec.peak_hbm_bw,
            }
        if info["available"]:
            per_chip = tok_per_s / n_chips
            # int8-weight engines are judged against the chip's int8 OPS
            # column — quantized matmuls move int8 operands through the MXU,
            # and dividing by the bf16 peak would overstate MFU ~2x on
            # chips with an int8 fast path
            peak = spec.int8_flops if self.quant.weights else spec.peak_flops
            info["mfu"] = round(flops_tok * per_chip / peak, 6)
            info["hbm_bw_utilization"] = round(
                bytes_tok * per_chip / spec.peak_hbm_bw, 6
            )
        return info

    def _prefill_group(self, bucket: int,
                       group: list[tuple[int, Request, int]]) -> None:
        """Prefill G same-bucket prompts in one dispatch, padded to the next
        power of two by repeating the last row — duplicate scatters write
        identical data to the same slot, so padding rows are free."""
        g = len(group)
        padded = 1
        while padded < g:
            padded *= 2
        ids = np.zeros((padded, bucket), np.int32)
        lens = np.zeros((padded,), np.int32)
        slot_ids = np.zeros((padded,), np.int32)
        for row, (slot_id, request, n) in enumerate(group):
            ids[row, :n] = self._effective_prompt(request)
            lens[row] = n
            slot_ids[row] = slot_id
        ids[g:] = ids[g - 1]
        lens[g:] = lens[g - 1]
        slot_ids[g:] = slot_ids[g - 1]
        # Per-row adapter indices (docs/lora.md): a mixed-adapter group
        # prefills in this ONE dispatch — the bgmv delta gathers each row's
        # factors by index, no per-adapter sub-batching. Padding rows repeat
        # the last real row like everything else.
        lora_idx = None
        if self.lora is not None:
            lidx = np.zeros((padded,), np.int32)
            lidx[:g] = self._lora_rows([r for _, r, _ in group])
            lidx[g:] = lidx[g - 1]
            lora_idx = jnp.asarray(lidx)

        prefill_start = time.monotonic()
        self._note_prefill_dispatch()
        t_dispatch = time.perf_counter()
        if self.page_pool is not None:
            # padding rows repeat the last real slot's table row, so their
            # duplicate scatters rewrite identical cells (same trick as ids)
            logits, self.cache_k, self.cache_v = self.family.prefill_into_pages(
                self.params,
                self.cfg,
                jnp.asarray(ids),
                jnp.asarray(lens),
                jnp.asarray(self._block_tables[slot_ids]),
                self.cache_k,
                self.cache_v,
                self.mesh,
                lora_idx=lora_idx,
            )
        else:
            logits, self.cache_k, self.cache_v = self.family.prefill_into_slots(
                self.params,
                self.cfg,
                jnp.asarray(ids),
                jnp.asarray(lens),
                jnp.asarray(slot_ids),
                self.cache_k,
                self.cache_v,
                self.mesh,
                lora_idx=lora_idx,
            )
        t_compute = time.perf_counter()
        # jitted prefill returns futures (async dispatch); block before timing
        # or the histogram records dispatch overhead, not device execution.
        jax.block_until_ready(logits)
        t_done = time.perf_counter()
        self.metrics.record_prefill_step(time.monotonic() - prefill_start)
        if self.flightrec.enabled:
            # emit before activation: split mode stages the group and vacates
            # the prefill slots, after which the requests are unreachable here
            for _slot_id, request, n in group:
                self.flightrec.emit(request.request_id, "prefill_chunk",
                                    tokens=n, cached_tokens=0)
        self._activate_group(group, slot_ids, lens, logits)
        self._record_step(
            "prefill",
            {"dispatch": t_compute - t_dispatch, "compute": t_done - t_compute,
             "emit": time.perf_counter() - t_done},
            active_slots=len(group), tokens=sum(n for _, _, n in group),
            slots=[s for s, _, _ in group],
        )

    def _activate_group(self, group: list[tuple[int, Request, int]],
                        padded_slot_ids: np.ndarray, padded_lens: np.ndarray,
                        logits) -> None:
        """Batched activation: ONE sample_tokens over the padded logits and
        one vector scatter per device array — ~6 dispatches for the whole
        group instead of ~6 per request. Padding rows repeat the last real
        row, so their scatters rewrite identical values.

        Split mode: a prefill-loop activation never lands in the prefill
        slot — the finished slot is STAGED (prompt KV pinned in its pages,
        final logits row held) and the handoff pump adopts it into a decode
        slot, re-entering here under the "handoff" tag."""
        if self.split is not None and self._loop_tag() == "prefill":
            self.split.stage_group(group, logits)
            self.split.pump_handoffs()
            return
        padded = len(padded_slot_ids)
        temps = np.ones((padded,), np.float32)
        top_ps = np.ones((padded,), np.float32)
        top_ks = np.zeros((padded,), np.int32)
        seeds = np.full((padded,), -1, np.int32)
        for row, (_slot_id, request, _n) in enumerate(group):
            s = request.sampling
            temps[row] = s.temperature
            top_ps[row] = s.top_p
            top_ks[row] = s.top_k
            if s.seed is not None:
                seeds[row] = s.seed & 0x7FFFFFFF
        temps[len(group):] = temps[len(group) - 1]
        top_ps[len(group):] = top_ps[len(group) - 1]
        top_ks[len(group):] = top_ks[len(group) - 1]
        seeds[len(group):] = seeds[len(group) - 1]

        # Constrained rows mask their first-token sampling too: the bias is
        # each slot's FSM start-state row (padding repeats the last real row,
        # so its duplicate scatter writes the same value).
        constrained = [
            (row, self.slots[slot_id].constraint)
            for row, (slot_id, _r, _n) in enumerate(group)
            if self.slots[slot_id].constraint is not None
        ]
        mask = None
        if constrained:
            bias = np.zeros((padded, logits.shape[-1]), np.float32)
            for row, state in constrained:
                bias[row] = state.bias_row()
            bias[len(group):] = bias[len(group) - 1]
            mask = jnp.asarray(bias)

        self._key, sk = jax.random.split(self._key)
        d_temps = jnp.asarray(temps)
        d_top_ps = jnp.asarray(top_ps)
        d_top_ks = jnp.asarray(top_ks)
        d_seeds = jnp.asarray(seeds)
        # steps = lens - 1: decode dispatches sample with the PRE-increment
        # seq_len, so the first decode token uses step = prompt_len — the
        # activation sample must fold a DIFFERENT step or a seeded request's
        # first two tokens would draw from the same per-row key.
        firsts = sample_tokens(logits, sk, d_temps, d_top_ps, d_top_ks,
                               mask, d_seeds, jnp.asarray(padded_lens) - 1)
        idx = jnp.asarray(padded_slot_ids)
        self._d_temps = self._d_temps.at[idx].set(d_temps)
        self._d_top_ps = self._d_top_ps.at[idx].set(d_top_ps)
        self._d_top_ks = self._d_top_ks.at[idx].set(d_top_ks)
        self._d_seeds = self._d_seeds.at[idx].set(d_seeds)
        if self.lora is not None:
            # adapter rows ride the same activation scatter as the sampling
            # params: the decode hot loop then needs zero per-step H2D
            lidx = np.zeros((padded,), np.int32)
            lidx[:len(group)] = self._lora_rows([r for _, r, _ in group])
            lidx[len(group):] = lidx[len(group) - 1]
            self._d_lora_idx = self._d_lora_idx.at[idx].set(
                jnp.asarray(lidx)
            )
        self._d_seq_lens = self._d_seq_lens.at[idx].set(
            jnp.asarray(padded_lens)
        )
        self._d_last_tokens = self._d_last_tokens.at[idx].set(firsts)

        if constrained:
            # The NEXT decode dispatch needs each constrained slot's mask
            # advanced past its first token, which only exists on device —
            # one synchronous fetch per constrained activation (the
            # constrained-TTFT cost documented in docs/structured-outputs.md;
            # unconstrained slots keep the zero-sync deferred-first path).
            first_host = self._fetch_tokens(firsts)
            for row, (slot_id, _r, _n) in enumerate(group):
                state = self.slots[slot_id].constraint
                if state is None:
                    continue
                if state.advance(int(first_host[row])):
                    self._set_mask_row(slot_id, state)
                else:
                    self.metrics.record_constraint_violation()

        for slot_id, request, n in group:
            self._seq_lens[slot_id] = n
            slot = self.slots[slot_id]
            slot.request = request
            if request.parked is not None:
                # preemption resume: restore the generation cursor — the
                # activation sample above IS the next token of the
                # interrupted stream (its step folded len(committed)-1,
                # exactly the step an uninterrupted decode would have used)
                st = request.parked
                slot.generated = st.generated
                slot.out_tokens = list(st.tokens)
                request.parked = None
                self.metrics.record_resume()
                self._fr_emit(request, "resumed", generated=st.generated)
            else:
                slot.generated = 0
                slot.out_tokens = []
            # last_emit_at 0 ⇒ the first token records no inter-token gap;
            # it is emitted with the next decode fetch (first_pending).
            slot.last_emit_at = 0.0
            slot.first_pending = True

    def _cp_bucket_for(self, n: int) -> int:
        """Padded length for the context-parallel prefill jit cache: next
        power of two (≥ the largest one-shot bucket), capped at capacity."""
        b = max(self.prefill_buckets[-1], 1)
        while b < n:
            b *= 2
        return min(b, self.slot_capacity)

    def _cp_prefill_into_slot(self, slot_id: int, request: Request,
                              n: int) -> None:
        """One-shot ring-attention prefill of a long prompt, scattered into
        the slot cache row (engine wiring for make_context_parallel_prefill,
        VERDICT r2 item 5)."""
        if self._cp_prefill_fn is None:
            self._cp_prefill_fn = self.family.make_context_parallel_prefill(
                self.cfg, self.mesh
            )
        padded = self._cp_bucket_for(n)
        ids = np.zeros((1, padded), np.int32)
        ids[0, :n] = self._effective_prompt(request)
        prefill_start = time.monotonic()
        self._note_prefill_dispatch()
        t_dispatch = time.perf_counter()
        logits, k_all, v_all = self._cp_prefill_fn(
            self.params, jnp.asarray(ids), jnp.asarray([n], np.int32)
        )
        t_compute = time.perf_counter()
        jax.block_until_ready(logits)  # async dispatch; time real execution
        t_done = time.perf_counter()
        self.metrics.record_prefill_step(time.monotonic() - prefill_start)
        self._fr_emit(request, "prefill_chunk", tokens=n, cached_tokens=0,
                      cp=True)
        self._record_step(
            "prefill",
            {"dispatch": t_compute - t_dispatch,
             "compute": t_done - t_compute},
            active_slots=1, tokens=n,
        )
        # KV beyond n is padding garbage; it lands in cells past the valid
        # length (masked by decode attention and overwritten as the sequence
        # grows into them) — same contract as the chunked path.
        if self.page_pool is not None:
            self.cache_k, self.cache_v = _scatter_kv_row_paged(
                self.cache_k, self.cache_v, k_all, v_all,
                jnp.asarray(self._block_tables[slot_id]),
            )
        else:
            self.cache_k, self.cache_v = _scatter_kv_row(
                self.cache_k, self.cache_v, k_all, v_all, jnp.int32(slot_id)
            )
        slot = self.slots[slot_id]
        slot.request = request
        slot.generated = 0
        self._attach_constraint(slot_id, request)
        self._activate_slot(slot_id, request, n, logits)

    def _advance_prefill(self) -> bool:
        """Feed ONE chunk of ONE prefilling slot's prompt into the KV cache.
        Rotates among prefilling slots so a second long prompt shares prefill
        bandwidth instead of waiting head-of-line behind the first."""
        prefilling = [i for i, s in enumerate(self.slots)
                      if s.prefilling and not s.handoff_ready]
        if not prefilling:
            return False
        slot_id = prefilling[self._prefill_rr % len(prefilling)]
        self._prefill_rr += 1
        slot = self.slots[slot_id]
        request = slot.request
        assert request is not None
        if self._is_cancelled(request):
            self._finish_slot(slot_id, "cancelled")
            return True

        prompt = self._effective_prompt(request)
        n = len(prompt)
        start = slot.prefill_pos
        chunk_max = self.prefill_buckets[-1]
        prefill_budget = self._prefill_budget_now()
        if prefill_budget:
            # decode-token budget (docs/scheduling.md): while decoders are
            # active, cap each chunk so decode steps interleave — a 128k
            # prompt then costs the decoders one budget-sized prefill per
            # iteration, never a whole drain iteration. The budget is shared
            # with _try_insert's one-shot batch from the same iteration.
            remaining = prefill_budget - self._prefill_spent_iter
            if remaining <= 0:
                return False
            chunk_max = min(chunk_max, self._budget_chunk_len(remaining))
        chunk_len = min(chunk_max, n - start)
        bucket = self._bucket_for(chunk_len)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :chunk_len] = prompt[start:start + chunk_len]
        lora_idx = (jnp.asarray(self._lora_rows([request]))
                    if self.lora is not None else None)

        prefill_start = time.monotonic()
        self._note_prefill_dispatch()
        t_dispatch = time.perf_counter()
        if self.page_pool is not None:
            logits, self.cache_k, self.cache_v = self.family.prefill_extend_pages(
                self.params,
                self.cfg,
                jnp.asarray(ids),
                jnp.asarray([chunk_len], np.int32),
                jnp.asarray([start], np.int32),
                jnp.asarray(self._block_tables[slot_id:slot_id + 1]),
                self.cache_k,
                self.cache_v,
                self.mesh,
                lora_idx=lora_idx,
            )
        else:
            logits, self.cache_k, self.cache_v = self.family.prefill_extend_slots(
                self.params,
                self.cfg,
                jnp.asarray(ids),
                jnp.asarray([chunk_len], np.int32),
                jnp.asarray([start], np.int32),
                jnp.asarray([slot_id], np.int32),
                self.cache_k,
                self.cache_v,
                self.mesh,
                lora_idx=lora_idx,
            )
        t_compute = time.perf_counter()
        jax.block_until_ready(logits)  # async dispatch; time real execution
        t_done = time.perf_counter()
        self.metrics.record_prefill_step(time.monotonic() - prefill_start)

        slot.prefill_pos = start + chunk_len
        self._fr_emit(request, "prefill_chunk", tokens=chunk_len,
                      cached_tokens=0, pos=start)
        if slot.prefill_pos >= n:
            slot.prefilling = False
            self._release_cache_entry(slot)  # suffix landed; donor evictable
            self._activate_slot(slot_id, request, n, logits)
        self._record_step(
            "prefill",
            {"dispatch": t_compute - t_dispatch, "compute": t_done - t_compute,
             "emit": time.perf_counter() - t_done},
            active_slots=1, tokens=chunk_len,
            slots=[slot_id],
        )
        return True

    def _activate_slot(self, slot_id: int, request: Request, n: int,
                       logits) -> None:
        """Single-slot activation (chunked/CP prefill completions): the
        sampled first token stays ON DEVICE and is emitted with the next
        decode fetch, so activation costs no host sync. first_token_at is
        stamped when the token actually reaches the host (_emit), keeping
        TTFT client-honest."""
        self._activate_group(
            [(slot_id, request, n)],
            np.asarray([slot_id], np.int32),
            np.asarray([n], np.int32),
            logits,
        )

    def _window_for(self, active: list[int], k: int) -> int:
        """Smallest context-window bucket covering every active sequence
        plus the k tokens this dispatch will add."""
        needed = max(int(self._seq_lens[i]) for i in active) + k + 1
        for w in self._window_buckets:
            if w >= needed:
                return w
        return self.slot_capacity

    def _build_decode_many(self, k: int, window: int) -> Callable:
        """Jit a k-step decode: lax.scan feeds each step's sampled tokens
        back into the next ON DEVICE, so the host syncs once per k tokens
        instead of once per token. Sampling params are scan-invariant;
        the caches are donated (the scan carries them in place). The paged
        variant additionally threads the (scan-invariant) block tables —
        _ensure_decode_pages pre-allocates every page the burst will write."""
        family, cfg, mesh = self.family, self.cfg, self.mesh

        if self.page_pool is not None:
            def many(params, last, lens, cache_k, cache_v, tables,
                     temps, top_ps, top_ks, seeds, key, lora_idx=None):
                keys = jax.random.split(key, k)

                def body(carry, step_key):
                    last, lens, ck, cv = carry
                    logits, ck, cv = family.decode_step_paged(
                        params, cfg, last, lens, ck, cv, tables, mesh,
                        window=window, lora_idx=lora_idx,
                    )
                    toks = sample_tokens(logits, step_key, temps, top_ps,
                                         top_ks, None, seeds, lens)
                    return (toks, lens + 1, ck, cv), toks

                first_in = last  # pre-burst tokens: pending first emissions
                (last, lens, cache_k, cache_v), toks = jax.lax.scan(
                    body, (last, lens, cache_k, cache_v), keys
                )
                toks = jnp.concatenate([first_in[None, :], toks], axis=0)
                return last, lens, cache_k, cache_v, toks

            return jax.jit(many, donate_argnums=(3, 4))

        def many(params, last, lens, cache_k, cache_v,
                 temps, top_ps, top_ks, seeds, key, lora_idx=None):
            keys = jax.random.split(key, k)

            def body(carry, step_key):
                last, lens, ck, cv = carry
                logits, ck, cv = family.decode_step(
                    params, cfg, last, lens, ck, cv, mesh, window=window,
                    lora_idx=lora_idx,
                )
                toks = sample_tokens(logits, step_key, temps, top_ps, top_ks,
                                     None, seeds, lens)
                return (toks, lens + 1, ck, cv), toks

            first_in = last  # pre-burst tokens: pending first emissions
            (last, lens, cache_k, cache_v), toks = jax.lax.scan(
                body, (last, lens, cache_k, cache_v), keys
            )
            # One fetchable array [k+1, SLOTS]: row 0 carries the pre-burst
            # last tokens so newly activated slots' first tokens ride the
            # same host readback as the burst output.
            toks = jnp.concatenate([first_in[None, :], toks], axis=0)
            return last, lens, cache_k, cache_v, toks

        return jax.jit(many, donate_argnums=(3, 4))

    def _shared_program(self, kind: str, extra: tuple, build) -> Callable:
        """Fetch/build a jit-wrapped step program through the process-wide
        _PROGRAM_CACHE so engines sharing a config reuse one executable
        set. The build key is everything the trace closes over (family,
        cfg, mesh, layout, plus the caller's k/window/variant in `extra`);
        array shapes (slots, pages, quantized-or-not pytrees) go through
        jit's own shape-keyed cache per call, not the build key."""
        key = (kind, id(self.family), id(self.cfg), self.mesh,
               self.page_pool is not None) + extra
        with _PROGRAM_CACHE_LOCK:
            hit = _PROGRAM_CACHE.get(key)
        if hit is None:
            fn = build()
            with _PROGRAM_CACHE_LOCK:
                # racing builders converge on whichever landed first
                hit = _PROGRAM_CACHE.setdefault(
                    key, (fn, self.family, self.cfg))
        return hit[0]

    def _decode_many_for(self, window: int) -> Callable:
        with self._decode_many_lock:
            fn = self._decode_many.get(window)
            if fn is None:
                fn = self._shared_program(
                    "decode_many", (self.decode_burst, window),
                    lambda: self._build_decode_many(self.decode_burst,
                                                    window))
                self._decode_many[window] = fn
            return fn

    def _build_decode_many_gram(self, k: int, window: int) -> Callable:
        """Grammar-masked variant of the burst scan: same k-step decode
        with, per step, the sampling bias gathered from the device grammar
        table and the per-row cursor advanced in-program on the sampled
        token — so constrained slots ride the burst instead of forcing the
        batch into single-step decode. Free rows carry cursor 0 (the
        all-zero table row): their bias is + 0.0 everywhere, bit-preserving
        the unconstrained sampling path."""
        family, cfg, mesh = self.family, self.cfg, self.mesh

        if self.page_pool is not None:
            def many(params, last, lens, cache_k, cache_v, tables,
                     temps, top_ps, top_ks, seeds, key, gram_table,
                     gram_state, lora_idx=None):
                keys = jax.random.split(key, k)

                def body(carry, step_key):
                    last, lens, gs, ck, cv = carry
                    logits, ck, cv = family.decode_step_paged(
                        params, cfg, last, lens, ck, cv, tables, mesh,
                        window=window, lora_idx=lora_idx,
                    )
                    bias = grammar_bias(gram_table, gs)
                    toks = sample_tokens(logits, step_key, temps, top_ps,
                                         top_ks, bias, seeds, lens)
                    gs = grammar_advance(gram_table, gs, toks)
                    return (toks, lens + 1, gs, ck, cv), toks

                first_in = last  # pre-burst tokens: pending first emissions
                (last, lens, _, cache_k, cache_v), toks = jax.lax.scan(
                    body, (last, lens, gram_state, cache_k, cache_v), keys
                )
                toks = jnp.concatenate([first_in[None, :], toks], axis=0)
                return last, lens, cache_k, cache_v, toks

            return jax.jit(many, donate_argnums=(3, 4))

        def many(params, last, lens, cache_k, cache_v,
                 temps, top_ps, top_ks, seeds, key, gram_table, gram_state,
                 lora_idx=None):
            keys = jax.random.split(key, k)

            def body(carry, step_key):
                last, lens, gs, ck, cv = carry
                logits, ck, cv = family.decode_step(
                    params, cfg, last, lens, ck, cv, mesh, window=window,
                    lora_idx=lora_idx,
                )
                bias = grammar_bias(gram_table, gs)
                toks = sample_tokens(logits, step_key, temps, top_ps,
                                     top_ks, bias, seeds, lens)
                gs = grammar_advance(gram_table, gs, toks)
                return (toks, lens + 1, gs, ck, cv), toks

            first_in = last  # pre-burst tokens: pending first emissions
            (last, lens, _, cache_k, cache_v), toks = jax.lax.scan(
                body, (last, lens, gram_state, cache_k, cache_v), keys
            )
            toks = jnp.concatenate([first_in[None, :], toks], axis=0)
            return last, lens, cache_k, cache_v, toks

        return jax.jit(many, donate_argnums=(3, 4))

    def _decode_many_gram_for(self, window: int) -> Callable:
        with self._decode_many_lock:
            fn = self._decode_many_gram.get(window)
            if fn is None:
                fn = self._shared_program(
                    "decode_many_gram", (self.decode_burst, window),
                    lambda: self._build_decode_many_gram(self.decode_burst,
                                                         window))
                self._decode_many_gram[window] = fn
            return fn

    def _decode_active(self) -> bool:
        decode_pool = (self.split.decode_pool if self.split is not None
                       else range(self.num_slots))
        active = [
            i for i in decode_pool
            if self.slots[i].request is not None
            and not self.slots[i].prefilling
        ]
        if not active:
            # The occupancy gauge is otherwise only written on decode steps
            # and would freeze at the last batch size on an idle engine.
            self.metrics.set_batch_occupancy(0)
            return False

        # Speculative decoding: when any active slot proposes drafts, ONE
        # verify dispatch replaces this step's decode — it scores all drafts
        # plus a correction/bonus sample and emits 1..K+1 tokens per slot.
        # Constrained slots ride the same dispatch with per-position FSM
        # lookahead masks, so a JSON-mode request advances multi-token
        # instead of forcing the whole batch into single-step decode. With
        # no drafter attached anywhere this block is a no-op and the decode
        # path below is bit-identical to the pre-speculation engine.
        draft_s = 0.0
        if self._spec_available and any(
            self.slots[i].drafter is not None for i in active
        ):
            t_draft = time.perf_counter()
            fused_spec = self._fused_step_ok(active)
            drafts, lookahead = self._collect_drafts(active, fused=fused_spec)
            draft_s = time.perf_counter() - t_draft
            if any(drafts.values()):
                return self._verify_active(active, drafts, lookahead, draft_s,
                                           fused=fused_spec)
            # no n-gram matched: fall through to plain decode; the draft
            # time lands in this step's record below

        t_sync = time.perf_counter()
        if self.page_pool is not None:
            # alloc-on-extend: every page this dispatch writes must exist
            # before the tables ship to the device
            active = self._ensure_decode_pages(active, self.decode_burst)
            if not active:
                self.metrics.set_batch_occupancy(0)
                return True  # pool exhaustion finished requests: work done
            self._sync_block_tables()
        sync_s = time.perf_counter() - t_sync

        self._key, sk = jax.random.split(self._key)
        k = self.decode_burst
        # Constrained slots advance a host-side FSM per token, so on the
        # LEGACY path their mask cannot be updated mid-burst: any constrained
        # slot in the batch forces single-step decode for this dispatch (the
        # constrained-TPS cost documented in docs/structured-outputs.md).
        # With fused decode the grammar lives on the device (ops/grammar) and
        # constrained slots ride the burst scan — the fallback below only
        # fires when a schema failed to register (budget), and is counted so
        # the "zero single-step fallbacks" invariant is checkable.
        constrained_active = self._constrained_count > 0 and any(
            self.slots[i].constraint is not None for i in active
        )
        fused_step = self._fused_step_ok(active)
        if k > 1 and constrained_active and not fused_step:
            k = 1
            self.metrics.record_constrained_burst_fallback()
        lora_idx = self._d_lora_idx if self.lora is not None else None
        if k > 1 or fused_step:
            burst_start = time.monotonic()
            window = self._window_for(active, k)
            grammar = fused_step and constrained_active
            t_mask = time.perf_counter()
            gram_args = {}
            if grammar:
                # Fresh int32 cursor vector from the host FSMs (source of
                # truth, advanced in _emit): one [SLOTS] H2D per step instead
                # of a [SLOTS, V] float32 mask scatter. Free/parked rows sit
                # at cursor 0 — the all-zero free row.
                gs = np.zeros((self.num_slots,), dtype=np.int32)
                for i in active:
                    slot = self.slots[i]
                    if slot.constraint is not None and slot.gram_offset >= 0:
                        gs[i] = slot.gram_offset + slot.constraint.state
                gram_args = {
                    "gram_table": self._grammar_tables.device(),
                    "gram_state": jnp.asarray(gs),
                }
                self.metrics.record_masked_decode_step()
            sync_s += time.perf_counter() - t_mask
            fn = (self._decode_many_gram_for(window) if grammar
                  else self._decode_many_for(window))
            t_dispatch = time.perf_counter()
            if self.page_pool is not None:
                (self._d_last_tokens, self._d_seq_lens, self.cache_k,
                 self.cache_v, toks_dev) = fn(
                    self.params, self._d_last_tokens, self._d_seq_lens,
                    self.cache_k, self.cache_v, self._d_block_tables,
                    self._d_temps, self._d_top_ps, self._d_top_ks,
                    self._d_seeds, sk, lora_idx=lora_idx, **gram_args,
                )
            else:
                (self._d_last_tokens, self._d_seq_lens, self.cache_k,
                 self.cache_v, toks_dev) = fn(
                    self.params, self._d_last_tokens, self._d_seq_lens,
                    self.cache_k, self.cache_v,
                    self._d_temps, self._d_top_ps, self._d_top_ks,
                    self._d_seeds, sk, lora_idx=lora_idx, **gram_args,
                )
            t_compute = time.perf_counter()
            # split device execution from the D2H readback: the dispatch
            # returned futures, block_until_ready is the compute wait, the
            # fetch below is pure transfer
            jax.block_until_ready(toks_dev)
            t_fetch = time.perf_counter()
            tokens = self._fetch_tokens(toks_dev)  # ONE D2H sync per k tokens
            t_emit = time.perf_counter()
            # Tokens reach the host back-to-back, so wall-clock gaps between
            # _emit calls are ~0 and would poison the ITL histogram; record
            # the amortized per-token pacing of the burst instead.
            step_s = (time.monotonic() - burst_start) / k
            self.metrics.record_decode_step(step_s, len(active))
            self._emit_fetched(tokens, active, itl=step_s)
            self._record_step(
                "decode",
                {"draft": draft_s,
                 "host_sync": sync_s,
                 "dispatch": t_compute - t_dispatch,
                 "compute": t_fetch - t_compute,
                 "fetch": t_emit - t_fetch,
                 "emit": time.perf_counter() - t_emit},
                active_slots=len(active), tokens=k * len(active),
                slots=active, dispatches=1, fused=fused_step,
            )
            return True

        step_start = time.monotonic()
        first_in = self._d_last_tokens  # pre-step tokens: pending firsts
        t_dispatch = time.perf_counter()
        if self.page_pool is not None:
            logits, self.cache_k, self.cache_v = self.family.decode_step_paged(
                self.params,
                self.cfg,
                self._d_last_tokens,
                self._d_seq_lens,
                self.cache_k,
                self.cache_v,
                self._d_block_tables,
                self.mesh,
                window=self._window_for(active, 1),
                lora_idx=lora_idx,
            )
        else:
            logits, self.cache_k, self.cache_v = self.family.decode_step(
                self.params,
                self.cfg,
                self._d_last_tokens,
                self._d_seq_lens,
                self.cache_k,
                self.cache_v,
                self.mesh,
                window=self._window_for(active, 1),
                lora_idx=lora_idx,
            )
        dispatch_s = time.perf_counter() - t_dispatch
        t_mask = time.perf_counter()
        mask = self._sync_mask() if constrained_active else None
        sync_s += time.perf_counter() - t_mask
        if mask is not None:
            self.metrics.record_masked_decode_step()
        t_sample = time.perf_counter()
        tokens_dev = sample_tokens(
            logits, sk, self._d_temps, self._d_top_ps, self._d_top_ks,
            mask, self._d_seeds, self._d_seq_lens,
        )
        self._d_last_tokens = tokens_dev
        self._d_seq_lens = self._d_seq_lens + 1
        dispatch_s += time.perf_counter() - t_sample
        t_compute = time.perf_counter()
        jax.block_until_ready(tokens_dev)  # device execution, not transfer
        t_fetch = time.perf_counter()
        # the one D2H sync per step; row 0 carries deferred first emissions.
        # itl = this step's duration: a deferred first and its decode token
        # land in the same fetch, so the wall gap between them is ~0 and
        # would skew the histogram exactly like an unamortized burst.
        tokens = self._fetch_tokens(jnp.stack([first_in, tokens_dev]))
        t_emit = time.perf_counter()
        step_s = time.monotonic() - step_start
        self.metrics.record_decode_step(step_s, len(active))
        self._emit_fetched(tokens, active, itl=step_s)
        self._record_step(
            "decode",
            {"draft": draft_s,
             "host_sync": sync_s,
             "dispatch": dispatch_s,
             "compute": t_fetch - t_compute,
             "fetch": t_emit - t_fetch,
             "emit": time.perf_counter() - t_emit},
            active_slots=len(active), tokens=len(active),
            slots=active,
            # legacy eager step: model forward, sample, lens advance are
            # separate dispatches, plus the mask scatter when constrained
            dispatches=3 + (1 if mask is not None else 0), fused=False,
        )
        return True

    def _emit_fetched(self, tokens, active: list[int],
                      itl: float | None) -> None:
        """Deliver one fetched token matrix [rows, SLOTS]: row 0 holds
        deferred first emissions for slots activated since the previous
        fetch (no seq_len advance — the first token is prefill output, not
        a decode step); rows 1.. are decode steps. Slots that finish
        mid-matrix (EOS / max_tokens / capacity / cancel) have their
        remaining tokens trimmed."""
        for i in active:
            slot = self.slots[i]
            if slot.first_pending and slot.request is not None:
                slot.first_pending = False
                # first=True: the grammar FSM already advanced on this token
                # at activation (the synchronous fetch there) — advancing
                # again would double-step the grammar.
                self._emit(i, int(tokens[0, i]), first=True)
        for t in range(1, tokens.shape[0]):
            for i in active:
                slot = self.slots[i]
                if slot.request is None or slot.prefilling:
                    continue
                self._seq_lens[i] += 1
                self._emit(i, int(tokens[t, i]), itl=itl)

    def _emit(self, slot_id: int, token: int,
              itl: float | None = None, first: bool = False) -> None:
        """Deliver one generated token. `itl` overrides the wall-clock
        inter-token gap (burst decode delivers k tokens back-to-back; the
        caller passes the amortized pacing instead). `first` marks the
        deferred first emission, whose grammar advance already happened at
        activation."""
        slot = self.slots[slot_id]
        request = slot.request
        assert request is not None
        if self._is_cancelled(request):
            request.finished_at = time.monotonic()
            request.events.put(("done", "cancelled"))
            self._fr_emit(request, "finished", reason="cancelled",
                          generated=slot.generated)
            self.metrics.record_request_done("cancelled")
            self._release_lora(request)
            self._cancelled_effective.discard(request.request_id)
            self._free_slot_kv(slot_id)
            self._clear_constraint(slot_id)
            slot.request = None
            slot.generated = 0
            slot.last_emit_at = 0.0
            slot.first_pending = False
            slot.drafter = None
            slot.spec_k = 0
            slot.out_tokens = []
            return
        slot.generated += 1
        if token != self.eos_id:
            # committed-sequence mirror: what a preemption park would need
            # to chunk-prefill on resume (EOS finishes, never parks)
            slot.out_tokens.append(token)
        # Incremental drafter update: every emitted token extends the
        # prompt-lookup index (first_pending emissions included — the first
        # token is part of the sequence the next proposal continues).
        if slot.drafter is not None and token != self.eos_id:
            slot.drafter.append(token)
        now = time.monotonic()
        if request.first_token_at is None:
            request.first_token_at = now
            self.metrics.record_ttft(now - request.submitted_at)
        if not slot.last_emit_at:
            self.metrics.record_emit(None)  # first token: no inter-token gap
        else:
            self.metrics.record_emit(
                itl if itl is not None else now - slot.last_emit_at
            )
        slot.last_emit_at = now
        with self._lock:
            self.total_tokens += 1

        # Advance the grammar FSM on every sampled token; the updated mask
        # row governs the NEXT dispatch. The mask makes a disallowed sample
        # impossible, so advance() failing means a vocabulary gap forced the
        # EOS fallback — counted, not crashed on.
        state = slot.constraint
        if state is not None and not first:
            if not state.advance(token):
                self.metrics.record_constraint_violation()
            elif token != self.eos_id:
                self._set_mask_row(slot_id, state)

        finish: str | None = None
        if token == self.eos_id:
            finish = "stop"
        elif slot.generated >= request.sampling.max_tokens:
            finish = "length"
        elif self._seq_lens[slot_id] + 1 >= self.slot_capacity:
            finish = "length"
        if (finish is not None and finish != "stop" and state is not None
                and not state.is_accepting):
            # cut short (max_tokens / capacity) before grammar acceptance
            self.metrics.record_constraint_violation()

        if finish == "stop":
            pass  # EOS itself is not emitted as content
        else:
            request.events.put(("token", token))

        if finish is not None:
            request.finished_at = time.monotonic()
            if (finish == "length" and request.export_kv and self.kv_ship
                    and self.page_pool is not None):
                # Handoff export: serialize this stream's KV pages D2H
                # BEFORE the pool frees them below — the adopter lands
                # them and continues with zero prefill dispatches. Only
                # the budgeted "length" finish exports: stop/cancel means
                # the stream is over, there is nothing to move.
                request.kv_export = self._kv_export_payload(slot_id, request)
            request.events.put(("done", finish))
            self._fr_emit(request, "finished", reason=finish,
                          generated=slot.generated)
            self.metrics.record_request_done(finish)
            self._release_lora(request)
            if self.prefix_cache is not None:
                # Donor retention: the freed slot's rows [0, prompt_len) hold
                # exactly the prompt's KV — pin them for prefix reuse instead
                # of discarding. Dense mode retains the whole slot (out of
                # the free pool until evicted); paged mode pins only the
                # head's pages and the slot frees immediately below.
                self._maybe_cache_prefix(slot_id, request)
            self._free_slot_kv(slot_id)
            self._clear_constraint(slot_id)
            slot.request = None
            slot.generated = 0
            slot.last_emit_at = 0.0
            slot.first_pending = False
            slot.drafter = None
            slot.spec_k = 0
            slot.out_tokens = []

    def _fail_all(self, message: str) -> None:
        for slot_id, slot in enumerate(self.slots):
            if slot.request is not None:
                slot.request.events.put(("error", message))
                self._fr_emit(slot.request, "errored", message=message)
                self.metrics.record_request_done("error")
                self._release_lora(slot.request)
                slot.request = None
            self._release_cache_entry(slot)
            self._free_slot_kv(slot_id)
            self._clear_constraint(slot_id)
            slot.prefilling = False
            slot.prefill_pos = 0
            slot.handoff_ready = False
            slot.handoff_logits = None
            slot.handoff_ready_at = 0.0
            slot.generated = 0
            slot.last_emit_at = 0.0
            slot.first_pending = False
            slot.drafter = None
            slot.spec_k = 0
            slot.out_tokens = []
        if self._held_request is not None:
            self._held_request.events.put(("error", message))
            self._fr_emit(self._held_request, "errored", message=message)
            self.metrics.record_request_done("error")
            self._release_lora(self._held_request)
            self._held_request = None
        for p in PRIORITY_CLASSES:
            q = self._class_queues[p]
            while q:
                r = q.popleft()
                r.events.put(("error", message))
                self._fr_emit(r, "errored", message=message)
                self.metrics.record_request_done("error")
                self._release_lora(r)
        while True:
            try:
                r = self.pending.get_nowait()
                r.events.put(("error", message))
                self._fr_emit(r, "errored", message=message)
                self.metrics.record_request_done("error")
                self._release_lora(r)
            except queue.Empty:
                break
