"""The tpu:// endpoint HTTP server — the contract the gateway routes to.

Implements the runtime-side API the reference gateway expects of any endpoint
(SURVEY.md §7 stance): OpenAI `/v1/models`, `/v1/chat/completions`,
`/v1/completions`, `/v1/responses` (SSE streams end with a usage-bearing
payload — the gateway's TPS tracker depends on it, reference
llmlb/src/api/proxy.rs:118-241), plus `/api/health` with TPU chip/HBM telemetry
in place of the GPU fields (endpoint_checker.rs:515) and `/api/system` carrying
the `tpu_engine` marker the gateway's type detection probes first.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import re
import time
import uuid

from aiohttp import web

from llmlb_tpu import __version__
from llmlb_tpu.disagg import HandoffError, handoff_payload, parse_handoff
from llmlb_tpu.engine.profiling import ProfileError, ProfileManager
from llmlb_tpu.engine.scheduler import SamplingParams
from llmlb_tpu.engine.service import Engine, EngineError
from llmlb_tpu.structured import inspect_request, parse_seed

log = logging.getLogger("llmlb_tpu.engine.server")

# Echoed as `system_fingerprint` on chat completions: one serving-stack
# identity per engine build, so clients pairing it with `seed` can tell
# "same fingerprint + same seed => same tokens" apart from a stack change.
SYSTEM_FINGERPRINT = f"fp_llmlb_tpu_{__version__}"

MAX_BODY_BYTES = 20 * 1024 * 1024  # parity: reference caps /v1/* at 20 MiB
# Handoff/resume envelopes may carry a serialized KV page payload
# (engine/kv_transfer.py) — base64 over tens of MiB for long contexts on
# real configs — so the aiohttp body cap sits above the plain-JSON limit.
# Plain chat bodies stay bounded by prompt length long before this.
KV_BODY_BYTES = 256 * 1024 * 1024


# The gateway forwards its trace id on proxied calls; it becomes the prefix
# of the scheduler request_id (service.py appends a unique suffix), joining
# engine-side events to the gateway trace. Shape is enforced — the id
# reaches logs and response headers.
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9_.:\-]{1,128}$")


def _request_id_from(request: web.Request) -> str | None:
    rid = request.headers.get("X-Request-Id")
    if rid and _REQUEST_ID_RE.match(rid):
        return rid
    return None


def _rid_headers(rid: str | None) -> dict:
    return {"X-Request-Id": rid} if rid else {}


def _error(status: int, message: str, err_type: str = "invalid_request_error"):
    return web.json_response(
        {"error": {"message": message, "type": err_type, "code": None}},
        status=status,
    )


def _sampling_from(body: dict, default_max: int = 256) -> SamplingParams:
    def pick(*names, default):
        for n in names:
            if body.get(n) is not None:
                return body[n]
        return default

    temperature = float(pick("temperature", default=1.0))
    top_p = float(pick("top_p", default=1.0))
    top_k = int(pick("top_k", default=0))
    max_tokens = int(
        pick("max_tokens", "max_completion_tokens", "max_output_tokens",
             default=default_max)
    )
    if temperature < 0:
        raise ValueError("'temperature' must be >= 0")
    if not 0 < top_p <= 1:
        raise ValueError("'top_p' must be in (0, 1]")
    if top_k < 0:
        raise ValueError("'top_k' must be >= 0")
    if max_tokens < 1:
        raise ValueError("'max_tokens' must be >= 1")
    return SamplingParams(
        temperature=temperature, top_p=top_p, top_k=top_k,
        max_tokens=max_tokens, speculative=_speculative_from(body),
        priority=_priority_from(body),
    )


_PRIORITY_NAMES = {"high": 0, "normal": 1, "low": 2}


def _priority_from(body: dict) -> int:
    """Per-request priority class (docs/scheduling.md), accepted on both
    the OpenAI and Anthropic dialects: "high"/"normal"/"low" or 0/1/2.
    Lower value = more important; default "normal"."""
    p = body.get("priority")
    if p is None:
        return 1
    if isinstance(p, str):
        if p not in _PRIORITY_NAMES:
            raise ValueError(
                "'priority' must be one of high, normal, low (or 0..2)"
            )
        return _PRIORITY_NAMES[p]
    if isinstance(p, bool) or not isinstance(p, int) or not 0 <= p <= 2:
        raise ValueError(
            "'priority' must be one of high, normal, low (or 0..2)"
        )
    return p


def _deadline_from(request: web.Request) -> float | None:
    """Remaining request deadline in milliseconds, propagated by the gateway
    (or set by a direct client) via the X-Request-Deadline-Ms header. The
    scheduler sheds the request if it is still queued when this budget runs
    out — work that cannot meet its deadline must not burn a prefill."""
    raw = request.headers.get("X-Request-Deadline-Ms")
    if not raw:
        return None
    try:
        ms = float(raw)
    except ValueError:
        raise ValueError("X-Request-Deadline-Ms must be a number")
    if ms <= 0:
        raise ValueError("X-Request-Deadline-Ms must be positive")
    return ms


def _speculative_from(body: dict) -> dict | None:
    """Per-request speculative-decoding knobs (an OpenAI-dialect extension,
    also carried through the Anthropic adapter): `speculative: {enabled,
    max_draft_tokens}`. Absent → engine defaults (--spec-decode /
    LLMLB_SPEC_*). Validated here so a malformed knob 400s instead of being
    silently ignored at the scheduler."""
    spec = body.get("speculative")
    if spec is None:
        return None
    if not isinstance(spec, dict):
        raise ValueError("'speculative' must be an object")
    out: dict = {}
    if "enabled" in spec:
        if not isinstance(spec["enabled"], bool):
            raise ValueError("'speculative.enabled' must be a boolean")
        out["enabled"] = spec["enabled"]
    if spec.get("max_draft_tokens") is not None:
        k = spec["max_draft_tokens"]
        if not isinstance(k, int) or isinstance(k, bool) or k < 1:
            raise ValueError(
                "'speculative.max_draft_tokens' must be a positive integer"
            )
        out["max_draft_tokens"] = k
    return out or None


def _handoff_tokens_from(body: dict) -> int:
    """Tokens the prefill side commits before handing off (the committed
    window the decode engine replays). Per-request `handoff_tokens`
    overrides LLMLB_DISAGG_HANDOFF_TOKENS (default 1 — prefill + first
    token, the smallest window that proves the stream is live). Clamped to
    64: the window rides the wire and is replayed by the adopter, so an
    absurd value just moves decode work back onto the prefill pool."""
    import os

    raw = body.get("handoff_tokens")
    if raw is None:
        raw = os.environ.get("LLMLB_DISAGG_HANDOFF_TOKENS", 1)
    try:
        k = int(raw)
    except (TypeError, ValueError):
        raise ValueError("'handoff_tokens' must be an integer")
    if isinstance(body.get("handoff_tokens"), bool) or not 1 <= k <= 64:
        raise ValueError("'handoff_tokens' must be between 1 and 64")
    return k


def _stops_from(body: dict) -> list[str]:
    stop = body.get("stop") or body.get("stop_sequences") or []
    if isinstance(stop, str):
        return [stop]
    return [s for s in stop if isinstance(s, str)]


def _usage(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


async def _sse_send(resp: web.StreamResponse, payload: dict | str) -> None:
    if isinstance(payload, str):
        data = payload
    else:
        data = json.dumps(payload, separators=(",", ":"))
    await resp.write(f"data: {data}\n\n".encode())


def _drain_grace_from_env() -> float:
    import os

    raw = os.environ.get("LLMLB_DRAIN_GRACE_S")
    if raw:
        try:
            return max(0.0, float(raw))
        except ValueError:
            log.warning("LLMLB_DRAIN_GRACE_S=%r is not a number; using 30",
                        raw)
    return 30.0


class DrainController:
    """Graceful engine drain (docs/deployment.md rolling-restart runbook).

    SIGTERM (via the aiohttp shutdown hook) and ``POST /api/drain`` both land
    here: the server flips to draining — new /v1 admissions 503 with an
    honest Retry-After, /api/health advertises ``draining`` so the gateway's
    health checker re-routes within one probe — while in-flight decodes get
    ``LLMLB_DRAIN_GRACE_S`` to finish. Anything still running when the grace
    expires is parked through the PR 10 park path (pages freed, resume state
    captured, counted in llmlb_engine_drain_parked_total) and its client
    connection hard-aborted, so the GATEWAY's mid-stream resume replays the
    committed tokens onto another engine. Drain is one-way: the process is
    expected to exit (SIGTERM) or be restarted by its supervisor."""

    def __init__(self, engine: Engine, grace_s: float | None = None):
        self.engine = engine
        self.grace_s = (_drain_grace_from_env()
                        if grace_s is None else max(0.0, float(grace_s)))
        self.draining = False
        self.started_at = 0.0
        self.parked = 0
        self.aborted_connections = 0
        # transports of in-flight POST /v1/* requests (the drain middleware
        # maintains this); aborting them after the grace is what turns a
        # straggler into a gateway-visible cut the resume path picks up
        self._streams: set = set()
        self._task: "asyncio.Task | None" = None

    # ------------------------------------------------------------- middleware

    def track(self, transport) -> None:
        if transport is not None:
            self._streams.add(transport)

    def untrack(self, transport) -> None:
        self._streams.discard(transport)

    def remaining_s(self) -> float:
        if not self.draining:
            return self.grace_s
        return max(0.0, self.started_at + self.grace_s - time.monotonic())

    def retry_after_s(self) -> int:
        """Honest Retry-After for a refused admission: the drain grace still
        remaining — after that this process is gone and its replacement (or
        the rest of the fleet) is the right target."""
        return max(1, int(self.remaining_s() + 0.999))

    def info(self) -> dict:
        return {
            "draining": self.draining,
            "grace_s": self.grace_s,
            "remaining_s": round(self.remaining_s(), 3),
            "active_streams": len(self._streams),
            "parked": self.parked,
            "aborted_connections": self.aborted_connections,
        }

    # ------------------------------------------------------------------ drain

    def start(self, grace_s: float | None = None) -> dict:
        """Begin draining (idempotent). Returns the current drain info."""
        if not self.draining:
            if grace_s is not None:
                self.grace_s = max(0.0, float(grace_s))
            self.draining = True
            self.started_at = time.monotonic()
            core = self.engine.core
            core.begin_drain()
            core.metrics.set_drain_state(1)
            log.info("drain started: %d in-flight stream(s), grace %.1fs",
                     len(self._streams), self.grace_s)
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="engine-drain"
            )
        return self.info()

    async def wait(self) -> None:
        if self._task is not None:
            await self._task

    async def _run(self) -> None:
        core = self.engine.core
        deadline = self.started_at + self.grace_s
        while time.monotonic() < deadline:
            if not self._streams and core.stats().active_slots == 0:
                log.info("drain complete: all in-flight work finished "
                         "within the grace")
                return
            await asyncio.sleep(0.05)
        # Grace spent: park what is still decoding (the step loop executes
        # the parks — slot state is loop-thread-owned) so the committed
        # tokens are accounted, then hard-abort the surviving connections.
        # The gateway sees each abort as a mid-stream cut and resumes the
        # stream on another engine from its own replay ledger.
        before = core.metrics.drain_parked_total
        core.request_drain_park()
        # wait for the step loop to CONSUME the park request (it may be
        # inside a long dispatch/compile), then briefly for the parks to
        # settle — a fixed short wait here under-reported `parked` whenever
        # a dispatch outlived it. Bounded: a wedged loop must not stall the
        # aborts (and the shutdown behind them) forever.
        flag_deadline = time.monotonic() + 30.0
        while (core._drain_park_requested
               and time.monotonic() < flag_deadline):
            await asyncio.sleep(0.02)
        settle_deadline = time.monotonic() + 2.0
        while (time.monotonic() < settle_deadline
               and core.stats().active_slots > 0):
            await asyncio.sleep(0.02)
        self.parked = core.metrics.drain_parked_total - before
        stragglers = list(self._streams)
        for transport in stragglers:
            try:
                transport.abort()
            except Exception:  # allow-silent: best-effort teardown of a
                # transport that may already be closing under us
                pass
        self.aborted_connections = len(stragglers)
        # AFTER the aborts: terminal-error everything still queued (parked
        # work included) so the handlers blocked on those event queues
        # unblock — their farewell frames can no longer reach a client (the
        # sockets are gone), and the gateway resumes from its own ledger.
        core.request_drain_flush()
        if stragglers or self.parked:
            log.warning(
                "drain grace expired: parked %d slot(s), aborted %d "
                "connection(s) for gateway-side resume",
                self.parked, len(stragglers),
            )


class EngineAPI:
    def __init__(self, engine: Engine, *, asr=None, tts=None, image=None):
        self.engine = engine
        self.asr = asr  # engine.asr.AsrEngine | None
        self.tts = tts  # engine.tts.TtsEngine | None
        self.image = image  # engine.image.ImageEngine | None
        # one capture at a time: the manager guards the global jax tracer
        self.profiles = ProfileManager()
        # graceful drain (SIGTERM / POST /api/drain): admission gate +
        # in-flight connection ledger (docs/deployment.md)
        self.drain = DrainController(engine)

    # ------------------------------------------------------------- inventory

    async def list_models(self, request: web.Request) -> web.Response:
        # structured_outputs: grammar-constrained decoding is a property of
        # the engine (llmlb_tpu/structured), advertised so the gateway's
        # capability routing steers constrained requests here and away from
        # endpoints that would ignore response_format.
        caps = ["chat_completion", "structured_outputs"]
        if self.engine.supports_embeddings():
            caps.append("embeddings")
        # Disaggregation roles ride the capability list (the structured-
        # outputs advertisement is the template): the gateway's role-aware
        # balancer steers prefill-heavy requests toward "prefill"-capable
        # endpoints and handoff adoption toward "decode"-capable ones
        # (docs/disaggregation.md).
        role = self.engine.core.role
        if role in ("both", "split", "prefill"):
            caps.append("prefill")
        if role in ("both", "split", "decode"):
            caps.append("decode")
        # Multi-LoRA (docs/lora.md): "lora" on the BASE entry means "this
        # endpoint can hot-load any adapter in its store"; each RESIDENT
        # adapter additionally advertises as its own model entry
        # `base:adapter`, so the gateway's model sync routes adapter
        # traffic to endpoints where it is already hot and falls back to
        # any lora-capable endpoint (triggering a hot-load) before 404ing.
        lora_mgr = self.engine.core.lora
        if lora_mgr is not None:
            caps.append("lora")

        def entry(model_id: str, caps: list[str]) -> dict:
            return {
                "id": model_id,
                "object": "model",
                "created": 0,
                "owned_by": "llmlb_tpu",
                # advertised so the gateway's model sync can assign
                # capabilities without name heuristics
                "capabilities": caps,
            }

        main_entry = entry(self.engine.model_id, caps)
        main_entry["role"] = role
        data = [main_entry]
        if lora_mgr is not None:
            for name in lora_mgr.resident_names():
                adapter_entry = entry(
                    f"{self.engine.model_id}:{name}",
                    [c for c in caps if c != "embeddings"],
                )
                adapter_entry["role"] = role
                adapter_entry["lora"] = name
                data.append(adapter_entry)
        if self.asr is not None:
            data.append(entry(self.asr.model_id, ["audio_transcription"]))
        if self.tts is not None:
            data.append(entry(self.tts.model_id, ["audio_speech"]))
        if self.image is not None:
            data.append(entry(self.image.model_id, ["image_generation"]))
        return web.json_response({"object": "list", "data": data})

    # ------------------------------------------------------------ multimodal

    async def audio_transcriptions(self, request: web.Request) -> web.Response:
        """OpenAI /v1/audio/transcriptions: multipart form with `file` (WAV)."""
        if self.asr is None:
            return _error(404, "no transcription model is loaded on this engine")
        if not (request.content_type or "").startswith("multipart/"):
            return _error(400, "multipart/form-data body required")
        file_bytes = None
        async for part in await request.multipart():
            if part.name == "file":
                file_bytes = await part.read(decode=False)
            else:
                await part.read(decode=False)  # drain model/language/etc.
        if not file_bytes:
            return _error(400, "'file' part is required")
        loop = asyncio.get_running_loop()
        try:
            text = await loop.run_in_executor(
                None, self.asr.transcribe_wav_bytes, file_bytes
            )
        except (ValueError, EOFError) as e:
            return _error(400, f"could not decode audio: {e}")
        return web.json_response({"text": text})

    async def audio_speech(self, request: web.Request) -> web.Response:
        """OpenAI /v1/audio/speech: JSON {input, voice, speed} -> WAV bytes."""
        if self.tts is None:
            return _error(404, "no speech model is loaded on this engine")
        body = await request.json()
        text = body.get("input")
        if not isinstance(text, str) or not text:
            return _error(400, "'input' is required")
        voice = str(body.get("voice", "alloy"))
        speed = float(body.get("speed", 1.0))
        loop = asyncio.get_running_loop()
        try:
            wav = await loop.run_in_executor(
                None, self.tts.synthesize, text, voice, speed
            )
        except ValueError as e:
            return _error(400, str(e))
        return web.Response(body=wav, content_type="audio/wav")

    async def images_generations(self, request: web.Request) -> web.Response:
        """OpenAI /v1/images/generations: JSON {prompt, n} -> b64 PNGs."""
        if self.image is None:
            return _error(404, "no image model is loaded on this engine")
        body = await request.json()
        prompt = body.get("prompt")
        if not isinstance(prompt, str) or not prompt:
            return _error(400, "'prompt' is required")
        n = body.get("n", 1)
        if not isinstance(n, int) or not 1 <= n <= 10:
            return _error(400, "'n' must be between 1 and 10")
        loop = asyncio.get_running_loop()
        try:
            images = await loop.run_in_executor(
                None, self.image.generate_b64, prompt, n
            )
        except ValueError as e:
            return _error(400, str(e))
        return web.json_response({
            "created": int(time.time()),
            "data": [{"b64_json": b} for b in images],
        })

    async def embeddings(self, request: web.Request) -> web.Response:
        """OpenAI /v1/embeddings: input may be a string, list of strings, or
        list of token-id lists."""
        body = await request.json()
        raw = body.get("input")
        if raw is None:
            return _error(400, "'input' is required")
        if isinstance(raw, str):
            inputs = [raw]
        elif isinstance(raw, list) and raw and all(
            isinstance(x, int) for x in raw
        ):
            inputs = [raw]  # single pre-tokenized input
        elif isinstance(raw, list) and raw:
            inputs = raw
        else:
            return _error(400, "'input' must be a non-empty string or array")

        batch_ids: list[list[int]] = []
        for item in inputs:
            if isinstance(item, str):
                batch_ids.append(self.engine.tokenizer.encode(item))
            elif isinstance(item, list) and all(isinstance(x, int) for x in item):
                batch_ids.append([int(x) for x in item])
            else:
                return _error(400, "each input must be a string or token array")
        try:
            vectors = await self.engine.embed(batch_ids)
        except ValueError as e:
            return _error(400, str(e))
        prompt_tokens = sum(len(x) for x in batch_ids)
        return web.json_response(
            {
                "object": "list",
                "model": body.get("model", self.engine.model_id),
                "data": [
                    {"object": "embedding", "index": i, "embedding": vec}
                    for i, vec in enumerate(vectors)
                ],
                "usage": {
                    "prompt_tokens": prompt_tokens,
                    "total_tokens": prompt_tokens,
                },
            }
        )

    async def health(self, request: web.Request) -> web.Response:
        body = self.engine.health()
        if self.drain.draining:
            # the gateway's health checker re-parses this on EVERY probe and
            # flips the endpoint out of selection within one interval
            body["status"] = "draining"
        body["draining"] = self.drain.info()
        # KV page shipping + host-RAM offload tier (docs/kv-cache.md)
        body["kv_transfer"] = self.engine.core.kv_transfer_info()
        return web.json_response(body)

    async def kv_export(self, request: web.Request) -> web.Response:
        """POST /v1/kv/export {"request_id": <gateway id>} — hand over a
        DRAINING engine's parked-stream KV pages (docs/kv-cache.md). The
        gateway fetches this between drain-park and /v1/resume on the
        adopter, so the mid-stream failover moves bytes instead of
        re-prefilling. One-shot: the payload is consumed by the fetch. 404
        when there is nothing for that id (never an error path for the
        resume — the gateway just falls back to plain replay).

        With {"park": true} (the rebalancer's proactive migration,
        docs/resilience.md) the engine is asked to park that ONE stream
        first: the step loop spills its KV at the next iteration and this
        handler polls briefly for the payload. 404 past the poll window
        means the stream was unparkable (mid-prefill, already finished) —
        the migration aborts with the origin stream untouched."""
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        rid = body.get("request_id") if isinstance(body, dict) else None
        if not isinstance(rid, str) or not rid:
            return _error(400, "'request_id' must be a non-empty string")
        core = self.engine.core
        if body.get("park"):
            core.request_park(rid)
            deadline = time.monotonic() + 2.0
            payload = core.take_kv_export(rid)
            while payload is None and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
                payload = core.take_kv_export(rid)
        else:
            payload = core.take_kv_export(rid)
        if payload is None:
            return _error(404, f"no KV export held for request {rid!r}")
        return web.json_response(
            {"object": "llmlb.kv_export", "request_id": rid,
             "kv_pages": payload}
        )

    async def drain_control(self, request: web.Request) -> web.Response:
        """POST /api/drain — begin a graceful drain (docs/deployment.md):
        new admissions 503 with Retry-After, in-flight decodes get the grace
        (optional body {"grace_s": N} overrides LLMLB_DRAIN_GRACE_S), then
        stragglers are parked and their connections closed so the gateway's
        mid-stream resume moves them to another engine. Idempotent; poll
        GET /api/health for progress."""
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        if not isinstance(body, dict):
            return _error(400, "body must be a JSON object")
        grace = body.get("grace_s")
        if grace is not None and (isinstance(grace, bool)
                                  or not isinstance(grace, (int, float))
                                  or grace < 0):
            return _error(400, "'grace_s' must be a non-negative number")
        return web.json_response(self.drain.start(grace))

    async def prometheus_metrics(self, request: web.Request) -> web.Response:
        """GET /metrics — Prometheus exposition of the serving loop
        (TTFT/ITL histograms, token/request counters, queue depth)."""
        core = self.engine.core
        stats = core.stats()
        text = core.metrics.render(
            queue_depth=stats.queued, active_slots=stats.active_slots,
            num_slots=stats.num_slots, prefix_cache=core.prefix_cache_info(),
            kv_cache=core.kv_cache_info(), structured=core.structured_info(),
            perf=core.perf_info(), quant=core.quant_info(),
            sched=core.sched_info(), lora=core.lora_info(),
            flightrec=core.flightrec.counters(),
            kv_offload=core.kv_transfer_info()["offload"],
        )
        return web.Response(
            text=text, content_type="text/plain", charset="utf-8"
        )

    async def system(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "name": "llmlb_tpu-engine",
                "version": __version__,
                "tpu_engine": True,
                "model": self.engine.model_id,
                "prefix_cache": self.engine.core.prefix_cache_info(),
                # paged mode reports live page-pool utilization; dense mode
                # the static slot-cache footprint
                "kv_cache": self.engine.core.kv_cache_info(),
                # int8 quantization knobs + honest byte footprints
                "quant": self.engine.core.quant_info(),
                "structured": self.engine.core.structured_info(),
                # speculative decoding: config + live acceptance figures
                "spec": self.engine.core.spec_info(),
                # overload protection: priority queues, preemption counters
                "sched": self.engine.core.sched_info(),
                # disaggregated prefill/decode: role + handoff counters
                "disagg": self.engine.core.disagg_info(),
                # KV page shipping + host-RAM offload tier (docs/kv-cache.md)
                "kv_transfer": self.engine.core.kv_transfer_info(),
                # multi-LoRA adapter pool (docs/lora.md)
                "lora": self.engine.core.lora_info(),
                # graceful drain state (docs/deployment.md)
                "draining": self.drain.info(),
                # live roofline: MFU / HBM-bandwidth utilization against the
                # chip's peak specs (available only on chips in the table
                # and once decode traffic has flowed)
                "perf": self.engine.core.perf_info(),
            }
        )

    async def steps(self, request: web.Request) -> web.Response:
        """GET /api/steps — the step-loop introspection surface: recent
        per-step phase breakdowns (plan / host_sync / dispatch / compute /
        fetch / emit), per-kind EMA baselines, and slow-step anomalies.
        `?limit=N` bounds the record count (default 64, max ring size);
        `?slow=1` returns only anomalous steps."""
        core = self.engine.core
        try:
            limit = int(request.query.get("limit", 64))
        except ValueError:
            return _error(400, "'limit' must be an integer")
        slow_only = request.query.get("slow", "") in ("1", "true", "yes")
        body = core.step_stats.snapshot(limit=limit, slow_only=slow_only)
        body["perf"] = core.perf_info()
        body["flightrec"] = core.flightrec.counters()
        return web.json_response(body)

    async def request_timeline(self, request: web.Request) -> web.Response:
        """GET /api/requests/{request_id}/timeline — one request's flight
        record: every lifecycle event this engine (plus any spool siblings)
        recorded for the gateway-minted X-Request-Id, sorted causally. The
        gateway's /api/traces/{id}?view=timeline merges this across every
        engine the request touched (docs/tracing.md)."""
        rid = request.match_info["request_id"]
        core = self.engine.core
        if not core.flightrec.enabled:
            return _error(404, "flight recorder disabled (LLMLB_FLIGHTREC=0)")
        body = core.flightrec.timeline(rid)
        if body is None:
            return _error(404, f"no flight record for request '{rid}'")
        return web.json_response(body)

    # ------------------------------------------------------------- profiling

    @staticmethod
    def _profile_authorized(request: web.Request) -> bool:
        """Capture gating: when LLMLB_PROFILE_TOKEN is set, profile control
        and artifact download require `Authorization: Bearer <token>` — the
        admin gate for a port that is otherwise auth-free by design."""
        import os

        token = os.environ.get("LLMLB_PROFILE_TOKEN")
        if not token:
            return True
        authz = request.headers.get("Authorization", "")
        return authz == f"Bearer {token}"

    async def profile_control(self, request: web.Request) -> web.Response:
        """POST /api/profile — on-demand jax.profiler capture of the live
        serving loop. Body: {"action": "start", "seconds": N} begins a
        capture with a bounded auto-stop (max 60s); {"action": "stop"} ends
        it early. The completed capture is downloadable as a zip at
        GET /api/profile/{capture_id} (docs/profiling.md)."""
        if not self._profile_authorized(request):
            return _error(401, "profile capture requires the profile token",
                          "authentication_error")
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        if not isinstance(body, dict):
            return _error(400, "body must be a JSON object")
        action = body.get("action", "start")
        try:
            if action == "start":
                try:
                    seconds = float(body.get("seconds", 3.0))
                except (TypeError, ValueError):
                    return _error(400, "'seconds' must be a number")
                started = self.profiles.start(seconds)
                return web.json_response({"started": True, **started})
            if action == "stop":
                # stop serializes the whole trace — worker thread, so the
                # event loop (and every in-flight stream) stays responsive
                loop = asyncio.get_running_loop()
                done = await loop.run_in_executor(None, self.profiles.stop)
                return web.json_response({"stopped": True, **done})
        except ProfileError as e:
            return _error(e.status, str(e),
                          "server_error" if e.status >= 500
                          else "invalid_request_error")
        return _error(400, "'action' must be 'start' or 'stop'")

    async def profile_status(self, request: web.Request) -> web.Response:
        """GET /api/profile — capture state + completed-capture ledger."""
        if not self._profile_authorized(request):
            return _error(401, "profile status requires the profile token",
                          "authentication_error")
        return web.json_response(self.profiles.status())

    async def profile_artifact(self, request: web.Request) -> web.StreamResponse:
        """GET /api/profile/{capture_id} — the downloadable trace artifact:
        a zip of the capture's trace directory, unpackable for
        `tensorboard --logdir` / xprof. Built on disk in a worker thread
        (TPU traces run to hundreds of MB) and streamed from the file."""
        if not self._profile_authorized(request):
            return _error(401, "profile download requires the profile token",
                          "authentication_error")
        loop = asyncio.get_running_loop()
        try:
            path, filename = await loop.run_in_executor(
                None, self.profiles.artifact,
                request.match_info["capture_id"],
            )
        except ProfileError as e:
            return _error(e.status, str(e))
        return web.FileResponse(
            path,
            headers={"Content-Type": "application/zip",
                     "Content-Disposition":
                     f'attachment; filename="{filename}"'},
        )

    async def debug_profile(self, request: web.Request) -> web.Response:
        """POST /debug/profile {"seconds": N} — the original one-shot form:
        start a capture, wait out its bounded duration, return the trace
        directory. Kept for operators and scripts that predate the
        start/stop /api/profile surface; both share one ProfileManager, so
        they can never double-start the global tracer."""
        if not self._profile_authorized(request):
            return _error(401, "profile capture requires the profile token",
                          "authentication_error")
        try:
            body = await request.json() if request.can_read_body else {}
        except Exception:
            body = {}
        if not isinstance(body, dict):
            return _error(400, "body must be a JSON object")
        try:
            seconds = min(30.0, max(0.1, float(body.get("seconds", 3.0))))
        except (TypeError, ValueError):
            return _error(400, "'seconds' must be a number")
        try:
            started = self.profiles.start(seconds)
        except ProfileError as e:
            return _error(e.status, str(e))
        # the bounded auto-stop ends the capture even if the client leaves;
        # this handler waits for the stop event itself (worker thread — no
        # poll loop, and the event loop stays free for in-flight streams)
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, self.profiles.wait_idle, seconds + 30.0
        )
        return web.json_response({
            "trace_dir": started["trace_dir"],
            "seconds": started["seconds"],
            "capture_id": started["capture_id"],
            "hint": "tensorboard --logdir <trace_dir> (profile plugin)",
        })

    # ------------------------------------------------------ chat completions

    def _parse_chat(self, request: web.Request, body: dict):
        """Shared chat-request parse (chat_completions + the handoff-prefill
        endpoint, which accepts the same body): returns (prompt_ids,
        sampling, stops, tool_name, model). Raises ValueError for anything
        malformed — callers turn that into a 400 naming the field."""
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            raise ValueError("'messages' must be a non-empty array")
        if int(body.get("n") or 1) != 1:
            raise ValueError("only n=1 is supported")
        model = body.get("model") or self.engine.model_id
        try:
            prompt_ids = self.engine.encode_chat(messages)
        except ValueError:
            raise
        except Exception as e:
            raise ValueError(f"failed to encode messages: {e}")
        # Structured outputs: response_format (json_object / json_schema) or
        # a forced tool_choice compile to a grammar constraint the scheduler
        # enforces token by token. Malformed or uncompilable requests 400
        # here with the offending feature named.
        structured = inspect_request(body)
        sampling = _sampling_from(body)
        sampling.seed = parse_seed(body)
        sampling.deadline_ms = _deadline_from(request)
        if structured is not None:
            sampling.constraint = structured.spec
        tool_name = structured.tool_name if structured is not None else None
        # Multi-LoRA (docs/lora.md): adapter via the `lora` field or the
        # `model:adapter` suffix (suffix considered only on LoRA-enabled
        # engines — a colon in a model name stays inert otherwise).
        # Unknown/invalid adapters 400 here with the field named, before a
        # stream response could start.
        adapter, base = self._parse_lora(body)
        if adapter is not None:
            sampling.lora = adapter
            model = base or model
        return prompt_ids, sampling, _stops_from(body), tool_name, model

    def _parse_lora(self, body: dict) -> tuple[str | None, str | None]:
        """(adapter, base_model) from a request body, validated against this
        engine's adapter store. Raises ValueError naming the `lora` field —
        the shared contract with the gateway's inspect path
        (llmlb_tpu/lora/api.py)."""
        from llmlb_tpu.lora import adapter_from_body

        core = self.engine.core
        if body.get("lora") is None and core.lora is None:
            return None, None
        if core.lora is None:
            raise ValueError(
                "'lora' adapters are not enabled on this engine "
                "(start it with --lora-dir)"
            )
        base, adapter = adapter_from_body(body)
        if adapter is None:
            return None, None
        core.lora.validate(adapter)
        return adapter, base

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        try:
            prompt_ids, sampling, stops, tool_name, model = self._parse_chat(
                request, body
            )
        except ValueError as e:
            return _error(400, str(e))

        completion_id = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        rid = _request_id_from(request)

        if body.get("stream"):
            return await self._stream_chat(
                request, completion_id, created, model, prompt_ids, sampling, stops,
                include_usage=bool(
                    (body.get("stream_options") or {}).get("include_usage", True)
                ),
                request_id=rid,
                tool_name=tool_name,
                replay=bool(body.get("llmlb_replay")),
            )

        try:
            result = await self.engine.complete(prompt_ids, sampling, stops,
                                                request_id=rid)
        except EngineError as e:
            return _error(500, str(e), "server_error")
        except ValueError as e:
            return _error(400, str(e))
        return self._chat_response(completion_id, created, model, result,
                                   tool_name, rid)

    async def _stream_chat(
        self, request, completion_id, created, model, prompt_ids, sampling, stops,
        include_usage: bool, request_id: str | None = None,
        tool_name: str | None = None, agen=None, replay: bool = False,
    ) -> web.StreamResponse:
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
                **_rid_headers(request_id),
            },
        )
        await resp.prepare(request)

        def chunk(delta: dict, finish: str | None = None) -> dict:
            return {
                "id": completion_id,
                "object": "chat.completion.chunk",
                "created": created,
                "model": model,
                "system_fingerprint": SYSTEM_FINGERPRINT,
                "choices": [
                    {"index": 0, "delta": delta, "finish_reason": finish}
                ],
            }

        await _sse_send(resp, chunk({"role": "assistant", "content": ""}))
        if tool_name is not None:
            # Forced tool call: open the call in the first tool delta (id +
            # name), then stream the constrained arguments as fragments —
            # the shape OpenAI SDKs and the Anthropic stream re-encoder
            # (gateway/api_anthropic.AnthropicStreamEncoder) both consume.
            await _sse_send(resp, chunk({"tool_calls": [{
                "index": 0,
                "id": f"call_{uuid.uuid4().hex[:24]}",
                "type": "function",
                "function": {"name": tool_name, "arguments": ""},
            }]}))
        usage = _usage(len(prompt_ids), 0)
        finish = "stop"
        if agen is None:
            agen = self.engine.stream(prompt_ids, sampling, stops,
                                      request_id=request_id)
        try:
            async for delta in agen:
                if replay and delta.token_ids:
                    # Durable streams (docs/resilience.md): ship the newly
                    # committed token ids as a gateway-internal frame BEFORE
                    # the text they produced — the gateway strips these and,
                    # on a mid-stream cut, replays them onto another engine's
                    # /v1/resume so the continuation is token-identical.
                    await _sse_send(resp, {
                        "object": "llmlb.replay",
                        "tokens": [int(t) for t in delta.token_ids],
                    })
                if delta.text:
                    if tool_name is not None:
                        await _sse_send(resp, chunk({"tool_calls": [{
                            "index": 0,
                            "function": {"arguments": delta.text},
                        }]}))
                    else:
                        await _sse_send(resp, chunk({"content": delta.text}))
                if delta.finish_reason is not None:
                    finish = delta.finish_reason
                    usage = _usage(delta.prompt_tokens, delta.completion_tokens)
        except (EngineError, ValueError) as e:
            try:
                await _sse_send(resp, {"error": {"message": str(e)}})
                await resp.write(b"data: [DONE]\n\n")
            except OSError:
                # socket already gone (drain aborted it / client left): the
                # farewell has nowhere to go, and failing loudly here would
                # just re-raise into the access log
                pass
            return resp
        if tool_name is not None and finish == "stop":
            finish = "tool_calls"
        await _sse_send(resp, chunk({}, finish))
        if include_usage:
            final = chunk({}, None)
            final["choices"] = []
            final["usage"] = usage
            await _sse_send(resp, final)
        await resp.write(b"data: [DONE]\n\n")
        return resp

    # -------------------------------------------- disaggregated handoff wire

    def _chat_response(self, completion_id: str, created: int, model: str,
                       result, tool_name: str | None,
                       rid: str | None) -> web.Response:
        """Non-streaming chat.completion JSON from a collected result —
        shared by /v1/chat/completions and the handoff surfaces."""
        if tool_name is not None:
            message: dict = {
                "role": "assistant",
                "content": None,
                "tool_calls": [{
                    "id": f"call_{uuid.uuid4().hex[:24]}",
                    "type": "function",
                    "function": {"name": tool_name, "arguments": result.text},
                }],
            }
            finish = ("tool_calls" if result.finish_reason == "stop"
                      else result.finish_reason)
        else:
            message = {"role": "assistant", "content": result.text}
            finish = result.finish_reason
        return web.json_response(
            {
                "id": completion_id,
                "object": "chat.completion",
                "created": created,
                "model": model,
                "system_fingerprint": SYSTEM_FINGERPRINT,
                "choices": [
                    {"index": 0, "message": message, "finish_reason": finish}
                ],
                "usage": _usage(result.prompt_tokens,
                                result.completion_tokens),
            },
            headers=_rid_headers(rid),
        )

    async def _collect_chat_response(self, agen, completion_id: str,
                                     created: int, model: str,
                                     tool_name: str | None,
                                     rid: str | None) -> web.Response:
        """Drain a stream generator into one chat.completion JSON — the
        non-streaming tail shared by /v1/handoff adoption and /v1/resume."""
        import dataclasses as _dc

        text = []
        final = None
        try:
            async for delta in agen:
                text.append(delta.text)
                if delta.finish_reason is not None:
                    final = delta
        except EngineError as e:
            return _error(500, str(e), "server_error")
        except ValueError as e:
            return _error(400, str(e))
        assert final is not None
        result = _dc.replace(final, text="".join(text))
        return self._chat_response(completion_id, created, model, result,
                                   tool_name, rid)

    async def handoff_prefill(self, request: web.Request) -> web.Response:
        """POST /v1/handoff/prefill — the prefill-role half of the
        cross-process handoff (docs/disaggregation.md). Body: a standard
        chat-completions request plus optional `handoff_tokens` (how many
        tokens to commit before handing off; default LLMLB_DISAGG_HANDOFF_TOKENS
        or 1). Responds `{"object": "llmlb.handoff", "handoff": <wire
        payload>, "finish": str|null, ...}` — the caller POSTs the payload
        to a decode-capable engine's /v1/handoff, which streams the FULL
        completion (committed + continuation). `finish` is null while the
        stream has more to generate; when the request completed inside the
        committed window (EOS / max_tokens) it carries the natural finish —
        the adopt replay still reproduces that finish token-identically
        (EOS re-samples at the same absolute position; a spent max_tokens
        budget finishes at adoption without touching the step loop), so
        orchestrators need only one shape."""
        if self.engine.core.role == "decode":
            return _error(
                409, "this engine serves --role decode; it adopts handoffs "
                "(/v1/handoff) but does not originate them",
            )
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        try:
            prompt_ids, sampling, stops, tool_name, model = self._parse_chat(
                request, body
            )
            emit = _handoff_tokens_from(body)
        except ValueError as e:
            return _error(400, str(e))
        rid = _request_id_from(request)
        try:
            committed, finish, kv_pages = await self.engine.prefill_handoff(
                prompt_ids, sampling, emit_tokens=emit, request_id=rid
            )
        except EngineError as e:
            return _error(500, str(e), "server_error")
        except ValueError as e:
            return _error(400, str(e))
        payload = handoff_payload(
            prompt_ids, committed, sampling, stop=stops, request_id=rid,
            kv_pages=kv_pages if finish is None else None,
        )
        return web.json_response(
            {
                "object": "llmlb.handoff",
                "model": model,
                "handoff": payload,
                "finish": finish,
                "tool_name": tool_name,
                "usage": _usage(len(prompt_ids), len(committed)),
            },
            headers=_rid_headers(rid),
        )

    async def handoff_adopt(self, request: web.Request) -> web.StreamResponse:
        """POST /v1/handoff — adopt a stream a prefill engine started. Body:
        `{"handoff": <wire payload>, "stream": bool, "model": str?,
        "tool_name": str?}`. The payload replays as prompt+committed chunk
        prefill (PR 10 park/resume), so the continuation is token-identical
        to an uninterrupted run; the response carries the FULL text
        (committed + continuation) as a normal chat completion / SSE stream.
        Malformed payloads 400 via HandoffError — never a crashed step loop.
        """
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        if not isinstance(body, dict):
            return _error(400, "body must be a JSON object")
        try:
            prompt_ids, committed, sampling, stops, wire_rid, t0 = (
                parse_handoff(body.get("handoff"))
            )
        except HandoffError as e:
            return _error(400, str(e))
        tool_name = body.get("tool_name")
        if tool_name is not None and not isinstance(tool_name, str):
            return _error(400, "'tool_name' must be a string")
        model = body.get("model") or self.engine.model_id
        rid = _request_id_from(request) or wire_rid
        try:
            # the gateway recomputes the REMAINING deadline budget onto the
            # header; it overrides the wire's original (now partly spent) one
            header_deadline = _deadline_from(request)
        except ValueError as e:
            return _error(400, str(e))
        if header_deadline is not None:
            sampling.deadline_ms = header_deadline
        # pages attachment: rides the handoff envelope itself (wire.py) —
        # anything non-dict is treated as absent and the adoption replays
        kv_pages = body.get("handoff", {}).get("kv_pages")
        if not isinstance(kv_pages, dict):
            kv_pages = None
        agen = self.engine.adopt_stream(
            prompt_ids, committed, sampling, stops,
            request_id=rid, emitted_at=t0, kv_pages=kv_pages,
        )
        completion_id = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        if body.get("stream"):
            return await self._stream_chat(
                request, completion_id, created, model,
                prompt_ids, sampling, stops,
                include_usage=True, request_id=rid, tool_name=tool_name,
                agen=agen, replay=bool(body.get("llmlb_replay")),
            )
        return await self._collect_chat_response(
            agen, completion_id, created, model, tool_name, rid
        )

    async def resume(self, request: web.Request) -> web.StreamResponse:
        """POST /v1/resume — continue a stream another engine started, from
        the ORIGINAL chat body plus the token ids already committed (durable
        streams, docs/resilience.md). This engine re-encodes the prompt with
        its own tokenizer (identical across engines serving one model),
        replays prompt+committed as a chunk prefill (the PR 10/11 park/adopt
        path — KV lands at identical absolute positions, greedy and seeded
        continuations are token-identical), and streams the FULL completion
        (committed + continuation) in the normal chat-completions shape; the
        gateway splices off the prefix its client already holds. Unlike
        /v1/handoff there is no wire sampling block: the chat body is the
        contract, so any tpu:// engine can adopt regardless of role."""
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        if not isinstance(body, dict):
            return _error(400, "body must be a JSON object")
        committed = body.get("committed_ids")
        if committed is None:
            committed = []
        if not isinstance(committed, list) or any(
            isinstance(t, bool) or not isinstance(t, int) for t in committed
        ):
            return _error(400, "'committed_ids' must be a list of token ids")
        try:
            prompt_ids, sampling, stops, tool_name, model = self._parse_chat(
                request, body
            )
        except ValueError as e:
            return _error(400, str(e))
        rid = _request_id_from(request)
        # optional pages payload pre-fetched by the gateway from the
        # draining origin's /v1/kv/export — lands instead of replaying
        kv_pages = body.get("kv_pages")
        if not isinstance(kv_pages, dict):
            kv_pages = None
        agen = self.engine.adopt_stream(
            prompt_ids, [int(t) for t in committed], sampling, stops,
            request_id=rid, kv_pages=kv_pages,
        )
        completion_id = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        if body.get("stream"):
            return await self._stream_chat(
                request, completion_id, created, model, prompt_ids, sampling,
                stops, include_usage=True, request_id=rid,
                tool_name=tool_name, agen=agen,
                replay=bool(body.get("llmlb_replay")),
            )
        return await self._collect_chat_response(
            agen, completion_id, created, model, tool_name, rid
        )

    # ----------------------------------------------------------- completions

    async def completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        prompt = body.get("prompt")
        if isinstance(prompt, list):
            if len(prompt) != 1 or not isinstance(prompt[0], str):
                return _error(400, "only a single string prompt is supported")
            prompt = prompt[0]
        if not isinstance(prompt, str) or not prompt:
            return _error(400, "'prompt' must be a non-empty string")
        model = body.get("model") or self.engine.model_id
        prompt_ids = self.engine.tokenizer.encode(prompt)
        sampling = _sampling_from(body, default_max=16)
        sampling.deadline_ms = _deadline_from(request)  # middleware 400s bad values
        adapter, base = self._parse_lora(body)  # middleware 400s bad values
        if adapter is not None:
            sampling.lora = adapter
            model = base or model
        stops = _stops_from(body)
        completion_id = f"cmpl-{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        rid = _request_id_from(request)

        if body.get("stream"):
            resp = web.StreamResponse(
                status=200, headers={"Content-Type": "text/event-stream",
                                     **_rid_headers(rid)}
            )
            await resp.prepare(request)
            usage = _usage(len(prompt_ids), 0)
            finish = "stop"
            try:
                async for delta in self.engine.stream(prompt_ids, sampling,
                                                      stops, request_id=rid):
                    if delta.finish_reason is not None:
                        finish = delta.finish_reason
                        usage = _usage(delta.prompt_tokens, delta.completion_tokens)
                    if delta.text:
                        await _sse_send(
                            resp,
                            {
                                "id": completion_id,
                                "object": "text_completion",
                                "created": created,
                                "model": model,
                                "choices": [
                                    {"index": 0, "text": delta.text,
                                     "finish_reason": None}
                                ],
                            },
                        )
            except (EngineError, ValueError) as e:
                await _sse_send(resp, {"error": {"message": str(e)}})
                await resp.write(b"data: [DONE]\n\n")
                return resp
            await _sse_send(
                resp,
                {
                    "id": completion_id,
                    "object": "text_completion",
                    "created": created,
                    "model": model,
                    "choices": [{"index": 0, "text": "", "finish_reason": finish}],
                    "usage": usage,
                },
            )
            await resp.write(b"data: [DONE]\n\n")
            return resp

        result = await self.engine.complete(prompt_ids, sampling, stops,
                                            request_id=rid)
        return web.json_response(
            {
                "id": completion_id,
                "object": "text_completion",
                "created": created,
                "model": model,
                "choices": [
                    {
                        "index": 0,
                        "text": result.text,
                        "finish_reason": result.finish_reason,
                    }
                ],
                "usage": _usage(result.prompt_tokens, result.completion_tokens),
            }
        )

    # ------------------------------------------------------------- responses

    async def responses(self, request: web.Request) -> web.StreamResponse:
        """OpenAI Responses API — the reference's recommended text path."""
        try:
            body = await request.json()
        except Exception:
            return _error(400, "invalid JSON body")
        model = body.get("model") or self.engine.model_id
        input_ = body.get("input")
        if isinstance(input_, str):
            messages = [{"role": "user", "content": input_}]
        elif isinstance(input_, list):
            messages = [
                {"role": m.get("role", "user"), "content": m.get("content", "")}
                for m in input_
                if isinstance(m, dict)
            ]
        else:
            return _error(400, "'input' must be a string or message array")
        if body.get("instructions"):
            messages = [{"role": "system", "content": body["instructions"]}] + messages

        prompt_ids = self.engine.encode_chat(messages)
        sampling = _sampling_from(body)
        sampling.deadline_ms = _deadline_from(request)
        response_id = f"resp_{uuid.uuid4().hex[:24]}"
        created = int(time.time())
        rid = _request_id_from(request)

        def envelope(status: str, text: str, usage: dict | None) -> dict:
            return {
                "id": response_id,
                "object": "response",
                "created_at": created,
                "status": status,
                "model": model,
                "output": [
                    {
                        "type": "message",
                        "id": f"msg_{response_id}",
                        "role": "assistant",
                        "status": status,
                        "content": [
                            {"type": "output_text", "text": text, "annotations": []}
                        ],
                    }
                ],
                "usage": usage
                or {"input_tokens": 0, "output_tokens": 0, "total_tokens": 0},
            }

        if body.get("stream"):
            resp = web.StreamResponse(
                status=200, headers={"Content-Type": "text/event-stream",
                                     **_rid_headers(rid)}
            )
            await resp.prepare(request)

            async def event(name: str, payload: dict) -> None:
                data = json.dumps(payload, separators=(",", ":"))
                await resp.write(f"event: {name}\ndata: {data}\n\n".encode())

            await event(
                "response.created",
                {"type": "response.created",
                 "response": envelope("in_progress", "", None)},
            )
            text_parts: list[str] = []
            usage = None
            try:
                async for delta in self.engine.stream(
                    prompt_ids, sampling, _stops_from(body), request_id=rid
                ):
                    if delta.text:
                        text_parts.append(delta.text)
                        await event(
                            "response.output_text.delta",
                            {
                                "type": "response.output_text.delta",
                                "item_id": f"msg_{response_id}",
                                "output_index": 0,
                                "content_index": 0,
                                "delta": delta.text,
                            },
                        )
                    if delta.finish_reason is not None:
                        usage = {
                            "input_tokens": delta.prompt_tokens,
                            "output_tokens": delta.completion_tokens,
                            "total_tokens": (
                                delta.prompt_tokens + delta.completion_tokens
                            ),
                        }
            except (EngineError, ValueError) as e:
                await event(
                    "response.failed",
                    {
                        "type": "response.failed",
                        "response": {
                            "id": response_id,
                            "object": "response",
                            "status": "failed",
                            "error": {"message": str(e)},
                        },
                    },
                )
                return resp
            await event(
                "response.completed",
                {
                    "type": "response.completed",
                    "response": envelope("completed", "".join(text_parts), usage),
                },
            )
            return resp

        result = await self.engine.complete(prompt_ids, sampling,
                                            _stops_from(body), request_id=rid)
        usage = {
            "input_tokens": result.prompt_tokens,
            "output_tokens": result.completion_tokens,
            "total_tokens": result.prompt_tokens + result.completion_tokens,
        }
        return web.json_response(envelope("completed", result.text, usage),
                                 headers=_rid_headers(rid))


@web.middleware
async def error_middleware(request: web.Request, handler):
    """Normalize engine/validation failures to OpenAI-style JSON errors."""
    try:
        return await handler(request)
    except web.HTTPException:
        raise
    except ValueError as e:
        return _error(400, str(e))
    except EngineError as e:
        return _error(500, str(e), "server_error")
    except Exception:
        log.exception("unhandled error serving %s", request.path)
        return _error(500, "internal server error", "server_error")


def create_engine_app(engine: Engine, *, owns_engine: bool = True,
                      asr=None, tts=None, image=None) -> web.Application:
    api = EngineAPI(engine, asr=asr, tts=tts, image=image)

    @web.middleware
    async def drain_middleware(request: web.Request, handler):
        """Admission gate + in-flight ledger for graceful drain: while
        draining, new /v1 work 503s with an honest Retry-After (the grace
        remaining); accepted /v1 POSTs register their transport so the
        post-grace abort can cut stragglers for gateway-side resume. Read
        surfaces (/api/health, /metrics) always answer — the health checker
        must be able to see the draining advertisement."""
        if (request.method == "POST" and request.path.startswith("/v1/")
                and request.path != "/v1/kv/export"):
            # /v1/kv/export is exempt on purpose: it exists FOR the drain
            # window — the gateway collects parked KV pages from a draining
            # engine before resuming the stream elsewhere
            drain = api.drain
            if drain.draining:
                return web.json_response(
                    {"error": {
                        "message": "engine is draining; retry on another "
                                   "endpoint",
                        "type": "overloaded_error", "code": "draining",
                    }},
                    status=503,
                    headers={"Retry-After": str(drain.retry_after_s())},
                )
            drain.track(request.transport)
            try:
                return await handler(request)
            finally:
                drain.untrack(request.transport)
        return await handler(request)

    app = web.Application(client_max_size=KV_BODY_BYTES,
                          middlewares=[error_middleware, drain_middleware])
    app.router.add_get("/v1/models", api.list_models)
    app.router.add_post("/v1/chat/completions", api.chat_completions)
    app.router.add_post("/v1/handoff", api.handoff_adopt)
    app.router.add_post("/v1/handoff/prefill", api.handoff_prefill)
    app.router.add_post("/v1/resume", api.resume)
    app.router.add_post("/v1/kv/export", api.kv_export)
    app.router.add_post("/v1/completions", api.completions)
    app.router.add_post("/v1/responses", api.responses)
    app.router.add_post("/v1/embeddings", api.embeddings)
    app.router.add_post("/v1/audio/transcriptions", api.audio_transcriptions)
    app.router.add_post("/v1/audio/speech", api.audio_speech)
    app.router.add_post("/v1/images/generations", api.images_generations)
    app.router.add_get("/api/health", api.health)
    app.router.add_post("/api/drain", api.drain_control)
    app.router.add_get("/metrics", api.prometheus_metrics)
    app.router.add_get("/api/system", api.system)
    app.router.add_get("/api/steps", api.steps)
    app.router.add_get("/api/requests/{request_id}/timeline",
                       api.request_timeline)
    app.router.add_post("/api/profile", api.profile_control)
    app.router.add_get("/api/profile", api.profile_status)
    app.router.add_get("/api/profile/{capture_id}", api.profile_artifact)
    app.router.add_post("/debug/profile", api.debug_profile)

    if owns_engine:
        async def on_shutdown(app):
            # Graceful path first (SIGTERM lands here through aiohttp's
            # shutdown hooks): drain — wait the grace for in-flight decodes,
            # park the rest, abort their connections for gateway-side
            # resume — and only THEN tear the engine core down.
            # engine.shutdown() is no longer the first move.
            api.drain.start()
            await api.drain.wait()
            engine.shutdown()

        app.on_shutdown.append(on_shutdown)
    return app


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description="llmlb_tpu inference engine")
    parser.add_argument("--preset", default="debug-tiny")
    parser.add_argument("--checkpoint", default=None)
    parser.add_argument("--model-id", default=None)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8100)
    parser.add_argument("--num-slots", type=int, default=8)
    # Default sized so a 4k-token prompt serves out of the box via chunked
    # prefill (VERDICT r2 item 5). Memory math: scheduler.kv_cache_bytes —
    # 8 slots x 4096 is 4.3 GiB for llama-3-8b, 1.5 GiB for tinyllama-1.1b.
    # EngineCore clamps to the model's max_position_embeddings.
    parser.add_argument("--slot-capacity", type=int, default=4096)
    parser.add_argument(
        "--prefill-buckets", default=None,
        help="comma-separated one-shot prefill lengths (default 32..512); "
             "prompts beyond the largest run through chunked prefill",
    )
    parser.add_argument(
        "--prefill-chunk-budget", type=int, default=None,
        help="max prompt tokens prefilled per step-loop iteration while "
             "other slots are decoding (default 0 = uncapped; also via "
             "LLMLB_PREFILL_CHUNK_BUDGET) — bounds decoder inter-token "
             "latency regardless of arriving prompt sizes "
             "(docs/scheduling.md)",
    )
    parser.add_argument(
        "--decode-burst", type=int, default=None,
        help="decode+sample steps fused per device dispatch (default: "
             "8 on TPU, 1 elsewhere; also via LLMLB_DECODE_BURST)",
    )
    parser.add_argument(
        "--init-timeout", type=float, default=None,
        help="TPU backend-init guard: prove jax.devices() completes within "
             "this many seconds in a probe child before serving; a hang "
             "dumps the captured libtpu/PJRT log tail + faulthandler stacks "
             "to stderr and exits instead of wedging silently (default 600; "
             "0 disables; also via LLMLB_INIT_TIMEOUT)",
    )
    parser.add_argument(
        "--kv-layout", choices=("paged", "dense"), default=None,
        help="KV cache layout (default paged; also via LLMLB_KV_LAYOUT): "
             "'paged' backs all slots with one shared page pool + block "
             "tables so HBM is held per token cached; 'dense' reserves "
             "slot-capacity rows per slot (the pre-paging layout, bit for "
             "bit)",
    )
    parser.add_argument(
        "--kv-page-size", type=int, default=None,
        help="tokens per KV page in paged mode (default 128; see "
             "docs/kv-cache.md for the waste-vs-overhead tradeoff)",
    )
    parser.add_argument(
        "--kv-pages", type=int, default=None,
        help="total pages in the paged pool (default: num_slots x "
             "slot_capacity worth — the dense HBM budget; raise num_slots "
             "against the same pool to serve more concurrent short "
             "requests)",
    )
    parser.add_argument(
        "--quantize", choices=("off", "weights", "kv", "all"), default=None,
        help="int8 quantization (default off; also via LLMLB_QUANTIZE): "
             "'weights' = per-output-channel int8 projection matrices, "
             "'kv' = int8 KV pages + per-vector scales (paged layout only), "
             "'all' = both — halves the HBM bytes each covers "
             "(docs/quantization.md); bf16 output is bit-identical when off",
    )
    parser.add_argument(
        "--spec-decode", choices=("on", "off"), default=None,
        help="speculative decoding default for requests without their own "
             "'speculative' knob (default off; also via LLMLB_SPEC_DECODE): "
             "prompt-lookup drafting + batched K+1-token verification "
             "(docs/speculative.md)",
    )
    parser.add_argument(
        "--spec-max-draft", type=int, default=None,
        help="max draft tokens per verify step (default 4, cap 16; also via "
             "LLMLB_SPEC_MAX_DRAFT) — the verify chunk width, one compile "
             "per context-window bucket",
    )
    parser.add_argument(
        "--spec-ngram", type=int, default=None,
        help="longest n-gram the prompt-lookup drafter matches on (default "
             "3; also via LLMLB_SPEC_NGRAM)",
    )
    parser.add_argument(
        "--role", choices=("both", "split", "prefill", "decode"),
        default=None,
        help="serving role (default both; also via LLMLB_ROLE): 'split' "
             "runs an in-process prefill pool + decode pool over one paged "
             "KV pool with page-id handoff; 'prefill'/'decode' advertise a "
             "cross-process role to the gateway, which steers prefill-heavy "
             "requests to prefill engines and hands the stream to a decode "
             "engine over the /v1/handoff wire (docs/disaggregation.md)",
    )
    parser.add_argument(
        "--disagg-prefill-slots", type=int, default=None,
        help="slots in the prefill pool under --role split (default "
             "num_slots // 4, min 1; also via LLMLB_DISAGG_PREFILL_SLOTS); "
             "the remaining slots form the decode pool",
    )
    parser.add_argument(
        "--lora-dir", default=None,
        help="directory of LoRA adapters (one PEFT-layout subdirectory per "
             "adapter; also via LLMLB_LORA_DIR). Enables multi-LoRA "
             "serving: per-request adapters via the 'lora' field or a "
             "'model:adapter' name, batched mixed-adapter decode, LRU "
             "hot-load/evict (docs/lora.md). Default off",
    )
    parser.add_argument(
        "--lora-max-adapters", type=int, default=None,
        help="device-resident adapter pool slots (default 8; also via "
             "LLMLB_LORA_MAX_ADAPTERS) — adapters beyond this LRU-evict "
             "when idle; HBM cost scales linearly (docs/lora.md)",
    )
    parser.add_argument(
        "--lora-rank-cap", type=int, default=None,
        help="max adapter rank the pool holds (default 16; also via "
             "LLMLB_LORA_RANK_CAP) — higher-rank adapters are refused "
             "with a 400; lower ranks zero-pad exactly",
    )
    parser.add_argument(
        "--prefix-cache", choices=("on", "off"), default=None,
        help="radix-tree prefix KV reuse across requests (default on; "
             "also via LLMLB_PREFIX_CACHE=0)",
    )
    parser.add_argument(
        "--prefix-cache-slots", type=int, default=None,
        help="max decode slots pinned as prefix donors "
             "(default num_slots // 2, always leaving one serving slot)",
    )
    parser.add_argument(
        "--min-prefix-len", type=int, default=None,
        help="shortest prompt prefix worth caching, in tokens "
             "(default: the smallest prefill bucket)",
    )
    # modality services (checkpoint dir, or "random" for test weights)
    parser.add_argument("--asr", default=None,
                        help="whisper checkpoint dir or 'random'")
    parser.add_argument("--tts", default=None,
                        help="TTS checkpoint dir or 'random'")
    parser.add_argument("--image", default=None,
                        help="diffusion checkpoint dir or 'random'")
    args = parser.parse_args(argv)
    extra = {}
    if args.prefill_buckets:
        try:
            buckets = tuple(
                int(b) for b in args.prefill_buckets.split(",") if b.strip()
            )
        except ValueError:
            parser.error(
                f"--prefill-buckets must be comma-separated integers, "
                f"got {args.prefill_buckets!r}"
            )
        if not buckets:
            parser.error("--prefill-buckets must name at least one length")
        extra["prefill_buckets"] = buckets
    if args.decode_burst is not None:
        extra["decode_burst"] = max(1, args.decode_burst)
    if args.prefill_chunk_budget is not None:
        extra["prefill_chunk_budget"] = max(0, args.prefill_chunk_budget)
    if args.kv_layout is not None:
        extra["kv_layout"] = args.kv_layout
    if args.kv_page_size is not None:
        extra["kv_page_size"] = max(1, args.kv_page_size)
    if args.kv_pages is not None:
        extra["kv_pages"] = max(2, args.kv_pages)
    if args.quantize is not None:
        extra["quantize"] = args.quantize
    if args.spec_decode is not None:
        extra["spec_decode"] = args.spec_decode == "on"
    if args.spec_max_draft is not None:
        extra["spec_max_draft"] = max(1, args.spec_max_draft)
    if args.spec_ngram is not None:
        extra["spec_ngram"] = max(1, args.spec_ngram)
    if args.role is not None:
        extra["role"] = args.role
    if args.disagg_prefill_slots is not None:
        extra["disagg_prefill_slots"] = max(1, args.disagg_prefill_slots)
    if args.lora_dir is not None:
        extra["lora_dir"] = args.lora_dir
    if args.lora_max_adapters is not None:
        extra["lora_max_adapters"] = max(1, args.lora_max_adapters)
    if args.lora_rank_cap is not None:
        extra["lora_rank_cap"] = max(1, args.lora_rank_cap)
    if args.prefix_cache is not None:
        extra["prefix_cache"] = args.prefix_cache == "on"
    if args.prefix_cache_slots is not None:
        extra["prefix_cache_slots"] = max(0, args.prefix_cache_slots)
    if args.min_prefix_len is not None:
        extra["min_prefix_len"] = max(1, args.min_prefix_len)

    # Shared logging subsystem (VERDICT L1 gap closed gateway-side in
    # logging_setup.py): level/format knobs + the worker-id field apply to
    # engine processes too. No file sink here — engines run under their own
    # supervisors that capture stderr.
    from llmlb_tpu.gateway.logging_setup import init_logging

    init_logging(file_sink=False)
    # TPU backend-init hang guard: BEFORE the first in-process jax backend
    # touch (which construction below triggers), prove the backend comes up
    # in a probe child or fail fast with the captured init-log evidence.
    from llmlb_tpu.engine.tpu_probe import guard_backend_init

    guard_backend_init(args.init_timeout)
    # Multi-host bring-up must precede the first jax backend use (engine
    # construction enumerates devices). No-op unless LLMLB_COORDINATOR/
    # LLMLB_NUM_HOSTS or LLMLB_DISTRIBUTED are set.
    from llmlb_tpu.parallel.distributed import init_from_env

    init_from_env()
    from llmlb_tpu.native import ensure_native_built

    ensure_native_built()  # build before serving; loader itself never builds
    if args.checkpoint:
        engine = Engine.from_checkpoint(
            args.checkpoint, model_id=args.model_id,
            num_slots=args.num_slots, slot_capacity=args.slot_capacity,
            **extra,
        )
    else:
        engine = Engine.from_preset(
            args.preset, model_id=args.model_id,
            num_slots=args.num_slots, slot_capacity=args.slot_capacity,
            **extra,
        )

    import jax

    if jax.process_count() > 1 and jax.process_index() != 0:
        # Follower host of a multi-host engine: the step thread runs the
        # lockstep loop (engine/multihost.py) dispatching the same collective
        # programs the leader plans; HTTP (and the modality engines, which
        # only HTTP reaches) belong to the leader.
        log.info("multihost follower: serving loop only (leader owns HTTP)")
        engine.core._thread.join()
        return

    asr = tts = image = None
    if args.asr:
        from llmlb_tpu.engine.asr import AsrEngine

        asr = (AsrEngine.from_random() if args.asr == "random"
               else AsrEngine.from_checkpoint(args.asr))
    if args.tts:
        from llmlb_tpu.engine.tts import TtsEngine

        tts = (TtsEngine.from_random() if args.tts == "random"
               else TtsEngine.from_checkpoint(args.tts))
    if args.image:
        from llmlb_tpu.engine.image import ImageEngine

        image = (ImageEngine.from_random() if args.image == "random"
                 else ImageEngine.from_checkpoint(args.image))

    web.run_app(
        create_engine_app(engine, asr=asr, tts=tts, image=image),
        host=args.host, port=args.port,
    )


if __name__ == "__main__":
    main()
