"""High-level engine service: text in, streamed text out.

Bridges the HTTP layer to the EngineCore step loop: chat templating, token
encode/decode, stop-sequence handling, usage accounting, and async iteration
over the core's thread-side event queues.
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import AsyncIterator


from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams
from llmlb_tpu.engine.tokenizer import (
    ByteTokenizer,
    HFTokenizer,
    IncrementalDetokenizer,
    Tokenizer,
)


def _quantize_weights(core_kwargs: dict) -> bool:
    """Resolve whether the construction path should int8-quantize weights
    while streaming the checkpoint (same knob the core itself parses)."""
    from llmlb_tpu.quant import parse_quant_mode

    return parse_quant_mode(core_kwargs.get("quantize")).weights


@dataclasses.dataclass
class StreamDelta:
    text: str = ""
    finish_reason: str | None = None
    prompt_tokens: int = 0
    completion_tokens: int = 0
    ttft_s: float | None = None
    # Token ids newly committed since the previous delta (durable streams,
    # docs/resilience.md): the HTTP layer ships these as gateway-internal
    # `llmlb.replay` SSE frames when the request was armed with
    # `llmlb_replay`, BEFORE the text they produced — so the gateway's
    # replay ledger always covers every character the client has seen.
    token_ids: list[int] = dataclasses.field(default_factory=list)


class Engine:
    """One served model: config + weights + tokenizer + scheduler core."""

    def __init__(
        self,
        model_id: str,
        core: EngineCore,
        tokenizer: Tokenizer,
    ):
        self.model_id = model_id
        self.core = core
        self.tokenizer = tokenizer
        # Event bridging blocks a thread per in-flight stream; size accordingly.
        self._executor = ThreadPoolExecutor(
            max_workers=max(32, core.num_slots * 4),
            thread_name_prefix="engine-events",
        )
        # Grammar-constraint compiler (llmlb_tpu/structured): owned here
        # because it needs the tokenizer, installed on the core so multihost
        # followers (which receive only the JSON spec over the plan wire)
        # can rebuild the token-DFA themselves.
        from llmlb_tpu.structured import ConstraintCompiler

        self.constraint_compiler = ConstraintCompiler(
            tokenizer, core.cfg.vocab_size, metrics=core.metrics
        )
        core.constraint_compiler = self.constraint_compiler

    # ------------------------------------------------------------ construction

    @classmethod
    def from_preset(
        cls,
        preset: str,
        *,
        model_id: str | None = None,
        checkpoint_dir: str | None = None,
        **core_kwargs,
    ) -> "Engine":
        """Build from a named preset; random weights unless checkpoint_dir."""
        cfg = get_preset(preset)
        params = None
        tokenizer: Tokenizer
        if checkpoint_dir:
            from llmlb_tpu.engine.weights import load_checkpoint, load_config

            cfg = load_config(checkpoint_dir, dtype=cfg.dtype)
            tokenizer = HFTokenizer(checkpoint_dir)
            params = load_checkpoint(
                checkpoint_dir, cfg,
                quantize_weights=_quantize_weights(core_kwargs),
            )
        else:
            tokenizer = ByteTokenizer(cfg.vocab_size)
        core = EngineCore(
            cfg, params, eos_id=tokenizer.eos_id, **core_kwargs
        )
        core.start()
        return cls(model_id or preset, core, tokenizer)

    @classmethod
    def from_checkpoint(cls, checkpoint_dir: str, *, model_id: str | None = None,
                        **core_kwargs) -> "Engine":
        from llmlb_tpu.engine.weights import load_checkpoint, load_config

        cfg = load_config(checkpoint_dir)
        tokenizer = HFTokenizer(checkpoint_dir)
        # int8 weight quantization happens per tensor WHILE streaming the
        # shards (host RAM and H2D both move the int8 bytes); the core's
        # own quantize pass is idempotent over the result
        params = load_checkpoint(
            checkpoint_dir, cfg,
            quantize_weights=_quantize_weights(core_kwargs),
        )
        core = EngineCore(cfg, params, eos_id=tokenizer.eos_id, **core_kwargs)
        core.start()
        return cls(
            model_id or os.path.basename(checkpoint_dir.rstrip("/")),
            core,
            tokenizer,
        )

    def shutdown(self) -> None:
        self.core.stop()
        self._executor.shutdown(wait=False, cancel_futures=True)

    # --------------------------------------------------------------- serving

    def encode_chat(self, messages: list[dict]) -> list[int]:
        return self.tokenizer.encode(self.tokenizer.apply_chat_template(messages))

    async def stream(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams,
        stop: list[str] | None = None,
        request_id: str | None = None,
    ) -> AsyncIterator[StreamDelta]:
        """Submit and stream deltas. Final delta carries finish_reason + usage.

        `request_id` (the gateway's X-Request-Id) prefixes the scheduler
        request id, so engine-side logs/events join the gateway trace. A
        unique suffix is always appended: the raw header is client-controlled
        and the scheduler's cancellation bookkeeping is keyed by request_id,
        so two in-flight requests must never share one.

        Stop sequences may straddle token/delta boundaries, so the last
        `max(len(stop)) - 1` characters are held back until the stream resolves;
        a stop hit truncates before anything past it is emitted. Early exit
        (stop hit, client gone) cancels the request so its slot frees promptly.
        """
        if request_id:
            request = Request(
                prompt_ids=prompt_ids, sampling=sampling,
                request_id=f"{request_id}.{uuid.uuid4().hex[:8]}",
            )
        else:
            request = Request(prompt_ids=prompt_ids, sampling=sampling)
        loop = asyncio.get_running_loop()
        if sampling.constraint is not None:
            # Compile (or LRU-fetch) the token-DFA BEFORE submit, off the
            # event loop AND off the engine step loop: a cold 128k-vocab
            # compile must stall neither other HTTP requests nor in-flight
            # decode. Invalid specs raise here (ValueError →
            # UnsupportedSchemaError included) and never reach a slot.
            request.compiled_constraint = await loop.run_in_executor(
                self._executor,
                self.constraint_compiler.compile_spec,
                sampling.constraint,
            )
        if sampling.lora:
            # Pin + hot-load the adapter off the event loop (first use reads
            # safetensors from disk) AND off the step loop; submit's own
            # prepare_lora call is then an idempotent lookup.
            await loop.run_in_executor(
                self._executor, self.core.prepare_lora, request
            )
        try:
            self.core.submit(request)
        except BaseException:
            # a pre-pinned adapter must not leak when the submit never
            # reaches a queue (validation refusal, or cancellation landing
            # between the prepare above and here); idempotent no-op when
            # nothing was acquired
            self.core._release_lora(request)
            raise

        detok = IncrementalDetokenizer(self.tokenizer)
        stop = [s for s in (stop or []) if s]
        holdback = max((len(s) for s in stop), default=1) - 1
        acc = ""  # decoded text; [:emitted] has been yielded
        emitted = 0
        completion_tokens = 0
        # ids committed since the last yielded delta: they ride the NEXT
        # delta (durable streams — the gateway's replay ledger)
        pending_ids: list[int] = []
        ttft: float | None = None  # attached to the first yielded delta

        finished = False

        def final(text: str, reason: str) -> StreamDelta:
            return StreamDelta(
                text=text,
                finish_reason=reason,
                prompt_tokens=len(prompt_ids),
                completion_tokens=completion_tokens,
                ttft_s=ttft,
                token_ids=pending_ids,
            )

        try:
            while True:
                kind, value = await loop.run_in_executor(
                    self._executor, request.events.get
                )
                if kind == "error":
                    raise EngineError(str(value))
                if kind == "token":
                    completion_tokens += 1
                    if completion_tokens == 1 and request.first_token_at:
                        ttft = request.first_token_at - request.submitted_at
                    pending_ids.append(int(value))
                    acc += detok.push(int(value))
                else:  # done
                    acc += detok.flush()

                hit = _find_stop(acc, stop)
                if hit is not None:
                    finished = True
                    request.cancel()
                    yield final(acc[emitted:hit], "stop")
                    return
                if kind == "done":
                    finished = True
                    yield final(acc[emitted:], str(value))
                    return
                boundary = max(emitted, len(acc) - holdback)
                if boundary > emitted:
                    delta = StreamDelta(text=acc[emitted:boundary],
                                        ttft_s=ttft, token_ids=pending_ids)
                    pending_ids = []
                    ttft = None  # report once
                    emitted = boundary
                    yield delta
        finally:
            if not finished:
                request.cancel()

    # -------------------------------------------------- cross-process handoff

    async def prefill_handoff(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams,
        emit_tokens: int = 1,
        request_id: str | None = None,
    ) -> tuple[list[int], str | None, dict | None]:
        """Prefill-role side of the cross-process handoff
        (docs/disaggregation.md): run admission + prefill and commit the
        first `emit_tokens` tokens, then stop. Returns ``(committed_ids,
        finish_reason, kv_pages)`` — finish_reason is None when the request
        has more to generate (the handoff case: the caller wraps the
        committed ids in a wire payload for a decode engine to adopt), or
        the natural finish ("stop"/"length") when the request completed
        inside the committed window and no handoff is needed. ``kv_pages``
        is the serialized KV page payload (engine/kv_transfer.py) in the
        handoff case when shipping is enabled, else None — the adopter
        lands it instead of re-prefilling.

        Token-level on purpose: the committed ids ride the wire verbatim and
        the ADOPTING engine owns detokenization and stop sequences, so its
        incremental detokenizer sees the exact same token sequence an
        uninterrupted run would have."""
        k = max(1, int(emit_tokens))
        bounded = dataclasses.replace(
            sampling, max_tokens=min(sampling.max_tokens, k)
        )
        request = Request(
            prompt_ids=prompt_ids, sampling=bounded,
            request_id=(f"{request_id}.{uuid.uuid4().hex[:8]}"
                        if request_id else uuid.uuid4().hex),
        )
        # ask the scheduler to serialize this stream's KV pages at the
        # emit-budget finish, before the pool reclaims them — the payload
        # rides the handoff envelope so the adopter can skip its replay
        # prefill entirely (docs/kv-cache.md)
        request.export_kv = self.core.kv_ship
        loop = asyncio.get_running_loop()
        if sampling.constraint is not None:
            request.compiled_constraint = await loop.run_in_executor(
                self._executor,
                self.constraint_compiler.compile_spec,
                sampling.constraint,
            )
        if sampling.lora:
            await loop.run_in_executor(
                self._executor, self.core.prepare_lora, request
            )
        try:
            self.core.submit(request)
        except BaseException:
            self.core._release_lora(request)  # see stream(): no pin leaks
            raise
        committed: list[int] = []
        finish: str | None = None
        try:
            while True:
                kind, value = await loop.run_in_executor(
                    self._executor, request.events.get
                )
                if kind == "error":
                    raise EngineError(str(value))
                if kind == "token":
                    committed.append(int(value))
                else:  # done
                    finish = str(value)
                    break
        finally:
            if finish is None:
                request.cancel()
        if (finish == "length" and len(committed) >= k
                and sampling.max_tokens > k):
            # the bounded run was cut at the emit budget, not a real finish:
            # this stream continues on whichever engine adopts it
            self.core.metrics.record_handoff("emitted")
            if self.core.flightrec.enabled:
                self.core.flightrec.emit(request.request_id, "handoff_emitted",
                                         tokens=len(committed))
            return committed, None, request.kv_export
        return committed, finish, None

    async def adopt_stream(
        self,
        prompt_ids: list[int],
        committed_ids: list[int],
        sampling: SamplingParams,
        stop: list[str] | None = None,
        request_id: str | None = None,
        emitted_at: float = 0.0,
        kv_pages: dict | None = None,
    ) -> AsyncIterator[StreamDelta]:
        """Decode-pool side of the cross-process handoff: adopt a stream a
        prefill engine started, by replaying prompt + committed tokens as a
        chunk-prefill (the PR 10 park/resume path — KV lands at identical
        absolute positions, so greedy and seeded-stochastic continuations
        are token-identical to an uninterrupted run) and then decoding the
        remainder here.

        When ``kv_pages`` carries a serialized page payload from the origin
        (engine/kv_transfer.py) and it is compatible with THIS pool, the
        replay prefill is skipped entirely: the pages land H2D and the
        stream re-enters decode directly. Any mismatch — version skew,
        dtype, page geometry, shipping disabled here — falls back to the
        replay path with a reason-labeled counter; a bad payload is never a
        client-visible error.

        The full text (committed + continuation) is emitted: the prefill
        side never detokenized, so this engine's incremental detokenizer
        and stop-sequence scan see the stream exactly as `--role both`
        would have."""
        from llmlb_tpu.engine.scheduler import ParkedState
        from llmlb_tpu.structured import ConstraintState

        loop = asyncio.get_running_loop()
        compiled = None
        cursor = None
        if sampling.constraint is not None:
            compiled = await loop.run_in_executor(
                self._executor,
                self.constraint_compiler.compile_spec,
                sampling.constraint,
            )
            # Rebuild the grammar cursor at its handoff position: the FSM
            # re-walks the committed tokens (a fresh start-state cursor
            # would mask the continuation as if at the string beginning —
            # the PR 10 park bug, cross-process edition).
            cursor = ConstraintState(compiled)
            for t in committed_ids:
                cursor.advance(int(t))
        drafter = None
        spec_k = 0
        core = self.core
        if core._spec_available:
            knobs = sampling.speculative
            knobs = knobs if isinstance(knobs, dict) else {}
            if bool(knobs.get("enabled", core.spec.enabled)):
                from llmlb_tpu.spec import PromptLookupDrafter

                try:
                    want = int(knobs.get("max_draft_tokens")
                               or core.spec.max_draft_tokens)
                except (TypeError, ValueError):
                    want = core.spec.max_draft_tokens
                spec_k = max(1, min(want, core.spec.max_draft_tokens))
                # index prompt + committed: exactly the state the prefill
                # engine's drafter held at the handoff point
                drafter = PromptLookupDrafter(
                    prompt_ids, max_ngram=core.spec.max_ngram,
                    min_ngram=core.spec.min_ngram,
                )
                for t in committed_ids:
                    drafter.append(int(t))

        detok = IncrementalDetokenizer(self.tokenizer)
        stop = [s for s in (stop or []) if s]
        holdback = max((len(s) for s in stop), default=1) - 1
        acc = "".join(detok.push(int(t)) for t in committed_ids)
        emitted = 0
        completion_tokens = len(committed_ids)
        # replayed ids count as committed here too: a SECOND failover from
        # this engine must replay the full sequence (durable streams)
        pending_ids: list[int] = [int(t) for t in committed_ids]
        ttft: float | None = None
        finished = False

        def final(text: str, reason: str) -> StreamDelta:
            return StreamDelta(
                text=text, finish_reason=reason,
                prompt_tokens=len(prompt_ids),
                completion_tokens=completion_tokens,
                ttft_s=ttft,
                token_ids=pending_ids,
            )

        # the wire stamp is time.time() (wall clock — the only clock two
        # processes share; same-host skew caveat in docs/disaggregation.md),
        # so the latency diff must stay in the same clock domain
        latency = max(0.0, time.time() - emitted_at) if emitted_at else None
        core.metrics.record_handoff("adopted", latency)
        if request_id and core.flightrec.enabled:
            attrs = {"committed": len(committed_ids)}
            if latency is not None:
                attrs["wire_latency_s"] = round(latency, 6)
            core.flightrec.emit(request_id, "adopted", **attrs)

        # A handoff that is already terminal (stop string inside the
        # committed text, or a payload whose committed run used up the
        # whole budget) finishes here without touching the step loop.
        hit = _find_stop(acc, stop)
        if hit is not None:
            # truncation lands at `hit`, before anything flush could append
            yield final(acc[:hit], "stop")
            return
        if completion_tokens >= sampling.max_tokens:
            # terminal without further pushes: drain the detokenizer's
            # held-back bytes exactly like the stream path does on "done"
            acc += detok.flush()
            yield final(acc, "length")
            return

        kv_restore = None
        if kv_pages is not None:
            if not core.kv_ship:
                # this engine cannot land pages (knob off, dense layout,
                # multihost, split prefill role): replay, with the reason
                core.metrics.record_kv_ship_fallback("disabled")
            elif not committed_ids:
                # zero committed tokens: the faithful continuation is the
                # activation-sample path — replay is already exact there
                core.metrics.record_kv_ship_fallback("capacity")
            else:
                from llmlb_tpu.engine.kv_transfer import (
                    KVTransferError, parse_kv_payload,
                )

                try:
                    parsed = await loop.run_in_executor(
                        self._executor, parse_kv_payload, kv_pages
                    )
                except KVTransferError as e:
                    core.metrics.record_kv_ship_fallback(e.reason)
                except Exception:
                    core.metrics.record_kv_ship_fallback("error")
                else:
                    reason = core.kv_restore_reason(parsed.header)
                    if reason is not None:
                        core.metrics.record_kv_ship_fallback(reason)
                    else:
                        kv_restore = parsed
        elif core.kv_ship:
            # shipping is on but the origin sent nothing (old peer, or a
            # killed engine whose export vanished with it): count it so an
            # operator can see replays that SHOULD have been page moves
            core.metrics.record_kv_ship_fallback("absent")

        request = Request(
            prompt_ids=list(prompt_ids), sampling=sampling,
            request_id=(f"{request_id}.{uuid.uuid4().hex[:8]}"
                        if request_id else uuid.uuid4().hex),
            compiled_constraint=compiled,
            parked=ParkedState(
                generated=len(committed_ids), tokens=list(committed_ids),
                constraint=cursor, drafter=drafter, spec_k=spec_k,
            ),
            kv_restore=kv_restore,
        )
        if sampling.lora:
            # adoption replays prompt+committed WITH the adapter — the
            # resumed continuation must read the same wq/wk/wv deltas
            await loop.run_in_executor(
                self._executor, core.prepare_lora, request
            )
        try:
            core.submit(request)
        except BaseException:
            core._release_lora(request)  # see stream(): no pin leaks
            raise
        try:
            while True:
                kind, value = await loop.run_in_executor(
                    self._executor, request.events.get
                )
                if kind == "error":
                    raise EngineError(str(value))
                if kind == "token":
                    completion_tokens += 1
                    if ttft is None and request.first_token_at:
                        ttft = (request.first_token_at
                                - request.submitted_at)
                    pending_ids.append(int(value))
                    acc += detok.push(int(value))
                else:  # done
                    acc += detok.flush()

                hit = _find_stop(acc, stop)
                if hit is not None:
                    finished = True
                    request.cancel()
                    yield final(acc[emitted:hit], "stop")
                    return
                if kind == "done":
                    finished = True
                    yield final(acc[emitted:], str(value))
                    return
                boundary = max(emitted, len(acc) - holdback)
                if boundary > emitted:
                    delta = StreamDelta(text=acc[emitted:boundary],
                                        ttft_s=ttft, token_ids=pending_ids)
                    pending_ids = []
                    emitted = boundary
                    yield delta
        finally:
            if not finished:
                request.cancel()

    async def complete(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams,
        stop: list[str] | None = None,
        request_id: str | None = None,
    ) -> StreamDelta:
        """Non-streaming: collect the full completion."""
        text = []
        final: StreamDelta | None = None
        async for delta in self.stream(prompt_ids, sampling, stop,
                                       request_id=request_id):
            text.append(delta.text)
            if delta.finish_reason is not None:
                final = delta
        assert final is not None
        return dataclasses.replace(final, text="".join(text))

    # One embed forward never exceeds this many rows: keeps a single request
    # from monopolizing HBM/compile time (generation is bounded by num_slots;
    # this is the embedding-path equivalent).
    _EMBED_CHUNK = 64
    MAX_EMBED_INPUTS = 2048  # request-level cap, matches OpenAI's limit

    def _embed_sync(self, batch_ids: list[list[int]]) -> "list[list[float]]":
        import numpy as np

        results: list[list[float]] = []
        for start in range(0, len(batch_ids), self._EMBED_CHUNK):
            chunk = batch_ids[start : start + self._EMBED_CHUNK]
            n = len(chunk)
            longest = max(len(x) for x in chunk)
            # pow2 buckets on BOTH dims keep the compile count logarithmic;
            # padding rows (lens=1 over zero ids) are sliced off below.
            bucket = 16
            while bucket < longest:
                bucket *= 2
            n_bucket = 1
            while n_bucket < n:
                n_bucket *= 2
            ids = np.zeros((n_bucket, bucket), np.int32)
            lens = np.ones((n_bucket,), np.int32)
            for i, toks in enumerate(chunk):
                ids[i, : len(toks)] = toks
                lens[i] = len(toks)
            out = self.core.family.encode(
                self.core.params, self.core.cfg, ids, lens
            )
            results.extend(np.asarray(out)[:n].tolist())
        return results

    def supports_embeddings(self) -> bool:
        """Capability by family contract: a family supports /v1/embeddings iff
        it exports an `encode` forward (the registry is the extension point)."""
        return hasattr(self.core.family, "encode")

    async def embed(self, batch_ids: list[list[int]]) -> "list[list[float]]":
        """Batch of token id lists -> L2-normalized embedding vectors.

        Raises ValueError (a client error) for empty/oversized/out-of-vocab
        inputs and for model families without an embedding forward.
        """
        if not self.supports_embeddings():
            raise ValueError(
                "embeddings are not supported for the "
                f"{self.core.family.__name__.rsplit('.', 1)[-1]} model family"
            )
        if not batch_ids or any(len(x) == 0 for x in batch_ids):
            raise ValueError("each input must contain at least one token")
        if len(batch_ids) > self.MAX_EMBED_INPUTS:
            raise ValueError(
                f"at most {self.MAX_EMBED_INPUTS} inputs per request "
                f"(got {len(batch_ids)})"
            )
        longest = max(len(x) for x in batch_ids)
        if longest > self.core.cfg.max_position_embeddings:
            raise ValueError(
                f"input of {longest} tokens exceeds the model context "
                f"({self.core.cfg.max_position_embeddings})"
            )
        import numpy as np

        vocab = self.core.cfg.vocab_size
        # vectorized range check — this runs on the event loop, so it must
        # stay O(total tokens) in numpy, not a Python per-token loop
        flat = np.fromiter(
            (t for toks in batch_ids for t in toks), np.int64
        )
        if flat.size and (flat.min() < 0 or flat.max() >= vocab):
            bad = int(flat[(flat < 0) | (flat >= vocab)][0])
            raise ValueError(
                f"token id {bad} out of range for vocab size {vocab}"
            )
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor, self._embed_sync, batch_ids
        )

    def health(self) -> dict:
        from llmlb_tpu.engine.telemetry import device_telemetry

        stats = self.core.stats()
        return {
            "status": "ok",
            "model": self.model_id,
            "engine": {
                "num_slots": stats.num_slots,
                "active_slots": stats.active_slots,
                "queued": stats.queued,
                "total_requests": stats.total_requests,
                "total_tokens": stats.total_tokens,
                "uptime_s": round(stats.uptime_s, 3),
                "mesh": dict(self.core.mesh.shape),
            },
            "tpu": device_telemetry(),
            "prefix_cache": self.core.prefix_cache_info(),
            "kv_cache": self.core.kv_cache_info(),
            # int8 quantization knobs + honest byte footprints
            "quant": self.core.quant_info(),
            "structured": self.core.structured_info(),
            # speculative decoding config + live acceptance figures
            # (llmlb_tpu/spec, docs/speculative.md)
            "spec": self.core.spec_info(),
            # overload protection: priority-queue depths, preemption and
            # deadline-shed counters (docs/scheduling.md)
            "sched": self.core.sched_info(),
            # disaggregated prefill/decode: served role, split-pool sizes,
            # handoff counters (docs/disaggregation.md) — the gateway's
            # health probe re-reads `role` from here every interval, so a
            # restarted engine that changed role re-routes within one probe
            "disagg": self.core.disagg_info(),
            # multi-LoRA adapter pool: resident/available adapters,
            # load/evict counters (docs/lora.md)
            "lora": self.core.lora_info(),
            # live roofline (MFU / HBM-BW vs chip peaks, docs/profiling.md);
            # the gateway's telemetry-aware placement can read how close to
            # the hardware each engine is running
            "perf": self.core.perf_info(),
            "metrics": self.core.metrics.summary(),
        }


class EngineError(RuntimeError):
    pass


def _find_stop(text: str, stops: list[str]) -> int | None:
    best: int | None = None
    for s in stops:
        if not s:
            continue
        idx = text.find(s)
        if idx != -1 and (best is None or idx < best):
            best = idx
    return best
