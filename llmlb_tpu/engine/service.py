"""High-level engine service: text in, streamed text out.

Bridges the HTTP layer to the EngineCore step loop: chat templating, token
encode/decode, stop-sequence handling, usage accounting, and async iteration
over the core's thread-side event queues.
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import AsyncIterator


from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams
from llmlb_tpu.engine.tokenizer import (
    ByteTokenizer,
    HFTokenizer,
    IncrementalDetokenizer,
    Tokenizer,
)


def _quantize_weights(core_kwargs: dict) -> bool:
    """Resolve whether the construction path should int8-quantize weights
    while streaming the checkpoint (same knob the core itself parses)."""
    from llmlb_tpu.quant import parse_quant_mode

    return parse_quant_mode(core_kwargs.get("quantize")).weights


@dataclasses.dataclass
class StreamDelta:
    text: str = ""
    finish_reason: str | None = None
    prompt_tokens: int = 0
    completion_tokens: int = 0
    ttft_s: float | None = None


class Engine:
    """One served model: config + weights + tokenizer + scheduler core."""

    def __init__(
        self,
        model_id: str,
        core: EngineCore,
        tokenizer: Tokenizer,
    ):
        self.model_id = model_id
        self.core = core
        self.tokenizer = tokenizer
        # Event bridging blocks a thread per in-flight stream; size accordingly.
        self._executor = ThreadPoolExecutor(
            max_workers=max(32, core.num_slots * 4),
            thread_name_prefix="engine-events",
        )
        # Grammar-constraint compiler (llmlb_tpu/structured): owned here
        # because it needs the tokenizer, installed on the core so multihost
        # followers (which receive only the JSON spec over the plan wire)
        # can rebuild the token-DFA themselves.
        from llmlb_tpu.structured import ConstraintCompiler

        self.constraint_compiler = ConstraintCompiler(
            tokenizer, core.cfg.vocab_size, metrics=core.metrics
        )
        core.constraint_compiler = self.constraint_compiler

    # ------------------------------------------------------------ construction

    @classmethod
    def from_preset(
        cls,
        preset: str,
        *,
        model_id: str | None = None,
        checkpoint_dir: str | None = None,
        **core_kwargs,
    ) -> "Engine":
        """Build from a named preset; random weights unless checkpoint_dir."""
        cfg = get_preset(preset)
        params = None
        tokenizer: Tokenizer
        if checkpoint_dir:
            from llmlb_tpu.engine.weights import load_checkpoint, load_config

            cfg = load_config(checkpoint_dir, dtype=cfg.dtype)
            tokenizer = HFTokenizer(checkpoint_dir)
            params = load_checkpoint(
                checkpoint_dir, cfg,
                quantize_weights=_quantize_weights(core_kwargs),
            )
        else:
            tokenizer = ByteTokenizer(cfg.vocab_size)
        core = EngineCore(
            cfg, params, eos_id=tokenizer.eos_id, **core_kwargs
        )
        core.start()
        return cls(model_id or preset, core, tokenizer)

    @classmethod
    def from_checkpoint(cls, checkpoint_dir: str, *, model_id: str | None = None,
                        **core_kwargs) -> "Engine":
        from llmlb_tpu.engine.weights import load_checkpoint, load_config

        cfg = load_config(checkpoint_dir)
        tokenizer = HFTokenizer(checkpoint_dir)
        # int8 weight quantization happens per tensor WHILE streaming the
        # shards (host RAM and H2D both move the int8 bytes); the core's
        # own quantize pass is idempotent over the result
        params = load_checkpoint(
            checkpoint_dir, cfg,
            quantize_weights=_quantize_weights(core_kwargs),
        )
        core = EngineCore(cfg, params, eos_id=tokenizer.eos_id, **core_kwargs)
        core.start()
        return cls(
            model_id or os.path.basename(checkpoint_dir.rstrip("/")),
            core,
            tokenizer,
        )

    def shutdown(self) -> None:
        self.core.stop()
        self._executor.shutdown(wait=False, cancel_futures=True)

    # --------------------------------------------------------------- serving

    def encode_chat(self, messages: list[dict]) -> list[int]:
        return self.tokenizer.encode(self.tokenizer.apply_chat_template(messages))

    async def stream(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams,
        stop: list[str] | None = None,
        request_id: str | None = None,
    ) -> AsyncIterator[StreamDelta]:
        """Submit and stream deltas. Final delta carries finish_reason + usage.

        `request_id` (the gateway's X-Request-Id) prefixes the scheduler
        request id, so engine-side logs/events join the gateway trace. A
        unique suffix is always appended: the raw header is client-controlled
        and the scheduler's cancellation bookkeeping is keyed by request_id,
        so two in-flight requests must never share one.

        Stop sequences may straddle token/delta boundaries, so the last
        `max(len(stop)) - 1` characters are held back until the stream resolves;
        a stop hit truncates before anything past it is emitted. Early exit
        (stop hit, client gone) cancels the request so its slot frees promptly.
        """
        if request_id:
            request = Request(
                prompt_ids=prompt_ids, sampling=sampling,
                request_id=f"{request_id}.{uuid.uuid4().hex[:8]}",
            )
        else:
            request = Request(prompt_ids=prompt_ids, sampling=sampling)
        loop = asyncio.get_running_loop()
        if sampling.constraint is not None:
            # Compile (or LRU-fetch) the token-DFA BEFORE submit, off the
            # event loop AND off the engine step loop: a cold 128k-vocab
            # compile must stall neither other HTTP requests nor in-flight
            # decode. Invalid specs raise here (ValueError →
            # UnsupportedSchemaError included) and never reach a slot.
            request.compiled_constraint = await loop.run_in_executor(
                self._executor,
                self.constraint_compiler.compile_spec,
                sampling.constraint,
            )
        self.core.submit(request)

        detok = IncrementalDetokenizer(self.tokenizer)
        stop = [s for s in (stop or []) if s]
        holdback = max((len(s) for s in stop), default=1) - 1
        acc = ""  # decoded text; [:emitted] has been yielded
        emitted = 0
        completion_tokens = 0
        ttft: float | None = None  # attached to the first yielded delta
        finished = False

        def final(text: str, reason: str) -> StreamDelta:
            return StreamDelta(
                text=text,
                finish_reason=reason,
                prompt_tokens=len(prompt_ids),
                completion_tokens=completion_tokens,
                ttft_s=ttft,
            )

        try:
            while True:
                kind, value = await loop.run_in_executor(
                    self._executor, request.events.get
                )
                if kind == "error":
                    raise EngineError(str(value))
                if kind == "token":
                    completion_tokens += 1
                    if completion_tokens == 1 and request.first_token_at:
                        ttft = request.first_token_at - request.submitted_at
                    acc += detok.push(int(value))
                else:  # done
                    acc += detok.flush()

                hit = _find_stop(acc, stop)
                if hit is not None:
                    finished = True
                    request.cancel()
                    yield final(acc[emitted:hit], "stop")
                    return
                if kind == "done":
                    finished = True
                    yield final(acc[emitted:], str(value))
                    return
                boundary = max(emitted, len(acc) - holdback)
                if boundary > emitted:
                    delta = StreamDelta(text=acc[emitted:boundary], ttft_s=ttft)
                    ttft = None  # report once
                    emitted = boundary
                    yield delta
        finally:
            if not finished:
                request.cancel()

    async def complete(
        self,
        prompt_ids: list[int],
        sampling: SamplingParams,
        stop: list[str] | None = None,
        request_id: str | None = None,
    ) -> StreamDelta:
        """Non-streaming: collect the full completion."""
        text = []
        final: StreamDelta | None = None
        async for delta in self.stream(prompt_ids, sampling, stop,
                                       request_id=request_id):
            text.append(delta.text)
            if delta.finish_reason is not None:
                final = delta
        assert final is not None
        return dataclasses.replace(final, text="".join(text))

    # One embed forward never exceeds this many rows: keeps a single request
    # from monopolizing HBM/compile time (generation is bounded by num_slots;
    # this is the embedding-path equivalent).
    _EMBED_CHUNK = 64
    MAX_EMBED_INPUTS = 2048  # request-level cap, matches OpenAI's limit

    def _embed_sync(self, batch_ids: list[list[int]]) -> "list[list[float]]":
        import numpy as np

        results: list[list[float]] = []
        for start in range(0, len(batch_ids), self._EMBED_CHUNK):
            chunk = batch_ids[start : start + self._EMBED_CHUNK]
            n = len(chunk)
            longest = max(len(x) for x in chunk)
            # pow2 buckets on BOTH dims keep the compile count logarithmic;
            # padding rows (lens=1 over zero ids) are sliced off below.
            bucket = 16
            while bucket < longest:
                bucket *= 2
            n_bucket = 1
            while n_bucket < n:
                n_bucket *= 2
            ids = np.zeros((n_bucket, bucket), np.int32)
            lens = np.ones((n_bucket,), np.int32)
            for i, toks in enumerate(chunk):
                ids[i, : len(toks)] = toks
                lens[i] = len(toks)
            out = self.core.family.encode(
                self.core.params, self.core.cfg, ids, lens
            )
            results.extend(np.asarray(out)[:n].tolist())
        return results

    def supports_embeddings(self) -> bool:
        """Capability by family contract: a family supports /v1/embeddings iff
        it exports an `encode` forward (the registry is the extension point)."""
        return hasattr(self.core.family, "encode")

    async def embed(self, batch_ids: list[list[int]]) -> "list[list[float]]":
        """Batch of token id lists -> L2-normalized embedding vectors.

        Raises ValueError (a client error) for empty/oversized/out-of-vocab
        inputs and for model families without an embedding forward.
        """
        if not self.supports_embeddings():
            raise ValueError(
                "embeddings are not supported for the "
                f"{self.core.family.__name__.rsplit('.', 1)[-1]} model family"
            )
        if not batch_ids or any(len(x) == 0 for x in batch_ids):
            raise ValueError("each input must contain at least one token")
        if len(batch_ids) > self.MAX_EMBED_INPUTS:
            raise ValueError(
                f"at most {self.MAX_EMBED_INPUTS} inputs per request "
                f"(got {len(batch_ids)})"
            )
        longest = max(len(x) for x in batch_ids)
        if longest > self.core.cfg.max_position_embeddings:
            raise ValueError(
                f"input of {longest} tokens exceeds the model context "
                f"({self.core.cfg.max_position_embeddings})"
            )
        import numpy as np

        vocab = self.core.cfg.vocab_size
        # vectorized range check — this runs on the event loop, so it must
        # stay O(total tokens) in numpy, not a Python per-token loop
        flat = np.fromiter(
            (t for toks in batch_ids for t in toks), np.int64
        )
        if flat.size and (flat.min() < 0 or flat.max() >= vocab):
            bad = int(flat[(flat < 0) | (flat >= vocab)][0])
            raise ValueError(
                f"token id {bad} out of range for vocab size {vocab}"
            )
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor, self._embed_sync, batch_ids
        )

    def health(self) -> dict:
        from llmlb_tpu.engine.telemetry import device_telemetry

        stats = self.core.stats()
        return {
            "status": "ok",
            "model": self.model_id,
            "engine": {
                "num_slots": stats.num_slots,
                "active_slots": stats.active_slots,
                "queued": stats.queued,
                "total_requests": stats.total_requests,
                "total_tokens": stats.total_tokens,
                "uptime_s": round(stats.uptime_s, 3),
                "mesh": dict(self.core.mesh.shape),
            },
            "tpu": device_telemetry(),
            "prefix_cache": self.core.prefix_cache_info(),
            "kv_cache": self.core.kv_cache_info(),
            # int8 quantization knobs + honest byte footprints
            "quant": self.core.quant_info(),
            "structured": self.core.structured_info(),
            # speculative decoding config + live acceptance figures
            # (llmlb_tpu/spec, docs/speculative.md)
            "spec": self.core.spec_info(),
            # overload protection: priority-queue depths, preemption and
            # deadline-shed counters (docs/scheduling.md)
            "sched": self.core.sched_info(),
            # live roofline (MFU / HBM-BW vs chip peaks, docs/profiling.md);
            # the gateway's telemetry-aware placement can read how close to
            # the hardware each engine is running
            "perf": self.core.perf_info(),
            "metrics": self.core.metrics.summary(),
        }


class EngineError(RuntimeError):
    pass


def _find_stop(text: str, stops: list[str]) -> int | None:
    best: int | None = None
    for s in stops:
        if not s:
            continue
        idx = text.find(s)
        if idx != -1 and (best is None or idx < best):
            best = idx
    return best
