"""Step-loop introspection: per-step phase records, ring buffer, anomalies.

Answers "where does a step's time go?" for the engine's serving loop. Every
scheduler step (prefill dispatch or decode dispatch) produces ONE StepRecord
with a per-phase wall-clock breakdown:

  plan      — request admission: queue pop, constraint prep, prefix match,
              page reservation, slot claim (scheduler._try_insert)
  host_sync — host→device state refresh before a dispatch: block-table rows
              and grammar-mask rows changed since the last step
  dispatch  — the jitted step call returning its (async) futures: python +
              jax dispatch overhead, no device time
  compute   — jax.block_until_ready delta: actual device execution
  fetch     — device→host token readback (the per-step D2H sync)
  emit      — host-side token delivery: stop checks, grammar FSM advance,
              event-queue puts (detokenization itself runs on the service
              layer's consumer threads, off the step loop)

Records land in a bounded ring buffer served at the engine's ``/api/steps``
plus per-phase histograms in ``/metrics``. A slow-step anomaly detector
keeps an EMA of step time per kind and flags steps that exceed a
configurable multiple of it — the "one step took 40x the usual" events that
histograms average away.

The recorder is deliberately dumb and allocation-light: a handful of
``time.perf_counter()`` deltas per step and one dict append. The guarantee
(tested in tests/engine/test_step_introspection.py) is < 1% of step time on
the CPU debug engine, whose steps are orders of magnitude shorter than any
real TPU step.
"""

from __future__ import annotations

import threading
import time
from collections import deque

PHASES = ("plan", "draft", "host_sync", "dispatch", "compute", "fetch",
          "emit")

# Step kinds the scheduler dispatches. Each kind keeps its OWN EMA baseline
# in the slow-step detector: a K+1-token speculative verify step is
# legitimately several times a single-token decode step, so folding them
# into one baseline would either flag every verify step or mask genuinely
# slow decodes.
#   prefill — prompt KV fill (one-shot group, chunked extend, or CP pass)
#   decode  — 1-token (or burst-scanned k-token) step, one token/slot/step
#   verify  — speculative K+1-token verification (llmlb_tpu/spec): scores
#             the drafts in one extend-style dispatch; `tokens` on its
#             records counts tokens actually EMITTED (accepted + 1 per
#             slot), not positions scored
KINDS = ("prefill", "decode", "verify")

# EMA smoothing for the per-kind step-time baseline. Small alpha: the
# baseline should drift with load, not chase a single outlier.
_EMA_ALPHA = 0.05
# A step is anomalous when it exceeds max(ratio x EMA, floor). The floor
# keeps microsecond-scale CPU steps from flagging scheduler jitter.
_SLOW_RATIO = 4.0
_SLOW_FLOOR_S = 0.020
# Steps observed before the detector arms (the first steps of a fresh
# engine include XLA compiles and would all flag).
_WARMUP_STEPS = 16


class StepRecorder:
    """Bounded ring of per-step phase breakdowns + slow-step detection +
    a sliding window of (tokens, busy seconds) for live MFU math.

    Thread-safety: observe() runs on the step loop only; snapshot()/window()
    may run on scrape threads — everything mutable sits behind one lock
    held for microseconds.
    """

    def __init__(self, capacity: int = 512, *, slow_ratio: float = _SLOW_RATIO,
                 slow_floor_s: float = _SLOW_FLOOR_S,
                 window: int = 128):
        self.capacity = max(1, capacity)
        self.slow_ratio = slow_ratio
        self.slow_floor_s = slow_floor_s
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._seq = 0
        self._ema: dict[str, float] = {}  # kind -> EMA of total_s
        self._seen: dict[str, int] = {}
        self.slow_steps_total = 0
        # sliding window of decode steps for throughput-derived figures
        self._window: deque[tuple[float, int]] = deque(maxlen=max(1, window))

    # -------------------------------------------------------------- recording

    def observe(self, kind: str, phases: dict[str, float], *,
                active_slots: int = 0, tokens: int = 0,
                request_ids: dict[str, str] | None = None,
                dispatches: int = 0) -> bool:
        """Record one step; returns True when it was flagged anomalous.
        `phases` maps phase name -> seconds (missing phases count as 0);
        `tokens` is the number of tokens this step delivered to the host
        (decode: burst x active slots); `request_ids` maps slot id ->
        gateway request id for the requests riding this dispatch, so a
        flagged record NAMES its victims (/api/steps?slow=1); `dispatches`
        counts the device programs this step launched (the fused-decode
        invariant — scripts/check_fused_dispatch.py — asserts exactly 1 on
        decode/verify records when LLMLB_FUSED_DECODE is on)."""
        now = time.time()
        total = sum(phases.values())
        with self._lock:
            seen = self._seen.get(kind, 0)
            ema = self._ema.get(kind)
            slow = False
            if seen >= _WARMUP_STEPS and ema is not None:
                threshold = max(self.slow_ratio * ema, self.slow_floor_s)
                slow = total > threshold
                if slow:
                    self.slow_steps_total += 1
            # anomalous steps do not feed the baseline: one 40x step must
            # not drag the EMA up and mask the next one
            if ema is None:
                self._ema[kind] = total
            elif not slow:
                self._ema[kind] = ema + _EMA_ALPHA * (total - ema)
            self._seen[kind] = seen + 1
            self._seq += 1
            self._ring.append({
                "seq": self._seq,
                "ts": now,
                "kind": kind,
                "total_s": total,
                "phases_s": {p: phases.get(p, 0.0) for p in PHASES},
                "active_slots": active_slots,
                "tokens": tokens,
                "dispatches": dispatches,
                "request_ids": dict(request_ids) if request_ids else {},
                "slow": slow,
            })
            # decode AND verify steps feed the throughput window: both
            # deliver committed tokens, and live MFU must see speculative
            # throughput or it would collapse the moment speculation engages
            if kind in ("decode", "verify") and tokens > 0:
                self._window.append((total, tokens))
        return slow

    # --------------------------------------------------------------- reading

    @property
    def seq(self) -> int:
        """Sequence number of the most recent record (0 before the first).
        Lock-free read of an int the GIL keeps coherent."""
        return self._seq

    def window_throughput(self) -> tuple[float, int]:
        """(busy seconds, tokens) over the sliding decode window — the
        denominator/numerator for live MFU. Busy seconds exclude idle loop
        sleeps: MFU is measured against time the device was actually
        stepping, which is the figure an operator tunes kernels by."""
        with self._lock:
            if not self._window:
                return 0.0, 0
            secs = sum(s for s, _ in self._window)
            toks = sum(t for _, t in self._window)
        return secs, toks

    def snapshot(self, limit: int = 64, *, slow_only: bool = False) -> dict:
        """JSON-safe view for /api/steps: recent records (newest first),
        per-kind EMA baselines, and the anomaly counter."""
        limit = max(0, min(limit, self.capacity))
        with self._lock:
            records = list(self._ring)
            ema = dict(self._ema)
            slow_total = self.slow_steps_total
            seq = self._seq
        if slow_only:
            records = [r for r in records if r["slow"]]
        records = records[-limit:]
        records.reverse()
        # copies: the ring's dicts stay untouched for concurrent snapshots
        records = [
            {**r,
             "total_s": round(r["total_s"], 6),
             "phases_s": {k: round(v, 6) for k, v in r["phases_s"].items()}}
            for r in records
        ]
        return {
            "steps_total": seq,
            "buffered": len(self._ring) if not slow_only else None,
            "capacity": self.capacity,
            "slow_steps_total": slow_total,
            "ema_step_s": {k: round(v, 6) for k, v in ema.items()},
            "slow_ratio": self.slow_ratio,
            "slow_floor_s": self.slow_floor_s,
            "records": records,
        }
