"""TPU chip/HBM telemetry for the engine's /api/health endpoint.

Replaces the GPU VRAM/utilization fields the reference's health checker reads
from xLLM endpoints (/root/reference/llmlb/src/health/endpoint_checker.rs:515,
types/health.rs) with libtpu-backed figures surfaced through JAX device APIs.
The gateway's scheduler consumes these for placement decisions.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Per-chip peak figures for utilization math (bf16 and int8 dense
    FLOPs, HBM bandwidth). Public spec-sheet numbers; MFU/HBM-utilization
    gauges divide measured work by these. `peak_flops_int8` is the OPS
    figure quantized serving is judged against (v5e/v5p/v6e double their
    bf16 rate on int8 operands; v4 has no int8 fast path — same figure)."""

    generation: str
    peak_flops: float  # bf16 FLOP/s per chip
    peak_hbm_bw: float  # bytes/s per chip
    peak_flops_int8: float = 0.0  # int8 OP/s per chip (0 -> same as bf16)

    @property
    def int8_flops(self) -> float:
        return self.peak_flops_int8 or self.peak_flops


# Keyed by a normalized device_kind substring (lowercase, spaces stripped).
# jax reports e.g. "TPU v4", "TPU v5 lite", "TPU v5p", "TPU v6 lite".
# Order matters: more specific keys first ("v5p" before "v5").
CHIP_SPECS: tuple[tuple[str, ChipSpec], ...] = (
    ("v6lite", ChipSpec("v6e", 918e12, 1.64e12, 1836e12)),
    ("v6e", ChipSpec("v6e", 918e12, 1.64e12, 1836e12)),
    ("v5p", ChipSpec("v5p", 459e12, 2.765e12, 918e12)),
    ("v5lite", ChipSpec("v5e", 197e12, 0.82e12, 394e12)),
    ("v5e", ChipSpec("v5e", 197e12, 0.82e12, 394e12)),
    ("v4", ChipSpec("v4", 275e12, 1.23e12)),
)


def chip_spec_for(device_kind: str) -> ChipSpec | None:
    """Resolve a jax device_kind string to its peak specs (None for CPU /
    unknown chips — utilization gauges are then unavailable, never wrong)."""
    key = str(device_kind).lower().replace(" ", "")
    for frag, spec in CHIP_SPECS:
        if frag in key:
            return spec
    return None


def model_flops_per_token(cfg, n_params: int) -> float:
    """Decode FLOPs per generated token: ~2 FLOPs per parameter touched
    (one multiply + one add per weight). MoE models only touch the routed
    experts' FFN weights, so count active params, not total."""
    experts = getattr(cfg, "num_experts", 0) or 0
    if experts > 1:
        per_tok = getattr(cfg, "experts_per_token", 1) or 1
        # FFN weights are the expert-replicated part; attention/embed are
        # shared. Approximate: scale the FFN fraction by routed/total.
        ffn = (3 * cfg.hidden_size * cfg.intermediate_size
               * cfg.num_layers * experts)
        active = n_params - ffn + ffn * per_tok / experts
        return 2.0 * active
    return 2.0 * n_params


def model_bytes_per_token(cfg, n_params: int, mean_context: float,
                          batch: int = 1, *,
                          weight_bytes: float | None = None,
                          kv_cell_bytes: float | None = None) -> float:
    """HBM bytes read per decoded token: every weight once per STEP (decode
    is memory-bound; weights dominate and are amortized across the `batch`
    sequences decoded together) plus the KV rows of the sequence's own
    context (never amortized — each sequence reads its own).

    Quantization overrides (llmlb_tpu/quant): `weight_bytes` is the actual
    total parameter footprint (int8 values + f32 scales when weights are
    quantized — the engine passes its measured device-array bytes), and
    `kv_cell_bytes` the bytes per cached (token, head) cell (D·1 + 4-byte
    scale under int8 KV vs D·itemsize bf16). Defaults reproduce the
    unquantized bf16 math exactly."""
    import jax.numpy as jnp

    itemsize = jnp.dtype(cfg.dtype).itemsize
    if weight_bytes is None:
        weight_bytes = n_params * itemsize
    if kv_cell_bytes is None:
        kv_cell_bytes = cfg.head_dim_ * itemsize
    kv_bytes = (cfg.num_layers * mean_context * cfg.num_kv_heads
                * kv_cell_bytes * 2)
    return weight_bytes / max(1, batch) + kv_bytes


def device_telemetry() -> dict[str, Any]:
    devices = jax.local_devices()
    chips = []
    hbm_used_total = 0
    hbm_limit_total = 0
    for d in devices:
        stats: dict[str, Any] = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        used = int(stats.get("bytes_in_use", 0))
        limit = int(stats.get("bytes_limit", 0))
        hbm_used_total += used
        hbm_limit_total += limit
        chips.append(
            {
                "id": d.id,
                "platform": d.platform,
                "device_kind": getattr(d, "device_kind", "unknown"),
                "hbm_used_bytes": used,
                "hbm_total_bytes": limit,
            }
        )
    return {
        "accelerator": devices[0].platform if devices else "none",
        "chip_count": len(devices),
        "hbm_used_bytes": hbm_used_total,
        "hbm_total_bytes": hbm_limit_total,
        "chips": chips,
    }
