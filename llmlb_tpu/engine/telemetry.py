"""TPU chip/HBM telemetry for the engine's /api/health endpoint.

Replaces the GPU VRAM/utilization fields the reference's health checker reads
from xLLM endpoints (/root/reference/llmlb/src/health/endpoint_checker.rs:515,
types/health.rs) with libtpu-backed figures surfaced through JAX device APIs.
The gateway's scheduler consumes these for placement decisions.
"""

from __future__ import annotations

from typing import Any

import jax


def device_telemetry() -> dict[str, Any]:
    devices = jax.local_devices()
    chips = []
    hbm_used_total = 0
    hbm_limit_total = 0
    for d in devices:
        stats: dict[str, Any] = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        used = int(stats.get("bytes_in_use", 0))
        limit = int(stats.get("bytes_limit", 0))
        hbm_used_total += used
        hbm_limit_total += limit
        chips.append(
            {
                "id": d.id,
                "platform": d.platform,
                "device_kind": getattr(d, "device_kind", "unknown"),
                "hbm_used_bytes": used,
                "hbm_total_bytes": limit,
            }
        )
    return {
        "accelerator": devices[0].platform if devices else "none",
        "chip_count": len(devices),
        "hbm_used_bytes": hbm_used_total,
        "hbm_total_bytes": hbm_limit_total,
        "chips": chips,
    }
