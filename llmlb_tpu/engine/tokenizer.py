"""Tokenizer abstraction for the engine.

Real checkpoints use the HF tokenizer shipped next to the weights. Random-weight
mode (benches, tests, CI — no network, no checkpoint) falls back to a byte-level
tokenizer so the full serving path (template → encode → decode → stream) is
exercised without any model artifacts. The reference counts tokens with tiktoken
only for *accounting* (/root/reference/llmlb/src/token/mod.rs:217); here the
tokenizer is load-bearing for inference itself.
"""

from __future__ import annotations

from typing import Protocol, Sequence


class Tokenizer(Protocol):
    eos_id: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    def apply_chat_template(self, messages: list[dict]) -> str: ...


def default_chat_template(messages: list[dict]) -> str:
    """Minimal ChatML-style rendering used when no HF template is available."""
    parts = []
    for m in messages:
        content = m.get("content") or ""
        if isinstance(content, list):  # OpenAI content-part arrays
            content = "".join(
                p.get("text", "") for p in content if isinstance(p, dict)
            )
        parts.append(f"<|{m.get('role', 'user')}|>\n{content}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0..255 are bytes, 256 is EOS/pad."""

    def __init__(self, vocab_size: int = 512):
        if vocab_size < 258:
            raise ValueError("ByteTokenizer needs vocab_size >= 258")
        self.eos_id = 256
        self.bos_id = 257
        self.vocab_size = vocab_size

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: list[dict]) -> str:
        return default_chat_template(messages)


class HFTokenizer:
    """Wraps a transformers tokenizer loaded from a checkpoint directory."""

    def __init__(self, model_dir: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(model_dir)
        self.eos_id = self._tok.eos_token_id

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=True)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict]) -> str:
        if getattr(self._tok, "chat_template", None):
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True
            )
        return default_chat_template(messages)


class IncrementalDetokenizer:
    """Streams text out of a growing id sequence without re-emitting prefixes.

    Decodes the full sequence each call and diffs against what was already
    emitted — robust to multi-byte/multi-token characters (a naive per-token
    decode emits U+FFFD for split UTF-8 sequences).
    """

    def __init__(self, tokenizer: Tokenizer):
        self._tok = tokenizer
        self._ids: list[int] = []
        self._emitted = 0

    def push(self, token_id: int) -> str:
        self._ids.append(token_id)
        text = self._tok.decode(self._ids)
        # Hold back a trailing replacement char: likely a split multi-byte seq.
        safe_end = len(text)
        if text.endswith("�"):
            safe_end = len(text) - 1
        delta = text[self._emitted : safe_end]
        self._emitted = safe_end
        return delta

    def flush(self) -> str:
        text = self._tok.decode(self._ids)
        delta = text[self._emitted :]
        self._emitted = len(text)
        return delta
