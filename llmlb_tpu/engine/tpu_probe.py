"""Staged TPU backend probe: diagnose init hangs instead of suffering them.

A broken libtpu / PJRT plugin / axon tunnel hangs `jax.devices()` forever
with no output — BENCH_r03–r05 all timed out exactly there, which is why
every committed bench number is still CPU (ROADMAP item 2). This module is
the shared diagnosis plumbing:

- The probe runs in a CHILD process, staged (import jax → device enum →
  tiny matmul) with `faulthandler` stack dumps every 30s, so a hang reports
  WHERE it hangs (e.g. jaxlib make_c_api_client waiting on the PJRT
  plugin's device claim) and the captured libtpu/PJRT log tail survives the
  kill.
- `bench.py` uses it before committing to a TPU run (evidence lands in the
  BENCH json `tail`); the ENGINE SERVER uses it at startup via
  `guard_backend_init` — a configurable init timeout that dumps the child's
  stderr tail to the server log and exits nonzero instead of wedging a
  deployment silently.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys

log = logging.getLogger("llmlb_tpu.engine.tpu_probe")

PROBE_TIMEOUT_S = 150
PROBE_LONG_TIMEOUT_S = 420  # init over a tunnel can legitimately take minutes

# The staged probe runs in a child with faulthandler stack dumps every 30s, so
# a hang reports WHERE it hangs instead of just "timed out".
PROBE_CODE = r"""
import faulthandler, sys, time
faulthandler.enable()
faulthandler.dump_traceback_later(30, repeat=True, file=sys.stderr)
t0 = time.time()
def mark(stage):
    print(f"[probe +{time.time()-t0:.1f}s] {stage}", file=sys.stderr, flush=True)
mark("stage1: import jax")
import jax
mark(f"stage1 done: jax {jax.__version__}")
mark("stage2: jax.devices() (backend init)")
d = jax.devices()
mark(f"stage2 done: {len(d)}x {getattr(d[0], 'device_kind', '?')}")
mark("stage3: tiny matmul")
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
mark("stage3 done")
print(jax.default_backend(), len(d), getattr(d[0], 'device_kind', '?'))
"""


def tail(text: str | bytes | None, lines: int = 25) -> list[str]:
    """Last N lines of captured child output, each clipped — the evidence
    payload for BENCH json and startup failure logs."""
    if not text:
        return []
    if isinstance(text, bytes):
        text = text.decode("utf-8", "replace")
    return [ln[:300] for ln in text.strip().splitlines()[-lines:]]


def probe_env() -> dict:
    """Child env with verbose libtpu/PJRT init logging, so a hang leaves a
    trail in the captured stderr."""
    env = dict(os.environ)
    env.setdefault("TPU_STDERR_LOG_LEVEL", "0")
    env.setdefault("TPU_MIN_LOG_LEVEL", "0")
    env.setdefault("JAX_DEBUG_LOG_MODULES", "jax._src.xla_bridge")
    return env


def staged_probe(
    timeouts: tuple[float, ...] = (PROBE_TIMEOUT_S, PROBE_LONG_TIMEOUT_S),
    *,
    code: str | None = None,
    log_fn=None,
) -> tuple[bool, str, dict]:
    """Run the staged probe subprocess once per timeout until it succeeds.
    Returns (ok, diagnostic, evidence) — evidence carries per-attempt
    outcome + child stdout/stderr tails (JSON-safe)."""
    if code is None:
        code = PROBE_CODE  # module attr at call time: tests may patch it
    emit = log_fn or (lambda msg: log.info("%s", msg))
    env = probe_env()
    evidence: dict = {"attempts": []}
    last = ""
    for attempt, timeout_s in enumerate(timeouts, start=1):
        emit(f"TPU probe attempt {attempt}/{len(timeouts)} "
             f"(timeout {timeout_s}s)")
        rec: dict = {"attempt": attempt, "timeout_s": timeout_s}
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout_s, env=env,
            )
        except subprocess.TimeoutExpired as te:
            # TimeoutExpired carries the child's output so far — keep it.
            rec["outcome"] = f"timeout after {timeout_s}s"
            rec["child_stderr_tail"] = tail(te.stderr)
            rec["child_stdout_tail"] = tail(te.stdout)
            evidence["attempts"].append(rec)
            last = f"probe timed out after {timeout_s}s (backend init hang)"
            emit(last)
            for ln in rec["child_stderr_tail"]:
                emit(f"  child| {ln}")
            continue
        rec["returncode"] = r.returncode
        if r.returncode == 0 and r.stdout.strip():
            out = r.stdout.strip().splitlines()[-1]
            emit(f"TPU probe OK: {out}")
            rec["outcome"] = f"ok: {out}"
            evidence["attempts"].append(rec)
            if out.startswith(("tpu", "axon")):
                return True, out, evidence
            last = f"backend is {out!r}, not tpu"
            return False, last, evidence
        rec["outcome"] = f"rc={r.returncode}"
        rec["child_stderr_tail"] = tail(r.stderr)
        rec["child_stdout_tail"] = tail(r.stdout)
        evidence["attempts"].append(rec)
        t = rec["child_stderr_tail"] or rec["child_stdout_tail"] or ["unknown"]
        last = f"probe rc={r.returncode}: {t[-1]}"
        emit(last)
    return False, last, evidence


def tpu_expected() -> bool:
    """Host-side evidence that a TPU backend-init attempt is coming: the
    operator pinned tpu, TPU-VM metadata is present, or accelerator device
    nodes exist. Mirrors bench.py's detection (one policy, two callers)."""
    env_platform = os.environ.get("JAX_PLATFORMS", "")
    if "tpu" in env_platform.lower():
        return True
    if env_platform:  # operator pinned cpu/gpu: no TPU init will run
        return False
    for name in ("TPU_NAME", "TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES",
                 "COLAB_TPU_ADDR", "TPU_ACCELERATOR_TYPE"):
        if os.environ.get(name):
            return True
    import glob

    return bool(glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*"))


def guard_backend_init(timeout_s: float | None = None) -> None:
    """Engine-server startup guard (ROADMAP item 2 prerequisite): before the
    first in-process jax backend touch, prove the TPU backend initializes
    within `timeout_s` in a CHILD — a hang there dumps the captured
    libtpu/PJRT log tail + staged faulthandler stacks to stderr and raises
    SystemExit, instead of the server wedging silently in jax.devices().

    No-op when no TPU init is expected on this host (CPU deployments must
    not pay a probe subprocess) or when disabled with timeout 0.
    `timeout_s` defaults from LLMLB_INIT_TIMEOUT (seconds; default 600)."""
    if timeout_s is None:
        raw = os.environ.get("LLMLB_INIT_TIMEOUT", "")
        try:
            timeout_s = float(raw) if raw else 600.0
        except ValueError:
            log.warning("LLMLB_INIT_TIMEOUT=%r is not a number; using 600",
                        raw)
            timeout_s = 600.0
    if timeout_s <= 0 or not tpu_expected():
        return
    ok, diag, evidence = staged_probe(
        (timeout_s,), log_fn=lambda m: log.info("[init-probe] %s", m)
    )
    if ok:
        return
    print("=" * 72, file=sys.stderr)
    print(f"TPU backend init FAILED: {diag}", file=sys.stderr)
    for rec in evidence["attempts"]:
        print(f"-- attempt {rec['attempt']} ({rec['outcome']}):",
              file=sys.stderr)
        for ln in rec.get("child_stderr_tail", []):
            print(f"   {ln}", file=sys.stderr)
    print("(set LLMLB_INIT_TIMEOUT=0 to skip this guard, or "
          "JAX_PLATFORMS=cpu to serve on CPU)", file=sys.stderr)
    print("=" * 72, file=sys.stderr)
    raise SystemExit(
        f"TPU backend init did not complete within {timeout_s:.0f}s: {diag}"
    )
