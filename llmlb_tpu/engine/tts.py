"""TTS service: text → WAV bytes via the JAX TTS model (models/tts.py).

Serves /v1/audio/speech on the tpu:// engine (reference proxies these to
endpoints advertising the AudioSpeech capability, api/audio.rs:377). WAV
encoding is stdlib `wave`; no external audio dependencies.
"""

from __future__ import annotations

import io
import wave

import jax
import jax.numpy as jnp
import numpy as np

from llmlb_tpu.models import tts
from llmlb_tpu.models.whisper import SAMPLE_RATE


def encode_wav(audio: np.ndarray, sample_rate: int = SAMPLE_RATE) -> bytes:
    """Mono float32 [-1, 1] -> RIFF/WAV PCM16 bytes."""
    pcm = np.clip(audio, -1.0, 1.0)
    pcm16 = (pcm * 32767.0).astype("<i2")
    buf = io.BytesIO()
    with wave.open(buf, "wb") as wf:
        wf.setnchannels(1)
        wf.setsampwidth(2)
        wf.setframerate(sample_rate)
        wf.writeframes(pcm16.tobytes())
    return buf.getvalue()


class TtsEngine:
    """One loaded TTS model + synthesis entry points."""

    MAX_INPUT_CHARS = 4096  # matches OpenAI's /v1/audio/speech input cap

    def __init__(self, cfg: tts.TtsConfig, params, model_id: str = "tts"):
        self.cfg = cfg
        self.params = jax.tree.map(jnp.asarray, params)
        self.model_id = model_id
        self.total_requests = 0

    @classmethod
    def from_random(cls, cfg: tts.TtsConfig | None = None,
                    model_id: str = "tts-random", seed: int = 0):
        cfg = cfg or tts.TtsConfig(
            d_model=64, encoder_layers=2, decoder_layers=2, num_heads=4,
            upsample=4, max_text_len=128,
        )
        return cls(cfg, tts.init_params(cfg, jax.random.PRNGKey(seed)),
                   model_id=model_id)

    @classmethod
    def from_checkpoint(cls, model_dir: str, model_id: str | None = None):
        cfg, params = tts.load_checkpoint(model_dir)
        import os

        return cls(cfg, params,
                   model_id or os.path.basename(model_dir.rstrip("/")))

    def synthesize(self, text: str, voice: str = "alloy",
                   speed: float = 1.0) -> bytes:
        """Text -> WAV bytes. `speed` resamples the output (0.25-4.0)."""
        if not text:
            raise ValueError("'input' text must not be empty")
        if len(text) > self.MAX_INPUT_CHARS:
            raise ValueError(
                f"input too long ({len(text)} chars; max {self.MAX_INPUT_CHARS})"
            )
        if not 0.25 <= speed <= 4.0:
            raise ValueError("'speed' must be between 0.25 and 4.0")
        self.total_requests += 1

        data = text.encode("utf-8", errors="replace")[: self.cfg.max_text_len]
        n = len(data)
        bucket = 16
        while bucket < n:
            bucket *= 2
        bucket = min(bucket, self.cfg.max_text_len)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :n] = np.frombuffer(data, np.uint8)
        mel = tts.synthesize_mel(
            self.params, self.cfg, jnp.asarray(ids),
            jnp.asarray([n], np.int32),
            jnp.asarray([tts.voice_id(voice)], np.int32),
        )[0]
        # vocode at the bucketed length (griffin_lim is jitted per shape —
        # trimming mel first would recompile for every distinct text length),
        # then trim the synthesized audio to the real frame count
        audio = np.asarray(tts.griffin_lim(mel))
        from llmlb_tpu.models.whisper import HOP_LENGTH

        audio = audio[: n * self.cfg.upsample * HOP_LENGTH]
        if speed != 1.0:
            n_out = max(1, int(round(len(audio) / speed)))
            audio = np.interp(
                np.linspace(0, len(audio) - 1, n_out),
                np.arange(len(audio)), audio,
            ).astype(np.float32)
        return encode_wav(audio)
