"""Checkpoint ingestion: HF-format safetensors → sharded device arrays.

TPU-native equivalent of the reference's `poc/nemotron-safetensors-cpp` probe
(SURVEY.md §2.3 item 2): instead of just mmapping and reporting tensors, we map
HF names onto the model pytree, transpose to our [in, out] matmul layout, stack
layers for `lax.scan`, and `jax.device_put` each leaf with its NamedSharding so
every host touches only its shard. A C++ mmap reader (native/) accelerates the
host-side read path; `safetensors.numpy` is the portable fallback.

Loading is STREAMING per tensor: `load_checkpoint` builds one pytree leaf at a
time (stack → cast → optional int8 quantization → device_put → drop the host
copy), so peak host RAM is one stacked tensor plus the device arrays instead
of a full second model-size host copy. With `quantize_weights=True` the big
projection matrices quantize per output channel BEFORE transfer
(llmlb_tpu/quant), so the H2D traffic and the device footprint are the int8
bytes too.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Mapping

import jax
import numpy as np

from llmlb_tpu.models.llama import LlamaConfig, Params, param_shardings
from llmlb_tpu.quant import WEIGHT_QUANT_NAMES, quantize_channelwise

TensorGetter = Callable[[str], np.ndarray]
LeafBuilder = Callable[[TensorGetter], np.ndarray]


def _param_builders(cfg: LlamaConfig) -> dict[str, LeafBuilder]:
    """Per-leaf builder functions (name → fn(get) -> host ndarray) in pytree
    order. Builders are lazy so the streaming loader materializes exactly one
    stacked tensor at a time."""

    def stack(fmt: str, transpose: bool) -> LeafBuilder:
        def build(get: TensorGetter) -> np.ndarray:
            leaves = []
            for i in range(cfg.num_layers):
                w = get(fmt.format(i=i))
                leaves.append(w.T if transpose else w)
            return np.stack(leaves)

        return build

    def single(name: str, transpose: bool = False) -> LeafBuilder:
        def build(get: TensorGetter) -> np.ndarray:
            w = get(name)
            return w.T if transpose else w

        return build

    if getattr(cfg, "num_experts", 0) > 1:
        return _moe_param_builders(cfg, stack, single)

    builders: dict[str, LeafBuilder] = {
        "embed": single("model.embed_tokens.weight"),
        "wq": stack("model.layers.{i}.self_attn.q_proj.weight", True),
        "wk": stack("model.layers.{i}.self_attn.k_proj.weight", True),
        "wv": stack("model.layers.{i}.self_attn.v_proj.weight", True),
        "wo": stack("model.layers.{i}.self_attn.o_proj.weight", True),
        "wg": stack("model.layers.{i}.mlp.gate_proj.weight", True),
        "wu": stack("model.layers.{i}.mlp.up_proj.weight", True),
        "wd": stack("model.layers.{i}.mlp.down_proj.weight", True),
        "ln_attn": stack("model.layers.{i}.input_layernorm.weight", False),
        "ln_mlp": stack("model.layers.{i}.post_attention_layernorm.weight",
                        False),
        "ln_final": single("model.norm.weight"),
    }
    if cfg.attention_bias:
        builders["bq"] = stack("model.layers.{i}.self_attn.q_proj.bias", False)
        builders["bk"] = stack("model.layers.{i}.self_attn.k_proj.bias", False)
        builders["bv"] = stack("model.layers.{i}.self_attn.v_proj.bias", False)
    if not cfg.tie_word_embeddings:
        builders["lm_head"] = single("lm_head.weight", True)
    return builders


def _moe_param_builders(cfg, stack, single) -> dict[str, LeafBuilder]:
    """Mixtral layout: block_sparse_moe.gate + experts.{e}.w1/w3/w2 per layer
    (w1 = gate/silu branch, w3 = up, w2 = down in HF's naming)."""

    def stack_experts(wname: str, transpose: bool) -> LeafBuilder:
        def build(get: TensorGetter) -> np.ndarray:
            layers = []
            for i in range(cfg.num_layers):
                experts = []
                for e in range(cfg.num_experts):
                    w = get(
                        f"model.layers.{i}.block_sparse_moe.experts.{e}"
                        f".{wname}.weight"
                    )
                    experts.append(w.T if transpose else w)
                layers.append(np.stack(experts))
            return np.stack(layers)  # [L, E_experts, ...]

        return build

    builders: dict[str, LeafBuilder] = {
        "embed": single("model.embed_tokens.weight"),
        "wq": stack("model.layers.{i}.self_attn.q_proj.weight", True),
        "wk": stack("model.layers.{i}.self_attn.k_proj.weight", True),
        "wv": stack("model.layers.{i}.self_attn.v_proj.weight", True),
        "wo": stack("model.layers.{i}.self_attn.o_proj.weight", True),
        "router": stack("model.layers.{i}.block_sparse_moe.gate.weight", True),
        "we_gate": stack_experts("w1", True),
        "we_up": stack_experts("w3", True),
        "we_down": stack_experts("w2", True),
        "ln_attn": stack("model.layers.{i}.input_layernorm.weight", False),
        "ln_mlp": stack("model.layers.{i}.post_attention_layernorm.weight",
                        False),
        "ln_final": single("model.norm.weight"),
    }
    if not cfg.tie_word_embeddings:
        builders["lm_head"] = single("lm_head.weight", True)
    return builders


def convert_hf_tensors(cfg: LlamaConfig, get: TensorGetter) -> Params:
    """Map HF llama/qwen2/mistral/mixtral tensor names to our stacked pytree
    (all leaves materialized at once — tests and tooling; the serving load
    path streams per tensor via load_checkpoint instead)."""
    return {name: build(get) for name, build in _param_builders(cfg).items()}


def _open_shard(path: str):
    """Prefer the C++ mmap reader (native/safetensors_reader.cpp); fall back
    to the safetensors package. Both expose keys()/get_tensor()."""
    try:
        from llmlb_tpu.native import NativeSafetensors

        return NativeSafetensors(path)
    except Exception:
        from safetensors import safe_open

        return safe_open(path, framework="numpy")


def _close_shard(shard) -> None:
    """Release a reader from _open_shard (NativeSafetensors or safe_open)."""
    if hasattr(shard, "close"):
        shard.close()
    elif hasattr(shard, "__exit__"):
        shard.__exit__(None, None, None)


def _safetensors_getter(model_dir: str) -> TensorGetter:
    """Build a name→tensor getter over all *.safetensors shards in a directory."""
    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    name_to_file: dict[str, str] = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            name_to_file = json.load(f)["weight_map"]
    else:
        for fname in sorted(os.listdir(model_dir)):
            if fname.endswith(".safetensors"):
                shard = _open_shard(os.path.join(model_dir, fname))
                try:
                    for name in shard.keys():
                        name_to_file[name] = fname
                finally:
                    _close_shard(shard)  # native readers mmap the whole file
    handles: dict[str, object] = {}

    def get(name: str) -> np.ndarray:
        fname = name_to_file[name]
        if fname not in handles:
            handles[fname] = _open_shard(os.path.join(model_dir, fname))
        return handles[fname].get_tensor(name)

    return get


def load_config(model_dir: str, dtype=None) -> LlamaConfig:
    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)
    kwargs = {} if dtype is None else {"dtype": dtype}
    if hf.get("model_type") == "mixtral" or hf.get("num_local_experts", 0) > 1:
        from llmlb_tpu.models.mixtral import MixtralConfig

        return MixtralConfig.from_hf_config(hf, **kwargs)
    return LlamaConfig.from_hf_config(hf, **kwargs)


def load_checkpoint(model_dir: str, cfg: LlamaConfig, mesh=None,
                    quantize_weights: bool = False) -> Params:
    """Load a HF checkpoint directory into (optionally sharded) device arrays.

    Streams one pytree leaf at a time: build the stacked host tensor, cast to
    the serving dtype, quantize it (per-output-channel int8 + f32 scales,
    when requested and the leaf is a projection matrix), `device_put`, then
    drop the host copy before touching the next leaf. Peak host RAM is one
    stacked tensor — not a second full model copy."""
    from llmlb_tpu.models import family_for

    get = _safetensors_getter(model_dir)
    shardings = (family_for(cfg).param_shardings(cfg, mesh)
                 if mesh is not None else None)

    def put(name: str, host: np.ndarray):
        if shardings is None:
            return jax.numpy.asarray(host)
        return jax.device_put(host, shardings[name])

    dtype = np.dtype(cfg.dtype)
    params: Params = {}
    for name, build in _param_builders(cfg).items():
        host = build(get)
        if quantize_weights and name in WEIGHT_QUANT_NAMES:
            q, scale = quantize_channelwise(np.asarray(host))
            params[name] = put(name, q)
            params[f"{name}_scale"] = put(f"{name}_scale", scale)
        else:
            params[name] = put(name, np.asarray(host, dtype=dtype))
        del host  # streaming contract: one host leaf live at a time
    return params
