"""The gateway: an OpenAI-compatible LLM load balancer fronting many endpoints.

Python/aiohttp re-design of the reference's Rust axum server (SURVEY.md §1-§3):
API surface (OpenAI /v1/*, Anthropic /v1/messages, admin /api/*, dashboard WS),
TPS-EMA load balancing with request leases, pull health checking, endpoint type
detection (tpu:// first), model sync, JWT/API-key auth, tamper-evident audit
log, SQLite persistence, event bus, drain-aware update gate. Hot-path pieces
(token accounting, hash chain, TPS tracking) have C++ twins in native/.
"""
