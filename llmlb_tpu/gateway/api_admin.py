"""Admin /api/* surface: endpoints CRUD + auth + users + keys + invitations +
audit queries + settings + system info.

Parity with reference api/{endpoints,auth,users,api_keys,invitations,
audit_log,system}.rs route behavior (SURVEY.md §2.1).
"""

from __future__ import annotations

import asyncio
import secrets
import time

import aiohttp
from aiohttp import web

from llmlb_tpu import __version__
from llmlb_tpu.gateway.auth import (
    CSRF_COOKIE,
    JWT_COOKIE,
    AuthError,
    create_jwt,
)
from llmlb_tpu.gateway.detection import (
    DetectionError,
    Unreachable,
    detect_endpoint_type,
)
from llmlb_tpu.gateway.model_sync import sync_endpoint_models
from llmlb_tpu.gateway.types import (
    Endpoint,
    EndpointStatus,
    EndpointType,
    Permission,
    Role,
)


def _json_error(status: int, message: str) -> web.Response:
    return web.json_response({"error": message}, status=status)


def endpoint_to_json(ep: Endpoint, models: list | None = None) -> dict:
    out = {
        "id": ep.id,
        "name": ep.name,
        "base_url": ep.base_url,
        "endpoint_type": ep.endpoint_type.value,
        "status": ep.status.value,
        "breaker_state": ep.breaker_state,
        # disaggregation role as of the last health probe ("both" when the
        # endpoint advertises none — docs/disaggregation.md)
        "role": ep.accelerator.role or "both",
        "latency_ms": ep.latency_ms,
        "consecutive_failures": ep.consecutive_failures,
        "accelerator": {
            "accelerator": ep.accelerator.accelerator,
            "chip_count": ep.accelerator.chip_count,
            "hbm_used_bytes": ep.accelerator.hbm_used_bytes,
            "hbm_total_bytes": ep.accelerator.hbm_total_bytes,
            "utilization": ep.accelerator.utilization,
        },
        "created_at": ep.created_at,
        "updated_at": ep.updated_at,
        "last_checked_at": ep.last_checked_at,
        "has_api_key": bool(ep.api_key),
    }
    if models is not None:
        out["models"] = [
            {
                "model_id": m.model_id,
                "canonical_name": m.canonical_name,
                "capabilities": [c.value for c in m.capabilities],
                "context_length": m.context_length,
            }
            for m in models
        ]
    return out


# ------------------------------------------------------------- endpoints API


async def list_endpoints(request: web.Request) -> web.Response:
    state = request.app["state"]
    out = [
        endpoint_to_json(ep, state.registry.models_for(ep.id))
        for ep in state.registry.list_all()
    ]
    out.sort(key=lambda e: (e["latency_ms"] is None, e["latency_ms"] or 0))
    return web.json_response({"endpoints": out})


async def get_endpoint(request: web.Request) -> web.Response:
    state = request.app["state"]
    ep = state.registry.get(request.match_info["endpoint_id"])
    if ep is None:
        return _json_error(404, "endpoint not found")
    return web.json_response(endpoint_to_json(ep, state.registry.models_for(ep.id)))


async def get_endpoint_system_info(request: web.Request) -> web.Response:
    """Live device/system probe of one endpoint's runtime (reference
    system_info/mod.rs dispatch; llama.cpp /slots + /metrics, TPU
    /api/health, Ollama /api/version + /api/ps, xLLM /api/system)."""
    from llmlb_tpu.gateway.system_info import get_endpoint_system_info as probe

    state = request.app["state"]
    ep = state.registry.get(request.match_info["endpoint_id"])
    if ep is None:
        return _json_error(404, "endpoint not found")
    info = await probe(ep, state.http)
    return web.json_response({
        "endpoint_id": ep.id,
        "endpoint_type": ep.endpoint_type.value,
        "available": info is not None,
        "info": info,
    })


async def create_endpoint(request: web.Request) -> web.Response:
    state = request.app["state"]
    try:
        body = await request.json()
    except Exception:
        return _json_error(400, "invalid JSON body")
    base_url = (body.get("base_url") or body.get("url") or "").strip()
    if not base_url.startswith(("http://", "https://")):
        return _json_error(400, "base_url must be an http(s) URL")
    name = body.get("name") or base_url
    ep = Endpoint(
        name=name, base_url=base_url, api_key=body.get("api_key"),
        status=EndpointStatus.PENDING,
    )
    requested_type = body.get("endpoint_type")
    if requested_type:
        try:
            ep.endpoint_type = EndpointType(requested_type)
        except ValueError:
            return _json_error(400, f"unknown endpoint_type {requested_type!r}")
    else:
        try:
            ep.endpoint_type = await detect_endpoint_type(
                base_url, state.http, timeout=state.config.health_check_timeout_s,
                api_key=ep.api_key,
            )
        except Unreachable:
            ep.endpoint_type = EndpointType.OPENAI_COMPATIBLE  # checked later
        except DetectionError:
            ep.endpoint_type = EndpointType.OPENAI_COMPATIBLE
    try:
        state.registry.add(ep)
    except ValueError as e:
        return _json_error(409, str(e))
    state.events.publish(
        "EndpointRegistered", {"endpoint_id": ep.id, "name": ep.name}
    )
    # immediate first health check + model sync (registration UX parity)
    if state.health_checker is not None:
        await state.health_checker.check_endpoint(ep)
        ep = state.registry.get(ep.id) or ep
    return web.json_response(
        endpoint_to_json(ep, state.registry.models_for(ep.id)), status=201
    )


async def update_endpoint(request: web.Request) -> web.Response:
    state = request.app["state"]
    ep = state.registry.get(request.match_info["endpoint_id"])
    if ep is None:
        return _json_error(404, "endpoint not found")
    try:
        body = await request.json()
    except Exception:
        return _json_error(400, "invalid JSON body")
    if "name" in body:
        ep.name = str(body["name"])
    if "base_url" in body:
        ep.base_url = str(body["base_url"])
    if "api_key" in body:
        ep.api_key = body["api_key"] or None
    if "endpoint_type" in body:
        try:
            ep.endpoint_type = EndpointType(body["endpoint_type"])
        except ValueError:
            return _json_error(400, "unknown endpoint_type")
    state.registry.update(ep)
    return web.json_response(endpoint_to_json(ep))


async def delete_endpoint(request: web.Request) -> web.Response:
    state = request.app["state"]
    endpoint_id = request.match_info["endpoint_id"]
    ep = state.registry.get(endpoint_id)
    if not state.registry.remove(endpoint_id):
        return _json_error(404, "endpoint not found")
    state.load_manager.clear_tps_for_endpoint(endpoint_id)
    state.load_manager.drop_endpoint_outcomes(endpoint_id)
    if state.resilience is not None:
        state.resilience.forget(endpoint_id,
                                endpoint_name=ep.name if ep else None)
    state.events.publish("EndpointRemoved", {"endpoint_id": endpoint_id})
    return web.json_response({"deleted": endpoint_id})


async def test_endpoint(request: web.Request) -> web.Response:
    """Connection test: probe + report (api/endpoints.rs run_connection_test)."""
    state = request.app["state"]
    ep = state.registry.get(request.match_info["endpoint_id"])
    if ep is None:
        return _json_error(404, "endpoint not found")
    start = time.monotonic()
    try:
        detected = await detect_endpoint_type(
            ep.base_url, state.http,
            timeout=state.config.health_check_timeout_s, api_key=ep.api_key,
        )
        return web.json_response({
            "ok": True,
            "detected_type": detected.value,
            "latency_ms": round((time.monotonic() - start) * 1000, 2),
        })
    except DetectionError as e:
        return web.json_response({
            "ok": False,
            "error": str(e),
            "latency_ms": round((time.monotonic() - start) * 1000, 2),
        })


async def sync_endpoint(request: web.Request) -> web.Response:
    state = request.app["state"]
    ep = state.registry.get(request.match_info["endpoint_id"])
    if ep is None:
        return _json_error(404, "endpoint not found")
    try:
        added, removed = await sync_endpoint_models(ep, state.registry, state.http)
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError, RuntimeError) as e:
        return _json_error(502, f"model sync failed: {e}")
    return web.json_response({
        "synced": True, "added": added, "removed": removed,
        "models": [m.model_id for m in state.registry.models_for(ep.id)],
    })


async def endpoint_health_history(request: web.Request) -> web.Response:
    state = request.app["state"]
    endpoint_id = request.match_info["endpoint_id"]
    rows = state.db.list_health_checks(endpoint_id, limit=200)
    return web.json_response({
        "checks": [
            {"ok": bool(r["ok"]), "latency_ms": r["latency_ms"],
             "error": r["error"], "checked_at": r["checked_at"]}
            for r in rows
        ]
    })


# -------------------------------------------------------------------- auth


async def login(request: web.Request) -> web.Response:
    state = request.app["state"]
    try:
        body = await request.json()
    except Exception:
        return _json_error(400, "invalid JSON body")
    user = state.users.authenticate(
        body.get("username") or "", body.get("password") or ""
    )
    if user is None:
        return _json_error(401, "invalid credentials")
    token = create_jwt(state.jwt_secret, user.id, user.username, user.role)
    resp = web.json_response({
        "token": token,
        "user": {
            "id": user.id, "username": user.username, "role": user.role.value,
            "must_change_password": user.must_change_password,
        },
    })
    # Cookie session for the dashboard SPA: HttpOnly JWT + a readable CSRF
    # token for the double-submit check (reference auth/middleware.rs:113-245).
    csrf = secrets.token_urlsafe(32)
    secure = request.headers.get("X-Forwarded-Proto", "").lower() == "https"
    resp.set_cookie(JWT_COOKIE, token, httponly=True, samesite="Lax",
                    secure=secure, max_age=24 * 3600, path="/")
    resp.set_cookie(CSRF_COOKIE, csrf, httponly=False, samesite="Lax",
                    secure=secure, max_age=24 * 3600, path="/")
    return resp


async def logout(request: web.Request) -> web.Response:
    resp = web.json_response({"ok": True})
    resp.del_cookie(JWT_COOKIE, path="/")
    resp.del_cookie(CSRF_COOKIE, path="/")
    return resp


async def me(request: web.Request) -> web.Response:
    auth = request.get("auth") or {}
    if not auth.get("user_id"):
        return _json_error(401, "not authenticated")
    state = request.app["state"]
    user = state.users.get(auth["user_id"])
    if user is None:
        return _json_error(404, "user not found")
    return web.json_response({
        "id": user.id, "username": user.username, "role": user.role.value,
        "must_change_password": user.must_change_password,
    })


async def change_password(request: web.Request) -> web.Response:
    state = request.app["state"]
    auth = request.get("auth") or {}
    if not auth.get("user_id"):
        return _json_error(401, "not authenticated")
    try:
        body = await request.json()
    except Exception:
        return _json_error(400, "invalid JSON body")
    user = state.users.get(auth["user_id"])
    if user is None or not state.users.authenticate(
        user.username, body.get("current_password") or ""
    ):
        return _json_error(401, "current password incorrect")
    try:
        state.users.change_password(user.id, body.get("new_password") or "")
    except AuthError as e:
        return _json_error(400, str(e))
    return web.json_response({"changed": True})


async def register_with_invitation(request: web.Request) -> web.Response:
    state = request.app["state"]
    try:
        body = await request.json()
    except Exception:
        return _json_error(400, "invalid JSON body")
    try:
        user = state.invitations.redeem(
            body.get("code") or "", body.get("username") or "",
            body.get("password") or "", state.users,
        )
    except AuthError as e:
        return _json_error(400, str(e))
    token = create_jwt(state.jwt_secret, user.id, user.username, user.role)
    return web.json_response({"token": token, "user": {
        "id": user.id, "username": user.username, "role": user.role.value,
    }}, status=201)


# -------------------------------------------------------------------- users


async def list_users(request: web.Request) -> web.Response:
    state = request.app["state"]
    return web.json_response({"users": [
        {"id": u.id, "username": u.username, "role": u.role.value,
         "must_change_password": u.must_change_password,
         "created_at": u.created_at}
        for u in state.users.list()
    ]})


async def create_user(request: web.Request) -> web.Response:
    state = request.app["state"]
    try:
        body = await request.json()
    except Exception:
        return _json_error(400, "invalid JSON body")
    try:
        role = Role(body.get("role", "viewer"))
        user = state.users.create(
            body.get("username") or "", body.get("password") or "", role
        )
    except (AuthError, ValueError) as e:
        return _json_error(400, str(e))
    return web.json_response(
        {"id": user.id, "username": user.username, "role": user.role.value},
        status=201,
    )


async def delete_user(request: web.Request) -> web.Response:
    state = request.app["state"]
    auth = request.get("auth") or {}
    user_id = request.match_info["user_id"]
    if auth.get("user_id") == user_id:
        return _json_error(400, "cannot delete your own account")
    if not state.users.delete(user_id):
        return _json_error(404, "user not found")
    return web.json_response({"deleted": user_id})


async def set_user_role(request: web.Request) -> web.Response:
    state = request.app["state"]
    try:
        body = await request.json()
        role = Role(body.get("role"))
    except Exception:
        return _json_error(400, "invalid role")
    user_id = request.match_info["user_id"]
    if state.users.get(user_id) is None:
        return _json_error(404, "user not found")
    state.users.set_role(user_id, role)
    return web.json_response({"id": user_id, "role": role.value})


# ----------------------------------------------------------------- api keys


async def list_api_keys(request: web.Request) -> web.Response:
    state = request.app["state"]
    auth = request.get("auth") or {}
    keys = state.api_keys.list(
        None if auth.get("role") == "admin" else auth.get("user_id")
    )
    return web.json_response({"api_keys": [
        {"id": k.id, "name": k.name, "key_prefix": k.key_prefix,
         "permissions": [p.value for p in k.permissions],
         "created_at": k.created_at, "revoked": k.revoked,
         "last_used_at": k.last_used_at, "expires_at": k.expires_at}
        for k in keys
    ]})


async def create_api_key(request: web.Request) -> web.Response:
    state = request.app["state"]
    auth = request.get("auth") or {}
    try:
        body = await request.json()
    except Exception:
        return _json_error(400, "invalid JSON body")
    perms = []
    for p in body.get("permissions") or []:
        try:
            perms.append(Permission(p))
        except ValueError:
            return _json_error(400, f"unknown permission {p!r}")
    if not perms:
        perms = [Permission.OPENAI_INFERENCE, Permission.OPENAI_MODELS_READ]
    record, raw = state.api_keys.create(
        auth.get("user_id") or "", body.get("name") or "unnamed", perms,
        expires_at=body.get("expires_at"),
    )
    return web.json_response({
        "id": record.id, "name": record.name, "api_key": raw,
        "permissions": [p.value for p in record.permissions],
    }, status=201)


async def revoke_api_key(request: web.Request) -> web.Response:
    state = request.app["state"]
    if not state.api_keys.revoke(request.match_info["key_id"]):
        return _json_error(404, "api key not found")
    return web.json_response({"revoked": request.match_info["key_id"]})


# -------------------------------------------------------------- invitations


async def list_invitations(request: web.Request) -> web.Response:
    state = request.app["state"]
    return web.json_response({"invitations": state.invitations.list()})


async def create_invitation(request: web.Request) -> web.Response:
    state = request.app["state"]
    auth = request.get("auth") or {}
    try:
        body = await request.json() if request.can_read_body else {}
    except Exception:
        body = {}
    try:
        role = Role(body.get("role", "viewer"))
    except ValueError:
        return _json_error(400, "invalid role")
    inv = state.invitations.create(auth.get("user_id") or "", role)
    return web.json_response(inv, status=201)


async def delete_invitation(request: web.Request) -> web.Response:
    state = request.app["state"]
    if not state.invitations.delete(request.match_info["invitation_id"]):
        return _json_error(404, "invitation not found")
    return web.json_response({"deleted": request.match_info["invitation_id"]})


# -------------------------------------------------------------------- audit


async def query_audit_log(request: web.Request) -> web.Response:
    state = request.app["state"]
    q = request.query
    entries = state.audit.search(
        q=q.get("q"), actor=q.get("actor"), path_prefix=q.get("path"),
        since=float(q["since"]) if "since" in q else None,
        until=float(q["until"]) if "until" in q else None,
        limit=min(int(q.get("limit", 100)), 1000),
        offset=int(q.get("offset", 0)),
    )
    return web.json_response({"entries": entries})


async def verify_audit_chain(request: web.Request) -> web.Response:
    state = request.app["state"]
    state.audit.flush()
    ok, err = state.audit.verify()
    return web.json_response({"ok": ok, "error": err})


# ----------------------------------------------------------------- settings


async def get_settings(request: web.Request) -> web.Response:
    state = request.app["state"]
    settings = {
        k: v for k, v in state.db.list_settings().items()
        if not k.startswith("auth.")  # never expose secrets
    }
    return web.json_response({"settings": settings})


async def update_setting(request: web.Request) -> web.Response:
    state = request.app["state"]
    try:
        body = await request.json()
        key, value = str(body["key"]), str(body["value"])
    except Exception:
        return _json_error(400, "body must have 'key' and 'value'")
    if key.startswith("auth."):
        return _json_error(400, "auth.* settings are not writable via API")
    if key == "ip_alert_threshold":
        from llmlb_tpu.gateway.api_dashboard import parse_ip_alert_threshold

        try:
            parse_ip_alert_threshold(value)
        except ValueError:
            return _json_error(400, "ip_alert_threshold must be an integer >= 1")
    state.db.set_setting(key, value)
    return web.json_response({"key": key, "value": value})


# ------------------------------------------------------------------- system


async def system_info(request: web.Request) -> web.Response:
    state = request.app["state"]
    update = None
    if state.update_manager is not None:
        update = state.update_manager.status()
    return web.json_response({
        "name": "llmlb_tpu",
        "version": __version__,
        "uptime_s": round(time.time() - state.started_at, 1),
        "update": update,
        "gate": {
            "rejecting": state.gate.rejecting,
            "in_flight": state.gate.in_flight,
        },
    })


async def tray_status(request: web.Request) -> web.Response:
    """Tray menu + notifications (headless backends expose them here since
    there is no desktop shell to draw in — gui/tray.rs equivalent surface)."""
    state = request.app["state"]
    if state.tray is None:
        return web.json_response({"enabled": False})
    return web.json_response({"enabled": True, **state.tray.status()})


async def tray_activate(request: web.Request) -> web.Response:
    """Dispatch a tray menu click (the reference's tray→update-manager proxy,
    reachable over HTTP because the backend is headless)."""
    state = request.app["state"]
    if state.tray is None:
        return _json_error(404, "tray is not enabled (set LLMLB_TRAY=1)")
    try:
        body = await request.json()
        item = str(body["item"])
    except Exception:
        return _json_error(400, "body must have 'item'")
    result = await state.tray.activate(item)
    return web.json_response(result, status=200 if result.get("ok") else 400)
