"""Anthropic Messages API adapter: /v1/messages backed by OpenAI endpoints.

Parity with reference api/anthropic.rs: `anthropic:`-prefixed models pass
through to the cloud natively (:137); local models are served by converting the
Anthropic request to OpenAI chat (:1048, tools/tool_choice :1218-1321),
proxying through the normal TPS selection path, then converting back — either
as a full message response (:1435, stop_reason mapping :1526) or as a stateful
SSE re-encoding of OpenAI chunks into the Anthropic event stream
(message_start/content_block_*/message_delta/message_stop incl. tool_use,
:728-1046).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
import uuid

import aiohttp
from aiohttp import web

from llmlb_tpu.gateway.api_openai import (
    HandoffOrchestrationError,
    QueueTimeout,
    StreamWriteTimeout,
    _acquire_resume,
    _chat_prompt_text,
    _handoff_upstream,
    _record,
    affinity_text_from_body,
    deadline_at_of,
    error_response,
    priority_label,
    ratelimit_verdict,
    select_endpoint_with_queue,
    stream_write_guard,
    tenant_of,
)
from llmlb_tpu.gateway.replay import (
    REPLAY_OBJECT,
    RESUMABLE_ENDPOINT_TYPES,
    ChunkSplicer,
    ReplayState,
)
from llmlb_tpu.gateway.balancer import prefix_affinity_hash
from llmlb_tpu.gateway.resilience import (
    RETRYABLE_EXCEPTIONS,
    FailoverController,
    PreStreamFailure,
    book_stream_outcome,
    retry_after_seconds,
    upstream_post,
)
from llmlb_tpu.gateway.model_names import to_canonical
from llmlb_tpu.gateway.token_accounting import estimate_tokens
from llmlb_tpu.gateway.tracing import (
    REQUEST_ID_HEADER,
    TokenTimeline,
    observe_first_token,
)
from llmlb_tpu.gateway.types import Capability, TpsApiKind
from llmlb_tpu.structured import inspect_request as inspect_structured

ANTHROPIC_BASE = os.environ.get(
    "LLMLB_ANTHROPIC_BASE_URL", "https://api.anthropic.com"
)

STOP_REASON_MAP = {
    "stop": "end_turn",
    "length": "max_tokens",
    "tool_calls": "tool_use",
    "content_filter": "end_turn",
}


def _anthropic_error(status: int, message: str,
                     err_type: str = "invalid_request_error",
                     headers: dict | None = None) -> web.Response:
    return web.json_response(
        {"type": "error", "error": {"type": err_type, "message": message}},
        status=status,
        headers=headers,
    )


def anthropic_error_event(message: str,
                          err_type: str = "api_error") -> bytes:
    """Anthropic's native SSE error event (the real API emits exactly this
    shape mid-stream), written before closing a cut stream so clients can
    tell truncation from completion."""
    payload = {"type": "error", "error": {"type": err_type,
                                          "message": message}}
    return (
        f"event: error\ndata: {json.dumps(payload, separators=(',', ':'))}\n\n"
    ).encode()


# ------------------------------------------------- request/response convert


def anthropic_request_to_openai(body: dict) -> dict:
    """Anthropic /v1/messages body → OpenAI chat body (anthropic.rs:1048)."""
    messages: list[dict] = []
    system = body.get("system")
    if system:
        if isinstance(system, list):  # content-block system prompts
            system = "".join(
                b.get("text", "") for b in system if isinstance(b, dict)
            )
        messages.append({"role": "system", "content": system})

    for m in body.get("messages") or []:
        role = m.get("role")
        content = m.get("content")
        if isinstance(content, str):
            messages.append({"role": role, "content": content})
            continue
        # content-block array: text, tool_use (assistant), tool_result (user)
        text_parts: list[str] = []
        tool_calls: list[dict] = []
        for block in content or []:
            if not isinstance(block, dict):
                continue
            btype = block.get("type")
            if btype == "text":
                text_parts.append(block.get("text", ""))
            elif btype == "tool_use":
                tool_calls.append({
                    "id": block.get("id") or f"call_{uuid.uuid4().hex[:12]}",
                    "type": "function",
                    "function": {
                        "name": block.get("name", ""),
                        "arguments": json.dumps(block.get("input") or {}),
                    },
                })
            elif btype == "tool_result":
                tool_content = block.get("content")
                if isinstance(tool_content, list):
                    tool_content = "".join(
                        b.get("text", "") for b in tool_content
                        if isinstance(b, dict)
                    )
                messages.append({
                    "role": "tool",
                    "tool_call_id": block.get("tool_use_id", ""),
                    "content": tool_content or "",
                })
        if text_parts or tool_calls:
            msg: dict = {"role": role, "content": "".join(text_parts) or None}
            if tool_calls:
                msg["tool_calls"] = tool_calls
            messages.append(msg)

    out: dict = {
        "model": body.get("model"),
        "messages": messages,
        "max_tokens": body.get("max_tokens", 1024),
    }
    for src, dst in (("temperature", "temperature"), ("top_p", "top_p"),
                     ("stream", "stream"),
                     # speculative-decoding knobs ({enabled,
                     # max_draft_tokens}) ride both dialects verbatim — the
                     # engine validates and clamps them
                     ("speculative", "speculative"),
                     # priority class (docs/scheduling.md): high/normal/low
                     # or 0..2, carried verbatim — the engine validates
                     ("priority", "priority"),
                     # LoRA adapter name (docs/lora.md): carried verbatim —
                     # the shared validator (llmlb_tpu/lora/api.py) runs at
                     # the gateway's inspect step and again at the engine
                     ("lora", "lora")):
        if body.get(src) is not None:
            out[dst] = body[src]
    if body.get("stop_sequences"):
        out["stop"] = body["stop_sequences"]
    if body.get("tools"):
        out["tools"] = [
            {
                "type": "function",
                "function": {
                    "name": t.get("name"),
                    "description": t.get("description", ""),
                    "parameters": t.get("input_schema") or {},
                },
            }
            for t in body["tools"]
            if isinstance(t, dict)
        ]
    choice = body.get("tool_choice")
    if isinstance(choice, dict):
        ctype = choice.get("type")
        if ctype == "auto":
            out["tool_choice"] = "auto"
        elif ctype == "any":
            out["tool_choice"] = "required"
        elif ctype == "tool":
            out["tool_choice"] = {
                "type": "function",
                "function": {"name": choice.get("name", "")},
            }
    return out


def openai_response_to_anthropic(resp: dict, model: str) -> dict:
    """OpenAI chat response → Anthropic message response (anthropic.rs:1435)."""
    content: list[dict] = []
    finish = "stop"
    choices = resp.get("choices") or []
    if choices:
        choice = choices[0]
        finish = choice.get("finish_reason") or "stop"
        msg = choice.get("message") or {}
        if isinstance(msg.get("content"), str) and msg["content"]:
            content.append({"type": "text", "text": msg["content"]})
        for tc in msg.get("tool_calls") or []:
            fn = tc.get("function") or {}
            try:
                args = json.loads(fn.get("arguments") or "{}")
            except ValueError:
                args = {}
            content.append({
                "type": "tool_use",
                "id": tc.get("id") or f"toolu_{uuid.uuid4().hex[:12]}",
                "name": fn.get("name", ""),
                "input": args,
            })
    usage = resp.get("usage") or {}
    return {
        "id": f"msg_{uuid.uuid4().hex[:24]}",
        "type": "message",
        "role": "assistant",
        "model": model,
        "content": content,
        "stop_reason": STOP_REASON_MAP.get(finish, "end_turn"),
        "stop_sequence": None,
        "usage": {
            "input_tokens": usage.get("prompt_tokens", 0),
            "output_tokens": usage.get("completion_tokens", 0),
        },
    }


class AnthropicStreamEncoder:
    """Re-encodes OpenAI chat chunks as Anthropic SSE events (anthropic.rs:728).

    Stateful: text deltas stream through an open text block; tool-call deltas
    are buffered per OpenAI tool index (OpenAI may interleave fragments of
    parallel tool calls, which cannot map onto Anthropic's sequential block
    stream) and emitted as complete tool_use blocks at finish. message_start
    carries an input-token estimate (the upstream only reports usage at stream
    end); message_delta carries the reported figures.
    """

    def __init__(self, model: str, input_token_estimate: int = 0):
        self.model = model
        self.message_id = f"msg_{uuid.uuid4().hex[:24]}"
        self.started = False
        self.block_index = -1
        self.block_type: str | None = None  # "text" (tool_use emitted at end)
        self.finish_reason: str | None = None
        self.input_token_estimate = input_token_estimate
        self.usage = {"input_tokens": 0, "output_tokens": 0}
        self._usage_reported = False
        # OpenAI tool index -> {"id", "name", "args": [fragments]}
        self._tools: dict[int, dict] = {}

    @staticmethod
    def _event(name: str, payload: dict) -> bytes:
        return (
            f"event: {name}\ndata: "
            f"{json.dumps(payload, separators=(',', ':'))}\n\n"
        ).encode()

    def start(self) -> bytes:
        self.started = True
        return self._event("message_start", {
            "type": "message_start",
            "message": {
                "id": self.message_id, "type": "message", "role": "assistant",
                "model": self.model, "content": [],
                "stop_reason": None, "stop_sequence": None,
                "usage": {"input_tokens": self.input_token_estimate,
                          "output_tokens": 0},
            },
        })

    def _close_block(self) -> list[bytes]:
        if self.block_type is None:
            return []
        out = [self._event("content_block_stop", {
            "type": "content_block_stop", "index": self.block_index,
        })]
        self.block_type = None
        return out

    def _open_block(self, btype: str, header: dict) -> list[bytes]:
        out = self._close_block()
        self.block_index += 1
        self.block_type = btype
        out.append(self._event("content_block_start", {
            "type": "content_block_start", "index": self.block_index,
            "content_block": header,
        }))
        return out

    def feed(self, chunk: dict) -> list[bytes]:
        """Consume one OpenAI chunk dict; returns encoded Anthropic events."""
        out: list[bytes] = []
        if not self.started:
            out.append(self.start())
        usage = chunk.get("usage")
        if isinstance(usage, dict):
            self.usage = {
                "input_tokens": usage.get("prompt_tokens", 0),
                "output_tokens": usage.get("completion_tokens", 0),
            }
            self._usage_reported = True
        for choice in chunk.get("choices") or []:
            if not isinstance(choice, dict):
                continue
            if choice.get("finish_reason"):
                self.finish_reason = choice["finish_reason"]
            delta = choice.get("delta") or {}
            content = delta.get("content")
            if isinstance(content, str) and content:
                if self.block_type != "text":
                    out.extend(self._open_block(
                        "text", {"type": "text", "text": ""}
                    ))
                out.append(self._event("content_block_delta", {
                    "type": "content_block_delta", "index": self.block_index,
                    "delta": {"type": "text_delta", "text": content},
                }))
            for tc in delta.get("tool_calls") or []:
                idx = tc.get("index", 0)
                fn = tc.get("function") or {}
                tool = self._tools.setdefault(
                    idx, {"id": None, "name": "", "args": []}
                )
                if tc.get("id"):
                    tool["id"] = tc["id"]
                if fn.get("name"):
                    tool["name"] = fn["name"]
                if fn.get("arguments"):
                    tool["args"].append(fn["arguments"])
        return out

    def finish(self) -> list[bytes]:
        out = self._close_block()
        for idx in sorted(self._tools):
            tool = self._tools[idx]
            self.block_index += 1
            out.append(self._event("content_block_start", {
                "type": "content_block_start", "index": self.block_index,
                "content_block": {
                    "type": "tool_use",
                    "id": tool["id"] or f"toolu_{uuid.uuid4().hex[:12]}",
                    "name": tool["name"], "input": {},
                },
            }))
            args = "".join(tool["args"])
            if args:
                out.append(self._event("content_block_delta", {
                    "type": "content_block_delta", "index": self.block_index,
                    "delta": {"type": "input_json_delta", "partial_json": args},
                }))
            out.append(self._event("content_block_stop", {
                "type": "content_block_stop", "index": self.block_index,
            }))
        usage = {"output_tokens": self.usage["output_tokens"]}
        if self._usage_reported:
            usage["input_tokens"] = self.usage["input_tokens"]
        out.append(self._event("message_delta", {
            "type": "message_delta",
            "delta": {
                "stop_reason": STOP_REASON_MAP.get(
                    self.finish_reason or "stop", "end_turn"
                ),
                "stop_sequence": None,
            },
            "usage": usage,
        }))
        out.append(self._event("message_stop", {"type": "message_stop"}))
        return out


# ------------------------------------------------------------------ handler


async def messages(request: web.Request) -> web.StreamResponse:
    state = request.app["state"]
    started = time.monotonic()
    trace = request.get("trace")
    if trace is not None:
        trace.end("auth")
    try:
        body = await request.json()
    except Exception:
        return _anthropic_error(400, "invalid JSON body")
    model = body.get("model")
    if not model or not isinstance(model, str):
        return _anthropic_error(400, "'model' is required")
    if not body.get("messages"):
        return _anthropic_error(400, "'messages' is required")
    if body.get("max_tokens") is None:
        return _anthropic_error(400, "'max_tokens' is required")

    if model.startswith("anthropic:"):
        return await _cloud_passthrough(request, state, body,
                                        model[len("anthropic:"):])

    canonical = to_canonical(model)
    if trace is not None:
        trace.model = canonical
    openai_body = anthropic_request_to_openai(body)
    # Forced tool_choice ({type: "tool"} → forced function call after the
    # OpenAI conversion above) is grammar-constrained exactly like the
    # OpenAI dialect: validate it here (400 in the Anthropic error shape,
    # unsupported schema feature named) and steer to structured-capable
    # endpoints when the model has any.
    capability = Capability.CHAT_COMPLETION
    try:
        structured = inspect_structured(openai_body)
    except ValueError as e:
        state.metrics.record_structured_rejected()
        return _anthropic_error(400, str(e))
    if structured is not None:
        state.metrics.record_structured_request(structured.kind)
        if state.registry.find_by_model(
            canonical, Capability.STRUCTURED_OUTPUTS
        ):
            capability = Capability.STRUCTURED_OUTPUTS
    # Multi-LoRA routing (docs/lora.md) — same resolution and 400 shape
    # contract as proxy_openai_post, refusals in the Anthropic error shape.
    from llmlb_tpu.lora.gateway import lora_route_for

    try:
        lora_route = lora_route_for(state, openai_body)
    except ValueError as e:
        state.metrics.record_lora_route("rejected")
        return _anthropic_error(400, str(e))
    if lora_route is not None:
        canonical = lora_route.canonical
        state.metrics.record_lora_route(lora_route.kind)
        if lora_route.capability is not None:
            capability = lora_route.capability
    prefix_hash = prefix_affinity_hash(
        lora_route.base_canonical if lora_route is not None else canonical,
        affinity_text_from_body(body),
        lora=lora_route.adapter if lora_route is not None else None,
    )
    is_stream = bool(body.get("stream"))
    if is_stream:
        openai_body["stream"] = True
        openai_body["stream_options"] = {"include_usage": True}

    # Overload protection (docs/scheduling.md): same pipeline as
    # proxy_openai_post — per-key token buckets, request deadline, WFQ
    # tenant — with refusals in the Anthropic error shape.
    try:
        deadline_at = deadline_at_of(request, state, started)
    except ValueError as e:
        return _anthropic_error(400, str(e))
    tenant, tenant_name = tenant_of(request)
    refused = ratelimit_verdict(
        state, request, estimate_tokens(_chat_prompt_text(openai_body))
    )
    if refused is not None:
        reason, retry_after = refused
        return _anthropic_error(
            429,
            f"rate limit exceeded ({reason}); retry after {retry_after}s",
            "rate_limit_error",
            headers={"Retry-After": str(retry_after)},
        )
    wfq_weight = state.admission.weight_for(tenant_name)
    prio = priority_label(body)

    # Disaggregation role steering — same policy as proxy_openai_post
    # (docs/disaggregation.md): long cold-prefix prompts prefer
    # prefill-capable endpoints, everything else avoids prefill-only ones.
    from llmlb_tpu.disagg.gateway import endpoint_role, is_prefill_heavy

    prefill_heavy = is_prefill_heavy(
        state, canonical,
        estimate_tokens(_chat_prompt_text(openai_body)), prefix_hash,
    )

    # Same failover loop as proxy_openai_post: re-select excluding failed
    # endpoints, retry under the attempt cap + global budget; streams fail
    # over only before the first Anthropic event reaches the client.
    fo = FailoverController(
        state, canonical, trace=trace,
        candidates_fn=lambda: [
            ep for ep, _ in state.registry.find_by_model(canonical, capability)
        ],
    )
    while True:
        queue_timeout = (fo.config.failover_queue_timeout_s
                         if fo.failed_ids else None)
        if deadline_at is not None:
            remaining = deadline_at - time.monotonic()
            if remaining <= 0:
                state.metrics.record_deadline_shed(canonical)
                return _anthropic_error(
                    504, "request deadline exceeded before an endpoint was "
                    "available", "timeout_error",
                )
            cap = (queue_timeout if queue_timeout is not None
                   else state.load_manager.queue_config.queue_timeout_s)
            queue_timeout = min(cap, remaining)
        try:
            selection = await select_endpoint_with_queue(
                state, canonical, capability, TpsApiKind.CHAT,
                trace=trace, prefix_hash=prefix_hash, exclude=fo.failed_ids,
                queue_timeout_s=queue_timeout,
                tenant=tenant, weight=wfq_weight,
                prefill_heavy=prefill_heavy,
            )
        except QueueTimeout:
            if deadline_at is not None and time.monotonic() >= deadline_at:
                state.metrics.record_deadline_shed(canonical)
                return _anthropic_error(
                    504, "request deadline exceeded while queued",
                    "timeout_error",
                )
            return _anthropic_error(
                503, "all endpoints busy", "overloaded_error",
                headers={"Retry-After": str(retry_after_seconds(
                    state, canonical, capability
                ))},
            )
        if selection is None:
            return _anthropic_error(
                404, f"model {model!r} is not available", "not_found_error"
            )
        endpoint, engine_model, lease, chosen_model = selection
        openai_body["model"] = engine_model
        if lora_route is not None:
            from llmlb_tpu.lora.gateway import forward_model_name

            openai_body["model"] = forward_model_name(
                lora_route, engine_model, lora_route.base_canonical
            )
            openai_body["lora"] = lora_route.adapter

        # Durable streams (gateway/replay.py): arm tpu:// engine streams so
        # a mid-stream engine death resumes token-identically elsewhere and
        # splices into the SAME Anthropic event stream (no second
        # message_start, exactly one message_stop).
        arm_replay = (
            is_stream
            and state.config.stream_resume
            and state.config.stream_resume_attempts > 0
            and endpoint.endpoint_type.value in RESUMABLE_ENDPOINT_TYPES
        )
        if arm_replay:
            openai_body["llmlb_replay"] = True
        else:
            openai_body.pop("llmlb_replay", None)

        headers = {"Content-Type": "application/json"}
        if endpoint.api_key:
            headers["Authorization"] = f"Bearer {endpoint.api_key}"
        rid = request.get("request_id")
        if rid:
            headers[REQUEST_ID_HEADER] = rid
        if deadline_at is not None:
            remaining_ms = (deadline_at - time.monotonic()) * 1000.0
            if remaining_ms <= 0:
                lease.fail()
                state.metrics.record_deadline_shed(canonical)
                return _anthropic_error(
                    504, "request deadline exceeded before forwarding",
                    "timeout_error",
                )
            headers["X-Request-Deadline-Ms"] = str(max(1, int(remaining_ms)))
        if trace is not None:
            trace.begin("proxy")
        try:
            if endpoint_role(endpoint, chosen_model) == "prefill":
                # two-phase disaggregated handoff: prefill here, adopt on a
                # decode-capable endpoint; the returned upstream is a normal
                # chat-completions response/SSE, so the Anthropic transform
                # below consumes it unchanged (docs/disaggregation.md)
                upstream, endpoint, lease, _adopt_model = (
                    await _handoff_upstream(
                        state, fo, endpoint, lease, canonical, capability,
                        TpsApiKind.CHAT, openai_body, headers, deadline_at,
                        is_stream, engine_model,
                    )
                )
            else:
                upstream = await upstream_post(
                    state, endpoint, "/v1/chat/completions",
                    json=openai_body,
                    headers=headers,
                    timeout=aiohttp.ClientTimeout(
                        total=state.config.inference_timeout_s
                    ),
                )
        except HandoffOrchestrationError as e:
            fo.record_failure(e.endpoint, e.lease, e.reason)
            if trace is not None:
                trace.end("proxy")
            if await fo.should_retry(e.reason):
                continue
            return _anthropic_error(
                502, f"handoff adoption failed: {e.reason}", "api_error"
            )
        except RETRYABLE_EXCEPTIONS as e:
            reason = ("timeout" if isinstance(e, asyncio.TimeoutError)
                      else "connect_error")
            fo.record_failure(endpoint, lease, reason)
            if trace is not None:
                trace.end("proxy")
            if await fo.should_retry(reason):
                continue
            return _anthropic_error(
                502, f"upstream unreachable: {type(e).__name__}", "api_error"
            )

        if upstream.status != 200:
            status_code = upstream.status
            try:
                detail = (await upstream.read())[:1024].decode(errors="replace")
            except RETRYABLE_EXCEPTIONS:
                detail = "<error body unreadable>"
            upstream.release()
            if trace is not None:
                trace.end("proxy")
            if status_code in fo.config.retryable_statuses:
                reason = f"http_{status_code}"
                fo.record_failure(endpoint, lease, reason)
                if await fo.should_retry(reason):
                    continue
            else:
                # non-retryable 4xx: not endpoint sickness, but liveness
                # evidence — resolves a half-open probe
                lease.fail()
                fo.record_alive(endpoint)
            _record(state, endpoint=endpoint, model=canonical,
                    api_kind=TpsApiKind.CHAT, path="/v1/messages", status=502,
                    started=started, client_ip=request.remote,
                    auth=request.get("auth"), error=detail)
            return _anthropic_error(
                502, f"upstream returned {status_code}: {detail}", "api_error"
            )

        if is_stream:
            replay = None
            if arm_replay:
                replay = ReplayState(
                    openai_body, capability=capability,
                    api_kind=TpsApiKind.CHAT, tenant=tenant,
                    weight=wfq_weight, deadline_at=deadline_at, rid=rid,
                    prefix_hash=prefix_hash,
                    max_attempts=state.config.stream_resume_attempts,
                )
            result = await _stream_transform(
                request, state, upstream, endpoint, canonical, started, lease,
                body, openai_body, trace=trace, failover=fo, priority=prio,
                replay=replay,
            )
            if isinstance(result, PreStreamFailure):
                fo.record_failure(endpoint, lease, "stream_pre_byte")
                if trace is not None:
                    trace.end("proxy")
                if await fo.should_retry("stream_pre_byte"):
                    continue
                return _anthropic_error(
                    502,
                    f"upstream stream failed before first byte: "
                    f"{result.error}",
                    "api_error",
                )
            return result

        observe_first_token(state, trace, canonical, endpoint.name, started)
        try:
            raw = await upstream.read()
        except RETRYABLE_EXCEPTIONS as e:
            # endpoint died mid-body: invisible to the client, fails over
            upstream.release()
            fo.record_failure(endpoint, lease, "read_error")
            if trace is not None:
                trace.end("proxy")
            if await fo.should_retry("read_error"):
                continue
            return _anthropic_error(
                502, f"upstream response read failed: {type(e).__name__}",
                "api_error",
            )
        upstream.release()
        if trace is not None:
            trace.end("proxy")
        try:
            openai_resp = json.loads(raw)
        except ValueError:
            # the endpoint answered (malformed): alive, but not a success
            lease.fail()
            fo.record_alive(endpoint)
            return _anthropic_error(
                502, "invalid upstream response", "api_error"
            )
        anthropic_resp = openai_response_to_anthropic(openai_resp, model)
        usage = anthropic_resp["usage"]
        lease.complete_with_tokens(usage["input_tokens"],
                                   usage["output_tokens"])
        fo.record_success(endpoint)
        # non-streaming goodput: only the TTFT target applies
        state.metrics.record_slo(canonical, time.monotonic() - started, None,
                                 priority=prio)
        _record(state, endpoint=endpoint, model=canonical,
                api_kind=TpsApiKind.CHAT, path="/v1/messages", status=200,
                started=started,
                prompt_tokens=usage["input_tokens"],
                completion_tokens=usage["output_tokens"],
                client_ip=request.remote, auth=request.get("auth"))
        return web.json_response(anthropic_resp)


async def _stream_transform(
    request, state, upstream, endpoint, model, started, lease,
    original_body, openai_body, trace=None, failover=None,
    priority: str = "normal", replay: ReplayState | None = None,
) -> "web.StreamResponse | PreStreamFailure":
    # First upstream chunk is pulled BEFORE the client response is prepared:
    # a failure there is invisible to the client and fails over.
    iterator = upstream.content.iter_any()
    first_chunk = None
    try:
        first_chunk = await iterator.__anext__()
    except StopAsyncIteration:
        first_chunk = None
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
            ConnectionResetError) as e:
        upstream.release()
        return PreStreamFailure(f"{type(e).__name__}: {e}")

    headers = {"Content-Type": "text/event-stream"}
    rid = request.get("request_id")
    if rid:
        headers[REQUEST_ID_HEADER] = rid
    resp = web.StreamResponse(status=200, headers=headers)
    await resp.prepare(request)
    lease.complete()
    # Estimate from the flattened OpenAI conversion: it folds system prompts
    # and content-block (tool) messages into plain strings, which the raw
    # Anthropic body does not.
    prompt_text = "\n".join(
        m.get("content") for m in openai_body.get("messages", [])
        if isinstance(m, dict) and isinstance(m.get("content"), str)
    )
    encoder = AnthropicStreamEncoder(
        original_body.get("model", model),
        input_token_estimate=estimate_tokens(prompt_text),
    )
    buffer = b""
    status = 200
    error = None
    upstream_failed = False
    # durable streams: a cut booked in-line (victim charged at the moment of
    # the cut) must not be booked again by the finally block
    outcome_booked = False
    splicer: ChunkSplicer | None = None  # active after the first resume
    # set when the upstream's [DONE] has been consumed: a transport reset
    # arriving AFTER a complete stream is not a cut (same guard as the
    # OpenAI armed pump's terminal_sent)
    upstream_done = False
    # Sampled token timeline + SLO inputs, same contract as the OpenAI
    # passthrough (_forward_stream): one mark per upstream data chunk that
    # produced client-visible events.
    timeline = (TokenTimeline()
                if trace is not None and state.traces.sample_timeline()
                else None)
    ttft_s: float | None = None

    # Hot loop locals: the dialect transform must JSON-parse each frame (it
    # rewrites OpenAI chunks into Anthropic events, unlike the byte-for-byte
    # OpenAI passthrough), but the line splitter and writer should not pay
    # attribute walks per line on top of that.
    loads = json.loads
    encoder_feed = encoder.feed

    # Slow-loris protection: the shared per-stream watchdog guard
    # (api_openai.StreamWriteGuard) — a non-draining client aborts the
    # pump instead of pinning the slot; no per-chunk wait_for.
    guard = stream_write_guard(state, resp, endpoint, "/v1/messages")
    resp_write = guard.write if guard.active() else resp.write

    async def pump(raw_chunk: bytes) -> None:
        nonlocal buffer, upstream_done
        buffer += raw_chunk
        wrote = False
        while b"\n" in buffer:
            line, buffer = buffer.split(b"\n", 1)
            line = line.strip()
            if not line.startswith(b"data:"):
                continue
            data = line[len(b"data:"):].strip()
            if not data:
                continue
            if data == b"[DONE]":
                upstream_done = True
                continue
            try:
                chunk = loads(data)
            except ValueError:
                continue
            if replay is not None:
                if splicer is None:
                    # primary segment: account committed ids + chars fed to
                    # the encoder; gateway-internal replay frames never feed
                    if not replay.note_openai_chunk(chunk):
                        continue
                else:
                    # resumed segment: the adopter re-emits the full text —
                    # splice off what the encoder already consumed
                    if chunk.get("object") == REPLAY_OBJECT:
                        replay.note_openai_chunk(chunk)
                        continue
                    chunk = splicer.splice(chunk)
                    if chunk is None:
                        continue
            for event in encoder_feed(chunk):
                await resp_write(event)
                wrote = True
        if wrote and timeline is not None:
            timeline.mark()

    try:
        if first_chunk is not None:
            observe_first_token(state, trace, model, endpoint.name,
                                started, streaming=True)
            ttft_s = time.monotonic() - started
            await pump(first_chunk)
            next_chunk = iterator.__anext__
            while True:
                try:
                    raw_chunk = await next_chunk()
                except StopAsyncIteration:
                    break
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError) as e:
                    if upstream_done:
                        break  # the stream already completed cleanly
                    if replay is not None and failover is not None:
                        # book the victim exactly once (breaker + one
                        # stream_interruption; also excludes it from the
                        # re-selection) and splice a token-identical
                        # continuation into THIS event stream — the open
                        # encoder keeps its state, so there is no second
                        # message_start and exactly one message_stop
                        failover.record_failure(
                            endpoint, None, "stream_interrupted",
                            stream_interrupted=True,
                        )
                        resumed = await _acquire_resume(
                            state, failover, replay, model, trace=trace,
                        )
                        if resumed is not None:
                            upstream.release()
                            upstream, endpoint, iterator, raw_chunk = resumed
                            next_chunk = iterator.__anext__
                            buffer = b""  # drop the dead stream's partials
                            splicer = ChunkSplicer(replay)
                            replay.mark_ledger_stale()
                            await pump(raw_chunk)
                            continue
                        outcome_booked = True  # victim booked above
                        status = 502
                        error = f"stream interrupted: {type(e).__name__}"
                        await resp_write(anthropic_error_event(error))
                        break
                    # mid-stream upstream cut: native Anthropic error event,
                    # then count it against the endpoint
                    status = 502
                    error = f"stream interrupted: {type(e).__name__}"
                    upstream_failed = True
                    # guarded: a stalled client must not pin the handler on
                    # the farewell frame either
                    await resp_write(anthropic_error_event(error))
                    break
                await pump(raw_chunk)
        if status == 200:
            for event in encoder.finish():
                await resp_write(event)
    except asyncio.CancelledError:
        # watchdog cancel landing at a non-write await (post-race): only a
        # fired guard converts, anything else propagates
        if not guard.fired:
            raise
        status = 502
        error = f"stream write timeout: {guard.timeout_error()}"
        state.metrics.record_stream_write_timeout(model)
    except StreamWriteTimeout as e:
        # the client stopped draining (slow-loris): abort so the engine
        # slot frees; counted, not blamed on the endpoint
        status = 502
        error = f"stream write timeout: {e}"
        state.metrics.record_stream_write_timeout(model)
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
            ConnectionResetError) as e:
        # client went away mid-write: not endpoint sickness
        status = 502
        error = error or f"client disconnected: {type(e).__name__}"
    finally:
        guard.close()
        upstream.release()
        if trace is not None:
            trace.end("decode")
            trace.end("proxy")
        if not outcome_booked:
            book_stream_outcome(state, failover, endpoint, model,
                                upstream_failed=upstream_failed,
                                completed=status == 200)
        ct = encoder.usage["output_tokens"]
        duration_s = time.monotonic() - started
        if trace is not None and timeline is not None:
            trace.attach_timeline(timeline)
        if status == 200 and ttft_s is not None:
            itl_mean = (max(0.0, duration_s - ttft_s) / (ct - 1)
                        if ct and ct > 1 else None)
            state.metrics.record_slo(model, ttft_s, itl_mean,
                                     priority=priority)
        if ct:
            state.load_manager.update_tps(
                endpoint.id, model, TpsApiKind.CHAT, ct, duration_s
            )
        _record(state, endpoint=endpoint, model=model, api_kind=TpsApiKind.CHAT,
                path="/v1/messages", status=status, started=started,
                prompt_tokens=encoder.usage["input_tokens"],
                completion_tokens=ct, client_ip=request.remote,
                auth=request.get("auth"), error=error, stream=True)
    return resp


async def _cloud_passthrough(request, state, body, model) -> web.StreamResponse:
    from llmlb_tpu.gateway.api_cloud import cloud_post

    key = os.environ.get("ANTHROPIC_API_KEY")
    if not key:
        return _anthropic_error(
            401, "ANTHROPIC_API_KEY not configured", "authentication_error"
        )
    payload = dict(body)
    payload["model"] = model
    upstream = await cloud_post(
        state, "anthropic", ANTHROPIC_BASE + "/v1/messages",
        json=payload,
        headers={
            "x-api-key": key,
            "anthropic-version": request.headers.get(
                "anthropic-version", "2023-06-01"
            ),
        },
        timeout=aiohttp.ClientTimeout(total=state.config.inference_timeout_s),
    )
    if payload.get("stream"):
        resp = web.StreamResponse(
            status=upstream.status,
            headers={"Content-Type": "text/event-stream"},
        )
        await resp.prepare(request)
        try:
            async for chunk in upstream.content.iter_any():
                await resp.write(chunk)
        finally:
            upstream.release()
        return resp
    raw = await upstream.read()
    upstream.release()
    return web.Response(body=raw, status=upstream.status,
                        content_type="application/json")
