"""TPS benchmark service: load-test endpoints through the normal routing path.

Parity with reference api/benchmarks.rs (start :250, concurrent execution
:371-404, per-request :408): POST /api/benchmarks/tps starts an async run of N
chat requests with bounded concurrency through the same selection pipeline real
traffic uses; results aggregate latency percentiles + TPS per endpoint and are
kept in a pruned in-memory store.
"""

from __future__ import annotations

import asyncio
import statistics
import time
import uuid

import aiohttp
from aiohttp import web

from llmlb_tpu.gateway.api_openai import select_endpoint_with_queue
from llmlb_tpu.gateway.token_accounting import extract_usage_from_response
from llmlb_tpu.gateway.types import Capability, TpsApiKind

MAX_STORED_RUNS = 20


class BenchmarkStore:
    def __init__(self):
        self.runs: dict[str, dict] = {}

    def put(self, run_id: str, run: dict) -> None:
        self.runs[run_id] = run
        while len(self.runs) > MAX_STORED_RUNS:
            self.runs.pop(next(iter(self.runs)))


STORE = BenchmarkStore()


def _percentile(values: list[float], pct: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(int(len(ordered) * pct / 100.0), len(ordered) - 1)
    return ordered[idx]


async def _run_single(state, model: str, prompt: str, max_tokens: int) -> dict:
    start = time.monotonic()
    try:
        selection = await select_endpoint_with_queue(
            state, model, Capability.CHAT_COMPLETION, TpsApiKind.CHAT
        )
    except Exception:
        selection = None
    if selection is None:
        return {"ok": False, "error": "no endpoint", "endpoint_id": None}
    endpoint, engine_model, lease, _model_rec = selection
    # Benchmarks go through the real admission machinery, so on a half-open
    # breaker they consume the probe slot — every exit below must report an
    # outcome to the resilience manager or that slot would stay wedged.
    resilience = state.resilience
    headers = {}
    if endpoint.api_key:
        headers["Authorization"] = f"Bearer {endpoint.api_key}"
    try:
        async with state.http.post(
            endpoint.url + "/v1/chat/completions",
            json={
                "model": engine_model,
                "messages": [{"role": "user", "content": prompt}],
                "max_tokens": max_tokens,
                "temperature": 0.7,
            },
            headers=headers,
            timeout=aiohttp.ClientTimeout(total=state.config.inference_timeout_s),
        ) as resp:
            body = await resp.json(content_type=None)
            elapsed = time.monotonic() - start
            if resp.status != 200:
                lease.fail()
                if resilience is not None:
                    if (resp.status in resilience.config.retryable_statuses
                            and resp.status != 429):
                        resilience.record_failure(endpoint.id,
                                                  f"http_{resp.status}")
                    else:
                        resilience.record_success(endpoint.id)
                return {"ok": False, "error": f"HTTP {resp.status}",
                        "endpoint_id": endpoint.id,
                        "latency_ms": elapsed * 1000}
            usage = extract_usage_from_response(body) or (0, 0)
            lease.complete_with_tokens(*usage)
            if resilience is not None:
                resilience.record_success(endpoint.id)
            state.load_manager.note_endpoint_success(endpoint.id)
            return {
                "ok": True, "endpoint_id": endpoint.id,
                "latency_ms": elapsed * 1000,
                "completion_tokens": usage[1],
                "tps": usage[1] / elapsed if elapsed > 0 else 0.0,
            }
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        lease.fail()
        if resilience is not None:
            resilience.record_failure(endpoint.id, "connect_error")
        state.load_manager.note_endpoint_failure(endpoint.id)
        return {"ok": False, "error": type(e).__name__,
                "endpoint_id": endpoint.id,
                "latency_ms": (time.monotonic() - start) * 1000}


async def _execute(state, run_id: str, model: str, requests: int,
                   concurrency: int, prompt: str, max_tokens: int) -> None:
    run = STORE.runs[run_id]
    sem = asyncio.Semaphore(concurrency)

    async def bounded() -> dict:
        async with sem:
            return await _run_single(state, model, prompt, max_tokens)

    started = time.monotonic()
    results = await asyncio.gather(*(bounded() for _ in range(requests)))
    elapsed = time.monotonic() - started

    ok = [r for r in results if r["ok"]]
    latencies = [r["latency_ms"] for r in ok]
    by_endpoint: dict[str, list[dict]] = {}
    for r in ok:
        by_endpoint.setdefault(r["endpoint_id"], []).append(r)

    run.update({
        "status": "completed",
        "completed_at": time.time(),
        "duration_s": round(elapsed, 3),
        "requests": requests,
        "succeeded": len(ok),
        "failed": len(results) - len(ok),
        "latency_ms": {
            "p50": round(_percentile(latencies, 50), 2),
            "p90": round(_percentile(latencies, 90), 2),
            "p99": round(_percentile(latencies, 99), 2),
            "mean": round(statistics.fmean(latencies), 2) if latencies else 0,
        },
        "throughput_rps": round(len(ok) / elapsed, 2) if elapsed > 0 else 0,
        "per_endpoint": {
            eid: {
                "requests": len(rs),
                "mean_tps": round(
                    statistics.fmean([r["tps"] for r in rs]), 2
                ) if rs else 0,
                "p50_latency_ms": round(
                    _percentile([r["latency_ms"] for r in rs], 50), 2
                ),
            }
            for eid, rs in by_endpoint.items()
        },
        "errors": [r["error"] for r in results if not r["ok"]][:10],
    })


async def start_tps_benchmark(request: web.Request) -> web.Response:
    state = request.app["state"]
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON body"}, status=400)
    model = body.get("model")
    if not model:
        return web.json_response({"error": "'model' is required"}, status=400)
    requests = min(int(body.get("requests", 10)), 1000)
    concurrency = min(int(body.get("concurrency", 4)), 64)
    prompt = body.get("prompt") or "Benchmark: write one sentence about TPUs."
    max_tokens = min(int(body.get("max_tokens", 64)), 2048)

    run_id = uuid.uuid4().hex
    STORE.put(run_id, {
        "run_id": run_id, "status": "running", "model": model,
        "started_at": time.time(),
    })
    asyncio.create_task(
        _execute(state, run_id, model, requests, concurrency, prompt, max_tokens)
    )
    return web.json_response({"run_id": run_id, "status": "running"}, status=202)


async def get_tps_benchmark(request: web.Request) -> web.Response:
    run = STORE.runs.get(request.match_info["run_id"])
    if run is None:
        return web.json_response({"error": "run not found"}, status=404)
    return web.json_response(run)


async def list_tps_benchmarks(request: web.Request) -> web.Response:
    return web.json_response({"runs": list(STORE.runs.values())})
