"""Cloud provider proxy: `openai:` / `google:` / `anthropic:` model prefixes.

Parity with reference api/cloud_proxy.rs (CloudProvider trait :34-60, impls
:207/:254/:346, proxy :62-180, env keys :187-204): each provider defines
request transform, auth injection, and response transform back to OpenAI shape.
Keys come from OPENAI_API_KEY / GEMINI_API_KEY|GOOGLE_API_KEY /
ANTHROPIC_API_KEY. Prometheus-style counters exposed at /api/metrics/cloud.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
import uuid
from collections import defaultdict

import aiohttp
from aiohttp import web

from llmlb_tpu.gateway.api_openai import error_response
from llmlb_tpu.gateway.resilience import RETRYABLE_EXCEPTIONS, backoff_delay

OPENAI_BASE = os.environ.get("LLMLB_OPENAI_BASE_URL", "https://api.openai.com")
GOOGLE_BASE = os.environ.get(
    "LLMLB_GOOGLE_BASE_URL", "https://generativelanguage.googleapis.com"
)
ANTHROPIC_BASE = os.environ.get(
    "LLMLB_ANTHROPIC_BASE_URL", "https://api.anthropic.com"
)


class CloudMetrics:
    """Process-global counters + latency histogram (cloud_metrics.rs:21-39)."""

    BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

    def __init__(self):
        self.requests: dict[tuple[str, str], int] = defaultdict(int)
        self.latency_buckets: dict[str, list[int]] = defaultdict(
            lambda: [0] * (len(self.BUCKETS) + 1)
        )
        self.latency_sum: dict[str, float] = defaultdict(float)
        self.latency_count: dict[str, int] = defaultdict(int)

    def observe(self, provider: str, status: str, latency_s: float) -> None:
        self.requests[(provider, status)] += 1
        buckets = self.latency_buckets[provider]
        for i, bound in enumerate(self.BUCKETS):
            if latency_s <= bound:
                buckets[i] += 1
                break
        else:
            buckets[-1] += 1
        self.latency_sum[provider] += latency_s
        self.latency_count[provider] += 1

    def render_prometheus(self) -> str:
        lines = [
            "# HELP llmlb_cloud_requests_total Cloud proxy requests",
            "# TYPE llmlb_cloud_requests_total counter",
        ]
        for (provider, status), count in sorted(self.requests.items()):
            lines.append(
                f'llmlb_cloud_requests_total{{provider="{provider}",'
                f'status="{status}"}} {count}'
            )
        lines += [
            "# HELP llmlb_cloud_latency_seconds Cloud request latency",
            "# TYPE llmlb_cloud_latency_seconds histogram",
        ]
        for provider, buckets in sorted(self.latency_buckets.items()):
            cumulative = 0
            for bound, n in zip(self.BUCKETS, buckets):
                cumulative += n
                lines.append(
                    f'llmlb_cloud_latency_seconds_bucket{{provider="{provider}",'
                    f'le="{bound}"}} {cumulative}'
                )
            cumulative += buckets[-1]
            lines.append(
                f'llmlb_cloud_latency_seconds_bucket{{provider="{provider}",'
                f'le="+Inf"}} {cumulative}'
            )
            lines.append(
                f'llmlb_cloud_latency_seconds_sum{{provider="{provider}"}} '
                f"{self.latency_sum[provider]:.6f}"
            )
            lines.append(
                f'llmlb_cloud_latency_seconds_count{{provider="{provider}"}} '
                f"{self.latency_count[provider]}"
            )
        return "\n".join(lines) + "\n"


METRICS = CloudMetrics()


def _api_key(provider: str) -> str | None:
    if provider == "openai":
        return os.environ.get("OPENAI_API_KEY")
    if provider == "google":
        return os.environ.get("GEMINI_API_KEY") or os.environ.get("GOOGLE_API_KEY")
    if provider == "anthropic":
        return os.environ.get("ANTHROPIC_API_KEY")
    return None


# ----------------------------------------------------- provider adaptations


def _openai_to_anthropic_request(body: dict, model: str) -> dict:
    """OpenAI chat body → Anthropic /v1/messages body."""
    messages = []
    system = None
    for m in body.get("messages") or []:
        role = m.get("role")
        if role == "system":
            system = m.get("content")
            continue
        messages.append({"role": role, "content": m.get("content") or ""})
    out = {
        "model": model,
        "messages": messages,
        "max_tokens": body.get("max_tokens")
        or body.get("max_completion_tokens") or 1024,
    }
    if system:
        out["system"] = system
    for k_src, k_dst in (("temperature", "temperature"), ("top_p", "top_p"),
                         ("stop", "stop_sequences"), ("stream", "stream")):
        if body.get(k_src) is not None:
            v = body[k_src]
            if k_dst == "stop_sequences" and isinstance(v, str):
                v = [v]
            out[k_dst] = v
    return out


def _anthropic_to_openai_response(body: dict, model: str) -> dict:
    text = "".join(
        b.get("text", "") for b in body.get("content") or []
        if isinstance(b, dict) and b.get("type") == "text"
    )
    usage = body.get("usage") or {}
    stop_reason = body.get("stop_reason")
    finish = {"end_turn": "stop", "max_tokens": "length",
              "stop_sequence": "stop"}.get(stop_reason, "stop")
    return {
        "id": body.get("id") or f"chatcmpl-{uuid.uuid4().hex[:24]}",
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "message": {"role": "assistant", "content": text},
            "finish_reason": finish,
        }],
        "usage": {
            "prompt_tokens": usage.get("input_tokens", 0),
            "completion_tokens": usage.get("output_tokens", 0),
            "total_tokens": usage.get("input_tokens", 0)
            + usage.get("output_tokens", 0),
        },
    }


def _openai_to_gemini_request(body: dict) -> dict:
    """OpenAI chat body → Gemini generateContent (generationConfig mapping
    parity: cloud_proxy.rs:254-343)."""
    contents = []
    system_instruction = None
    for m in body.get("messages") or []:
        role = m.get("role")
        text = m.get("content")
        if isinstance(text, list):
            text = "".join(
                p.get("text", "") for p in text if isinstance(p, dict)
            )
        if role == "system":
            system_instruction = {"parts": [{"text": text or ""}]}
            continue
        contents.append({
            "role": "user" if role == "user" else "model",
            "parts": [{"text": text or ""}],
        })
    cfg = {}
    if body.get("temperature") is not None:
        cfg["temperature"] = body["temperature"]
    if body.get("top_p") is not None:
        cfg["topP"] = body["top_p"]
    if body.get("max_tokens") is not None:
        cfg["maxOutputTokens"] = body["max_tokens"]
    stop = body.get("stop")
    if stop:
        cfg["stopSequences"] = [stop] if isinstance(stop, str) else stop
    out: dict = {"contents": contents}
    if system_instruction:
        out["systemInstruction"] = system_instruction
    if cfg:
        out["generationConfig"] = cfg
    return out


def _gemini_to_openai_response(body: dict, model: str) -> dict:
    text = ""
    finish = "stop"
    candidates = body.get("candidates") or []
    if candidates:
        cand = candidates[0]
        parts = (cand.get("content") or {}).get("parts") or []
        text = "".join(p.get("text", "") for p in parts if isinstance(p, dict))
        if cand.get("finishReason") == "MAX_TOKENS":
            finish = "length"
    meta = body.get("usageMetadata") or {}
    pt = meta.get("promptTokenCount", 0)
    ct = meta.get("candidatesTokenCount", 0)
    return {
        "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "message": {"role": "assistant", "content": text},
            "finish_reason": finish,
        }],
        "usage": {"prompt_tokens": pt, "completion_tokens": ct,
                  "total_tokens": pt + ct},
    }


async def cloud_post(state, provider: str, url: str, *, json=None,
                     headers=None, timeout=None):
    """POST to a cloud provider with bounded retry + capped backoff on
    connect errors and retryable statuses (5xx/429), spending the gateway's
    global retry budget. No circuit breaker: cloud providers are not
    registry endpoints, and there is no alternative to fail over to — this
    is same-target retry only."""
    resilience = state.resilience
    cfg = (resilience.config
           if resilience is not None and resilience.config.enabled else None)
    if cfg is not None:
        # fund the shared retry budget: cloud requests never build a
        # FailoverController, and a cloud-heavy deployment must not starve
        # local failover down to the budget's min floor
        resilience.budget.note_request()
    attempt = 1

    def spend_retry(reason: str) -> bool:
        nonlocal attempt
        if cfg is None or attempt >= cfg.max_attempts:
            return False
        if not resilience.budget.try_spend():
            # same bookkeeping as FailoverController: a budget-refused
            # retry must show up in the exhaustion counter/alert
            state.metrics.record_retry_budget_exhausted()
            return False
        state.metrics.record_failover_retry(f"cloud:{provider}", reason)
        attempt += 1
        return True

    while True:
        try:
            upstream = await state.http.post(
                url, json=json, headers=headers, timeout=timeout
            )
        except RETRYABLE_EXCEPTIONS:
            if spend_retry("connect_error"):
                await asyncio.sleep(backoff_delay(attempt - 1, cfg))
                continue
            raise
        if cfg is not None and upstream.status in cfg.retryable_statuses:
            reason = f"http_{upstream.status}"
            if spend_retry(reason):
                upstream.release()
                await asyncio.sleep(backoff_delay(attempt - 1, cfg))
                continue
        return upstream


# --------------------------------------------------------------- entry point


async def proxy_cloud_request(
    request: web.Request, provider: str, model: str, body: dict, path: str
) -> web.StreamResponse:
    state = request.app["state"]
    key = _api_key(provider)
    if not key:
        return error_response(
            401, f"no API key configured for cloud provider {provider!r} "
            f"(set {provider.upper()}_API_KEY)", "authentication_error",
        )
    start = time.monotonic()
    try:
        if provider == "openai":
            resp = await _proxy_openai_passthrough(
                request, state, key, model, body, path
            )
        elif provider == "anthropic":
            resp = await _proxy_anthropic(request, state, key, model, body)
        elif provider == "google":
            resp = await _proxy_google(request, state, key, model, body)
        else:
            return error_response(400, f"unknown cloud provider {provider!r}")
        METRICS.observe(provider, str(resp.status), time.monotonic() - start)
        return resp
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        METRICS.observe(provider, "error", time.monotonic() - start)
        return error_response(
            502, f"cloud provider {provider} unreachable: {type(e).__name__}",
            "server_error",
        )


async def _proxy_openai_passthrough(
    request, state, key, model, body, path
) -> web.StreamResponse:
    """Same wire format: swap model + auth, stream or buffer verbatim."""
    payload = dict(body)
    payload["model"] = model
    upstream = await cloud_post(
        state, "openai", OPENAI_BASE + path,
        json=payload,
        headers={"Authorization": f"Bearer {key}"},
        timeout=aiohttp.ClientTimeout(total=state.config.inference_timeout_s),
    )
    if payload.get("stream") and "text/event-stream" in upstream.headers.get(
        "Content-Type", ""
    ):
        resp = web.StreamResponse(
            status=upstream.status,
            headers={"Content-Type": "text/event-stream"},
        )
        await resp.prepare(request)
        try:
            async for chunk in upstream.content.iter_any():
                await resp.write(chunk)
        finally:
            upstream.release()
        return resp
    raw = await upstream.read()
    upstream.release()
    return web.Response(
        body=raw, status=upstream.status,
        content_type="application/json",
    )


async def _proxy_anthropic(request, state, key, model, body) -> web.Response:
    payload = _openai_to_anthropic_request(body, model)
    payload.pop("stream", None)  # converted cloud path is non-streaming
    upstream = await cloud_post(
        state, "anthropic", ANTHROPIC_BASE + "/v1/messages",
        json=payload,
        headers={"x-api-key": key, "anthropic-version": "2023-06-01"},
        timeout=aiohttp.ClientTimeout(total=state.config.inference_timeout_s),
    )
    raw = await upstream.read()
    upstream.release()
    if upstream.status != 200:
        return web.Response(
            body=raw, status=upstream.status, content_type="application/json"
        )
    return web.json_response(
        _anthropic_to_openai_response(json.loads(raw), f"anthropic:{model}")
    )


async def _proxy_google(request, state, key, model, body) -> web.Response:
    payload = _openai_to_gemini_request(body)
    upstream = await cloud_post(
        state, "google", f"{GOOGLE_BASE}/v1beta/models/{model}:generateContent",
        json=payload,
        headers={"x-goog-api-key": key},
        timeout=aiohttp.ClientTimeout(total=state.config.inference_timeout_s),
    )
    raw = await upstream.read()
    upstream.release()
    if upstream.status != 200:
        return web.Response(
            body=raw, status=upstream.status, content_type="application/json"
        )
    return web.json_response(
        _gemini_to_openai_response(json.loads(raw), f"google:{model}")
    )


async def cloud_metrics_handler(request: web.Request) -> web.Response:
    return web.Response(
        text=METRICS.render_prometheus(),
        content_type="text/plain",
    )
