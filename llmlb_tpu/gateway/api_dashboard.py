"""Dashboard data APIs + live WebSocket.

Parity with reference api/dashboard.rs (overview/stats/history/token stats/
client analytics :171-1254) and api/dashboard_ws.rs (JWT-auth WS pushing event
bus messages :36-76). Data comes from the in-memory 60-min history ring plus
the daily-stats and request_history tables.
"""

from __future__ import annotations

import asyncio
import datetime
import json
import logging
import time

from aiohttp import WSMsgType, web

from llmlb_tpu.gateway.auth import AuthError, verify_jwt

IP_ALERT_THRESHOLD_DEFAULT = 100  # parity: dashboard.rs:1350


def parse_ip_alert_threshold(value: str) -> int:
    """Integer >= 1, or ValueError (dashboard.rs:1353-1364)."""
    parsed = int(value)  # raises ValueError on non-integers
    if parsed < 1:
        raise ValueError("ip_alert_threshold must be an integer >= 1")
    return parsed


def effective_ip_alert_threshold(raw: str | None) -> int:
    """Configured threshold with default fallback; a corrupt stored value
    logs and falls back rather than breaking analytics (dashboard.rs:1367)."""
    if raw is None:
        return IP_ALERT_THRESHOLD_DEFAULT
    try:
        return parse_ip_alert_threshold(raw)
    except ValueError:
        log.warning("invalid ip_alert_threshold %r in settings; using "
                    "default %d", raw, IP_ALERT_THRESHOLD_DEFAULT)
        return IP_ALERT_THRESHOLD_DEFAULT

log = logging.getLogger("llmlb_tpu.gateway.dashboard")


async def overview(request: web.Request) -> web.Response:
    state = request.app["state"]
    endpoints = state.registry.list_all()
    online = [e for e in endpoints if e.status.value == "online"]
    models = state.registry.canonical_model_names()
    lm_stats = state.load_manager.stats()
    today = datetime.date.today().isoformat()
    row = state.db.query_one(
        """SELECT COALESCE(SUM(request_count),0) AS requests,
                  COALESCE(SUM(error_count),0) AS errors,
                  COALESCE(SUM(prompt_tokens),0) AS pt,
                  COALESCE(SUM(completion_tokens),0) AS ct
           FROM endpoint_daily_stats WHERE date=?""",
        (today,),
    )
    return web.json_response({
        "endpoints": {"total": len(endpoints), "online": len(online)},
        "models": {"total": len(models)},
        "requests": {
            "active": lm_stats["active_requests"],
            "today": row["requests"], "errors_today": row["errors"],
        },
        "tokens_today": {"prompt": row["pt"], "completion": row["ct"]},
        "latency": state.metrics.summary(),
        "tpu": {
            "total_chips": sum(e.accelerator.chip_count for e in online),
            "hbm_used_bytes": sum(e.accelerator.hbm_used_bytes for e in online),
            "hbm_total_bytes": sum(e.accelerator.hbm_total_bytes for e in online),
        },
    })


async def request_history_minutes(request: web.Request) -> web.Response:
    state = request.app["state"]
    return web.json_response(
        {"minutes": state.load_manager.history_minute_buckets()}
    )


async def request_records(request: web.Request) -> web.Response:
    state = request.app["state"]
    q = request.query
    clauses, params = [], []
    if q.get("model"):
        clauses.append("model=?")
        params.append(q["model"])
    if q.get("endpoint_id"):
        clauses.append("endpoint_id=?")
        params.append(q["endpoint_id"])
    if q.get("status"):
        clauses.append("status_code=?")
        params.append(int(q["status"]))
    where = ("WHERE " + " AND ".join(clauses)) if clauses else ""
    limit = min(int(q.get("limit", 50)), 500)
    offset = int(q.get("offset", 0))
    rows = state.db.query(
        f"""SELECT id, ts, endpoint_id, endpoint_name, model, api_kind, path,
                  status_code, duration_ms, prompt_tokens, completion_tokens,
                  client_ip, stream, error
           FROM request_history {where} ORDER BY ts DESC LIMIT ? OFFSET ?""",
        tuple(params) + (limit, offset),
    )
    return web.json_response({"records": [dict(r) for r in rows]})


async def request_record_detail(request: web.Request) -> web.Response:
    state = request.app["state"]
    row = state.db.query_one(
        "SELECT * FROM request_history WHERE id=?",
        (request.match_info["record_id"],),
    )
    if row is None:
        return web.json_response({"error": "record not found"}, status=404)
    return web.json_response(dict(row))


async def token_stats(request: web.Request) -> web.Response:
    """Total/daily/by-model/by-endpoint token statistics."""
    state = request.app["state"]
    days = min(int(request.query.get("days", 30)), 365)
    since = (
        datetime.date.today() - datetime.timedelta(days=days)
    ).isoformat()
    daily = state.db.query(
        """SELECT date, SUM(prompt_tokens) AS pt, SUM(completion_tokens) AS ct,
                  SUM(request_count) AS requests
           FROM endpoint_daily_stats WHERE date>=? GROUP BY date ORDER BY date""",
        (since,),
    )
    by_model = state.db.query(
        """SELECT model, SUM(prompt_tokens) AS pt,
                  SUM(completion_tokens) AS ct, SUM(request_count) AS requests
           FROM endpoint_daily_stats WHERE date>=? GROUP BY model
           ORDER BY ct DESC""",
        (since,),
    )
    by_endpoint = state.db.query(
        """SELECT endpoint_id, SUM(prompt_tokens) AS pt,
                  SUM(completion_tokens) AS ct, SUM(request_count) AS requests
           FROM endpoint_daily_stats WHERE date>=? GROUP BY endpoint_id""",
        (since,),
    )
    total = state.db.query_one(
        """SELECT COALESCE(SUM(prompt_tokens),0) AS pt,
                  COALESCE(SUM(completion_tokens),0) AS ct,
                  COALESCE(SUM(request_count),0) AS requests
           FROM endpoint_daily_stats WHERE date>=?""",
        (since,),
    )
    return web.json_response({
        "total": dict(total),
        "daily": [dict(r) for r in daily],
        "by_model": [dict(r) for r in by_model],
        "by_endpoint": [dict(r) for r in by_endpoint],
    })


async def endpoint_stats(request: web.Request) -> web.Response:
    state = request.app["state"]
    endpoint_id = request.match_info["endpoint_id"]
    days = min(int(request.query.get("days", 30)), 365)
    since = (
        datetime.date.today() - datetime.timedelta(days=days)
    ).isoformat()
    rows = state.db.query(
        """SELECT date, model, api_kind, request_count, error_count,
                  prompt_tokens, completion_tokens, total_duration_ms
           FROM endpoint_daily_stats
           WHERE endpoint_id=? AND date>=? ORDER BY date""",
        (endpoint_id, since),
    )
    return web.json_response({"stats": [dict(r) for r in rows]})


async def model_tps(request: web.Request) -> web.Response:
    state = request.app["state"]
    return web.json_response({"tps": state.load_manager.tps_snapshot()})


async def client_analytics(request: web.Request) -> web.Response:
    """Client-IP rankings / timeline / per-client detail (dashboard.rs analytics)."""
    state = request.app["state"]
    q = request.query
    try:
        days = min(int(q.get("days", 7)), 90)
    except ValueError:
        return web.json_response(
            {"error": "days must be an integer"}, status=400
        )
    since_ts = (
        datetime.datetime.now() - datetime.timedelta(days=days)
    ).timestamp()
    ranking = state.db.query(
        """SELECT client_ip, COUNT(*) AS requests,
                  SUM(prompt_tokens) AS pt, SUM(completion_tokens) AS ct,
                  SUM(CASE WHEN status_code>=400 THEN 1 ELSE 0 END) AS errors
           FROM request_history WHERE ts>=? AND client_ip IS NOT NULL
           GROUP BY client_ip ORDER BY requests DESC LIMIT 50""",
        (since_ts,),
    )
    # is_alert: last-HOUR request count at/above the configurable threshold
    # (settings key ip_alert_threshold, default 100 — dashboard.rs:1265-1279)
    threshold = effective_ip_alert_threshold(
        state.db.get_setting("ip_alert_threshold")
    )
    hour_ago = time.time() - 3600.0
    last_hour = {
        row["client_ip"]: row["n"]
        for row in state.db.query(
            """SELECT client_ip, COUNT(*) AS n FROM request_history
               WHERE ts>=? AND client_ip IS NOT NULL GROUP BY client_ip""",
            (hour_ago,),
        )
    }
    heatmap = state.db.query(
        """SELECT CAST(strftime('%w', ts, 'unixepoch') AS INTEGER) AS dow,
                  CAST(strftime('%H', ts, 'unixepoch') AS INTEGER) AS hour,
                  COUNT(*) AS requests
           FROM request_history WHERE ts>=?
           GROUP BY dow, hour""",
        (since_ts,),
    )
    by_key = state.db.query(
        """SELECT api_key_id, COUNT(*) AS requests,
                  SUM(completion_tokens) AS ct
           FROM request_history WHERE ts>=? AND api_key_id IS NOT NULL
           GROUP BY api_key_id ORDER BY requests DESC LIMIT 50""",
        (since_ts,),
    )
    ranking_out = []
    for r in ranking:
        row = dict(r)
        row["is_alert"] = last_hour.get(row["client_ip"], 0) >= threshold
        ranking_out.append(row)
    return web.json_response({
        "ranking": ranking_out,
        "heatmap": [dict(r) for r in heatmap],
        "by_api_key": [dict(r) for r in by_key],
        "ip_alert_threshold": threshold,
    })


# ---------------------------------------------------------------- WebSocket


async def dashboard_ws(request: web.Request) -> web.WebSocketResponse:
    """JWT-authenticated (header, query param, or cookie), admin-only."""
    state = request.app["state"]
    token = None
    authz = request.headers.get("Authorization", "")
    if authz.startswith("Bearer "):
        token = authz[7:]
    token = token or request.query.get("token") or request.cookies.get("llmlb_token")
    if not token:
        raise web.HTTPUnauthorized(text="missing token")
    try:
        payload = verify_jwt(state.jwt_secret, token)
    except AuthError as e:
        raise web.HTTPUnauthorized(text=str(e))
    if payload.get("role") != "admin":
        raise web.HTTPForbidden(text="admin role required")

    ws = web.WebSocketResponse(heartbeat=30)
    await ws.prepare(request)
    sub_id, queue = state.events.subscribe()
    try:
        consumer = asyncio.create_task(_consume_client(ws))
        try:
            while not ws.closed:
                try:
                    event = await asyncio.wait_for(queue.get(), timeout=5.0)
                except asyncio.TimeoutError:
                    continue
                await ws.send_str(json.dumps(event, separators=(",", ":")))
        finally:
            consumer.cancel()
    finally:
        state.events.unsubscribe(sub_id)
    return ws


async def _consume_client(ws: web.WebSocketResponse) -> None:
    """Drain client frames so pings/closes are processed."""
    try:
        async for msg in ws:
            if msg.type in (WSMsgType.CLOSE, WSMsgType.ERROR):
                break
    except Exception:  # allow-silent: client ws died; writer side handles it
        pass


async def tail_lb_logs(request: web.Request) -> web.Response:
    """GET /api/dashboard/logs/lb — tail the gateway's own log file
    (parity: api/logs.rs:52; requires logs.read via the API-key perm map)."""
    from llmlb_tpu.gateway.logging_setup import active_log_path, tail_log

    try:
        lines = int(request.query.get("lines", "200"))
    except ValueError:
        return web.json_response({"error": "lines must be an integer"},
                                 status=400)
    path = active_log_path()
    return web.json_response({
        "path": path,
        "available": path is not None,
        "lines": tail_log(lines),
    })
