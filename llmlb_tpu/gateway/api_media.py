"""Audio + image routes: capability-routed proxies.

Parity with reference api/audio.rs (:199-370 transcriptions multipart re-proxy,
:377 speech) and api/images.rs (:184/:284/:508 generations/edits/variations,
capability selection :158-182): the gateway validates, selects an endpoint
advertising the capability, and re-proxies JSON or multipart bodies.
"""

from __future__ import annotations

import asyncio
import time

import aiohttp
from aiohttp import web

from llmlb_tpu.gateway.api_openai import (
    QueueTimeout,
    _record,
    error_response,
)
from llmlb_tpu.gateway.resilience import (
    RETRYABLE_EXCEPTIONS,
    FailoverController,
    retry_after_seconds,
    upstream_post,
)
from llmlb_tpu.gateway.types import Capability, TpsApiKind


def _capability_pairs(state, capability: Capability, model: str | None):
    pairs = state.registry.list_online_by_capability(capability)
    if model:
        pairs = [
            (ep, m) for ep, m in pairs
            if m.canonical_name == model or m.model_id == model
        ]
    return pairs


async def _admit_by_capability(state, capability: Capability,
                               model: str | None,
                               exclude: set[str] | None = None,
                               queue_timeout_s: float | None = None):
    """Atomic admission on the capability-filtered pool; parks on the
    AdmissionQueue (same machinery as /v1/chat) when all slots are taken.
    `exclude` drops endpoints that already failed this request (failover)."""
    if not _capability_pairs(state, capability, model):
        return None
    schedule_key = model or capability.value

    def get_endpoints():
        return [
            ep for ep, _ in _capability_pairs(state, capability, model)
            if not exclude or ep.id not in exclude
        ]

    result = await state.admission.admit(
        get_endpoints, schedule_key, TpsApiKind.OTHER,
        timeout_s=queue_timeout_s,
    )
    if not result.admitted:
        raise QueueTimeout(result.queue_position, result.waited_s)
    pairs = _capability_pairs(state, capability, model)
    engine_model = next(
        (m.model_id for ep, m in pairs if ep.id == result.endpoint.id),
        model or "",
    )
    return result.endpoint, engine_model, result.lease


async def _read_multipart(request: web.Request) -> list[dict]:
    """Buffer the client's multipart form once so each failover attempt can
    re-emit a fresh FormData toward a different endpoint (the request body
    can only be read from the socket once)."""
    reader = await request.multipart()
    parts: list[dict] = []
    async for part in reader:
        name = part.name or "file"
        if part.filename:
            parts.append({
                "name": name,
                "data": await part.read(decode=False),
                "filename": part.filename,
                "content_type": part.headers.get("Content-Type"),
            })
        else:
            parts.append({
                "name": name,
                "value": (await part.read(decode=True)).decode(
                    errors="replace"
                ),
            })
    return parts


def _build_form(parts: list[dict], model_override: str | None) -> aiohttp.FormData:
    form = aiohttp.FormData()
    for p in parts:
        if "filename" in p:
            form.add_field(p["name"], p["data"], filename=p["filename"],
                           content_type=p["content_type"])
        else:
            value = p["value"]
            if p["name"] == "model" and model_override:
                value = model_override
            form.add_field(p["name"], value)
    return form


async def _media_proxy(
    request: web.Request, capability: Capability, path: str,
    multipart: bool,
) -> web.Response:
    state = request.app["state"]
    started = time.monotonic()
    model = None
    body = None
    if not multipart:
        try:
            body = await request.json()
        except Exception:
            return error_response(400, "invalid JSON body")
        model = body.get("model")
        if capability == Capability.IMAGE_GENERATION:
            prompt = body.get("prompt")
            if not prompt or not isinstance(prompt, str):
                return error_response(400, "'prompt' is required")
            n = body.get("n", 1)
            if not isinstance(n, int) or not 1 <= n <= 10:
                return error_response(400, "'n' must be between 1 and 10")
    else:
        if not (request.content_type or "").startswith("multipart/"):
            return error_response(400, "multipart/form-data body required")

    # Multipart bodies are buffered once up front so every failover attempt
    # can re-emit them (the client socket can only be read once). A client
    # aborting mid-upload is its failure, not ours — a clean 400, no
    # endpoint involved yet.
    parts = None
    if multipart:
        try:
            parts = await _read_multipart(request)
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                ConnectionResetError, ValueError) as e:
            return error_response(
                400, f"could not read multipart body: {type(e).__name__}"
            )
    schedule_key = model or capability.value
    fo = FailoverController(
        state, schedule_key,
        candidates_fn=lambda: [
            ep for ep, _ in _capability_pairs(state, capability, model)
        ],
    )
    while True:
        try:
            selection = await _admit_by_capability(
                state, capability, model, exclude=fo.failed_ids,
                queue_timeout_s=(fo.config.failover_queue_timeout_s
                                 if fo.failed_ids else None),
            )
        except QueueTimeout as qt:
            return error_response(
                503,
                f"all endpoints busy; queue timeout exceeded "
                f"(position {qt.queue_position})",
                "server_error",
                headers={"Retry-After": str(
                    retry_after_seconds(state, model, capability)
                )},
            )
        if selection is None:
            return error_response(
                404,
                f"no online endpoint provides capability {capability.value!r}"
                + (f" for model {model!r}" if model else ""),
            )
        endpoint, engine_model, lease = selection
        headers = {}
        if endpoint.api_key:
            headers["Authorization"] = f"Bearer {endpoint.api_key}"
        upstream = None
        try:
            if multipart:
                upstream = await upstream_post(
                    state, endpoint, path,
                    data=_build_form(parts, engine_model),
                    headers=headers,
                    timeout=aiohttp.ClientTimeout(
                        total=state.config.inference_timeout_s
                    ),
                )
            else:
                payload = dict(body)
                if model:
                    payload["model"] = engine_model
                upstream = await upstream_post(
                    state, endpoint, path, json=payload, headers=headers,
                    timeout=aiohttp.ClientTimeout(
                        total=state.config.inference_timeout_s
                    ),
                )
            raw = await upstream.read()
            ctype = upstream.headers.get("Content-Type", "application/json")
            status = upstream.status
            upstream.release()
        except RETRYABLE_EXCEPTIONS as e:
            if upstream is not None:  # failed mid-read: reclaim the pooled
                upstream.release()    # connection before retrying
            reason = ("timeout" if isinstance(e, asyncio.TimeoutError)
                      else "connect_error")
            fo.record_failure(endpoint, lease, reason)
            if await fo.should_retry(reason):
                continue
            _record(state, endpoint=endpoint, model=model or capability.value,
                    api_kind=TpsApiKind.OTHER, path=path, status=502,
                    started=started, client_ip=request.remote,
                    auth=request.get("auth"), error=str(e))
            return error_response(
                502, f"upstream endpoint unreachable: {type(e).__name__}",
                "server_error",
            )

        if status in fo.config.retryable_statuses:
            reason = f"http_{status}"
            fo.record_failure(endpoint, lease, reason)
            if await fo.should_retry(reason):
                continue
        elif status >= 400:
            # non-retryable upstream error: alive, not sick — resolves a
            # half-open probe
            lease.fail()
            fo.record_alive(endpoint)
        else:
            lease.complete()
            fo.record_success(endpoint)
        _record(state, endpoint=endpoint, model=model or capability.value,
                api_kind=TpsApiKind.OTHER, path=path, status=status,
                started=started, client_ip=request.remote,
                auth=request.get("auth"))
        return web.Response(
            body=raw, status=status, content_type=ctype.split(";")[0]
        )


async def audio_transcriptions(request: web.Request) -> web.Response:
    return await _media_proxy(
        request, Capability.AUDIO_TRANSCRIPTION, "/v1/audio/transcriptions",
        multipart=True,
    )


async def audio_speech(request: web.Request) -> web.Response:
    return await _media_proxy(
        request, Capability.AUDIO_SPEECH, "/v1/audio/speech", multipart=False
    )


async def images_generations(request: web.Request) -> web.Response:
    return await _media_proxy(
        request, Capability.IMAGE_GENERATION, "/v1/images/generations",
        multipart=False,
    )


async def images_edits(request: web.Request) -> web.Response:
    return await _media_proxy(
        request, Capability.IMAGE_GENERATION, "/v1/images/edits", multipart=True
    )


async def images_variations(request: web.Request) -> web.Response:
    return await _media_proxy(
        request, Capability.IMAGE_GENERATION, "/v1/images/variations",
        multipart=True,
    )
