"""Audio + image routes: capability-routed proxies.

Parity with reference api/audio.rs (:199-370 transcriptions multipart re-proxy,
:377 speech) and api/images.rs (:184/:284/:508 generations/edits/variations,
capability selection :158-182): the gateway validates, selects an endpoint
advertising the capability, and re-proxies JSON or multipart bodies.
"""

from __future__ import annotations

import asyncio
import time

import aiohttp
from aiohttp import web

from llmlb_tpu.gateway.api_openai import (
    QueueTimeout,
    _record,
    error_response,
)
from llmlb_tpu.gateway.types import Capability, TpsApiKind


def _capability_pairs(state, capability: Capability, model: str | None):
    pairs = state.registry.list_online_by_capability(capability)
    if model:
        pairs = [
            (ep, m) for ep, m in pairs
            if m.canonical_name == model or m.model_id == model
        ]
    return pairs


async def _admit_by_capability(state, capability: Capability,
                               model: str | None):
    """Atomic admission on the capability-filtered pool; parks on the
    AdmissionQueue (same machinery as /v1/chat) when all slots are taken."""
    if not _capability_pairs(state, capability, model):
        return None
    schedule_key = model or capability.value

    def get_endpoints():
        return [ep for ep, _ in _capability_pairs(state, capability, model)]

    result = await state.admission.admit(
        get_endpoints, schedule_key, TpsApiKind.OTHER
    )
    if not result.admitted:
        raise QueueTimeout(result.queue_position, result.waited_s)
    pairs = _capability_pairs(state, capability, model)
    engine_model = next(
        (m.model_id for ep, m in pairs if ep.id == result.endpoint.id),
        model or "",
    )
    return result.endpoint, engine_model, result.lease


async def _reproxy_multipart(
    request: web.Request, state, endpoint, path: str, model_override: str | None,
) -> web.Response:
    """Re-read multipart form and re-emit it toward the endpoint."""
    reader = await request.multipart()
    form = aiohttp.FormData()
    async for part in reader:
        name = part.name or "file"
        if part.filename:
            data = await part.read(decode=False)
            form.add_field(
                name, data, filename=part.filename,
                content_type=part.headers.get("Content-Type"),
            )
        else:
            value = (await part.read(decode=True)).decode(errors="replace")
            if name == "model" and model_override:
                value = model_override
            form.add_field(name, value)
    headers = {}
    if endpoint.api_key:
        headers["Authorization"] = f"Bearer {endpoint.api_key}"
    upstream = await state.http.post(
        endpoint.url + path, data=form, headers=headers,
        timeout=aiohttp.ClientTimeout(total=state.config.inference_timeout_s),
    )
    raw = await upstream.read()
    ctype = upstream.headers.get("Content-Type", "application/json")
    status = upstream.status
    upstream.release()
    return web.Response(body=raw, status=status, content_type=ctype.split(";")[0])


async def _media_proxy(
    request: web.Request, capability: Capability, path: str,
    multipart: bool,
) -> web.Response:
    state = request.app["state"]
    started = time.monotonic()
    model = None
    body = None
    if not multipart:
        try:
            body = await request.json()
        except Exception:
            return error_response(400, "invalid JSON body")
        model = body.get("model")
        if capability == Capability.IMAGE_GENERATION:
            prompt = body.get("prompt")
            if not prompt or not isinstance(prompt, str):
                return error_response(400, "'prompt' is required")
            n = body.get("n", 1)
            if not isinstance(n, int) or not 1 <= n <= 10:
                return error_response(400, "'n' must be between 1 and 10")
    else:
        if not (request.content_type or "").startswith("multipart/"):
            return error_response(400, "multipart/form-data body required")

    try:
        selection = await _admit_by_capability(state, capability, model)
    except QueueTimeout as qt:
        return error_response(
            503,
            f"all endpoints busy; queue timeout exceeded "
            f"(position {qt.queue_position})",
            "server_error",
        )
    if selection is None:
        return error_response(
            404,
            f"no online endpoint provides capability {capability.value!r}"
            + (f" for model {model!r}" if model else ""),
        )
    endpoint, engine_model, lease = selection
    try:
        if multipart:
            resp = await _reproxy_multipart(
                request, state, endpoint, path, engine_model
            )
        else:
            payload = dict(body)
            if model:
                payload["model"] = engine_model
            headers = {}
            if endpoint.api_key:
                headers["Authorization"] = f"Bearer {endpoint.api_key}"
            upstream = await state.http.post(
                endpoint.url + path, json=payload, headers=headers,
                timeout=aiohttp.ClientTimeout(
                    total=state.config.inference_timeout_s
                ),
            )
            raw = await upstream.read()
            ctype = upstream.headers.get("Content-Type", "application/json")
            status = upstream.status
            upstream.release()
            resp = web.Response(
                body=raw, status=status, content_type=ctype.split(";")[0]
            )
        lease.complete()
        _record(state, endpoint=endpoint, model=model or capability.value,
                api_kind=TpsApiKind.OTHER, path=path, status=resp.status,
                started=started, client_ip=request.remote,
                auth=request.get("auth"))
        return resp
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        lease.fail()
        _record(state, endpoint=endpoint, model=model or capability.value,
                api_kind=TpsApiKind.OTHER, path=path, status=502,
                started=started, client_ip=request.remote,
                auth=request.get("auth"), error=str(e))
        return error_response(
            502, f"upstream endpoint unreachable: {type(e).__name__}",
            "server_error",
        )


async def audio_transcriptions(request: web.Request) -> web.Response:
    return await _media_proxy(
        request, Capability.AUDIO_TRANSCRIPTION, "/v1/audio/transcriptions",
        multipart=True,
    )


async def audio_speech(request: web.Request) -> web.Response:
    return await _media_proxy(
        request, Capability.AUDIO_SPEECH, "/v1/audio/speech", multipart=False
    )


async def images_generations(request: web.Request) -> web.Response:
    return await _media_proxy(
        request, Capability.IMAGE_GENERATION, "/v1/images/generations",
        multipart=False,
    )


async def images_edits(request: web.Request) -> web.Response:
    return await _media_proxy(
        request, Capability.IMAGE_GENERATION, "/v1/images/edits", multipart=True
    )


async def images_variations(request: web.Request) -> web.Response:
    return await _media_proxy(
        request, Capability.IMAGE_GENERATION, "/v1/images/variations",
        multipart=True,
    )
