"""Model registry + HF catalog + per-endpoint model management.

Parity with three reference modules:
- api/models.rs — `POST /api/models/register` pulls a HF repo's file listing
  (safetensors or GGUF), stores metadata + manifest ONLY (no weights,
  :1021-1165), and serves `GET /api/models/registry/:model/manifest.json`
  (:1167) for runtimes to pull from.
- api/catalog.rs — dashboard search over the huggingface.co API (:292) with
  per-endpoint download recommendation (:440-475).
- download/ + delete/ + metadata/ — per-engine model download (Ollama
  `/api/pull` etc.), delete (`/api/delete`), and info (`/api/show`)
  re-proxies, exposed under `/api/endpoints/:id/models/...`.

The HF base URL comes from `HF_BASE_URL` (reference README.md:490) so tests
point it at a mock server; without egress the handlers fail with an explicit
502 rather than hanging.
"""

from __future__ import annotations

import asyncio
import os
import time
import uuid

import aiohttp
from aiohttp import web

from llmlb_tpu.gateway.types import EndpointType


def _json_error(status: int, message: str) -> web.Response:
    return web.json_response({"error": message}, status=status)


def hf_base_url() -> str:
    return os.environ.get("HF_BASE_URL", "https://huggingface.co").rstrip("/")


def _hf_headers() -> dict:
    token = os.environ.get("HF_TOKEN")
    return {"Authorization": f"Bearer {token}"} if token else {}


# ---------------------------------------------------------------------------
# Registry (manifest-only model registration)
# ---------------------------------------------------------------------------

def pick_gguf(files: list[str], policy: str = "q4") -> str | None:
    """GGUF pick policy: prefer the requested quant tier, else smallest-ish
    (parity with the reference's policy-based GGUF selection)."""
    ggufs = [f for f in files if f.endswith(".gguf")]
    if not ggufs:
        return None
    preferred = [f for f in ggufs if policy.lower() in f.lower()]
    return sorted(preferred or ggufs)[0]


async def register_model(request: web.Request) -> web.Response:
    """POST /api/models/register {repo, name?, gguf_policy?} — fetch the HF
    repo's sibling file list, build a manifest (no weight download)."""
    state = request.app["state"]
    try:
        body = await request.json()
    except Exception:
        return _json_error(400, "invalid JSON body")
    repo = body.get("repo")
    if not repo or not isinstance(repo, str) or repo.count("/") != 1:
        return _json_error(400, "'repo' must be a HF 'org/name' id")
    name = body.get("name") or repo.split("/", 1)[1]

    url = f"{hf_base_url()}/api/models/{repo}"
    try:
        async with state.http.get(
            url, headers=_hf_headers(),
            timeout=aiohttp.ClientTimeout(total=30),
        ) as resp:
            if resp.status == 404:
                return _json_error(404, f"HF repo {repo!r} not found")
            if resp.status != 200:
                return _json_error(502, f"HF API returned {resp.status}")
            info = await resp.json(content_type=None)
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        return _json_error(502, f"HF API unreachable: {type(e).__name__}")

    files = [s.get("rfilename", "") for s in info.get("siblings", [])]
    safetensors = [f for f in files if f.endswith(".safetensors")]
    gguf = pick_gguf(files, body.get("gguf_policy", "q4"))
    if safetensors:
        format_ = "safetensors"
        weight_files = sorted(safetensors)
    elif gguf:
        format_ = "gguf"
        weight_files = [gguf]
    else:
        return _json_error(
            422, f"repo {repo!r} contains neither safetensors nor GGUF weights"
        )

    manifest = {
        "name": name,
        "source_repo": repo,
        "format": format_,
        "files": [
            {
                "path": f,
                "url": f"{hf_base_url()}/{repo}/resolve/main/{f}",
            }
            for f in weight_files
            + [f for f in files if f in (
                "config.json", "tokenizer.json", "tokenizer_config.json",
                "tokenizer.model", "generation_config.json",
                "model.safetensors.index.json",
            )]
        ],
        "created_at": time.time(),
    }
    caps = ["embeddings"] if "embed" in name.lower() else ["chat_completion"]
    model_id = state.db.register_model(name, repo, format_, caps, manifest)
    return web.json_response(
        {"id": model_id, "name": name, "format": format_,
         "files": len(manifest["files"])},
        status=201,
    )


async def list_registered_models(request: web.Request) -> web.Response:
    state = request.app["state"]
    return web.json_response({"models": state.db.list_registered_models()})


async def delete_registered_model(request: web.Request) -> web.Response:
    state = request.app["state"]
    name = request.match_info["name"]
    if not state.db.delete_registered_model(name):
        return _json_error(404, f"model {name!r} is not registered")
    return web.json_response({"deleted": name})


async def get_model_manifest(request: web.Request) -> web.Response:
    """GET /api/models/registry/{model}/manifest.json — the pull contract
    runtimes consume (api/models.rs:1167)."""
    state = request.app["state"]
    model = state.db.get_registered_model(request.match_info["model"])
    if model is None or not model.get("manifest"):
        return _json_error(404, "no manifest for this model")
    return web.json_response(model["manifest"])


# ---------------------------------------------------------------------------
# HF catalog search (api/catalog.rs parity)
# ---------------------------------------------------------------------------

async def catalog_search(request: web.Request) -> web.Response:
    """GET /api/catalog/search?q=...&limit=N — HF model search plus, per hit,
    which registered endpoints could serve/download it (catalog.rs:440-475)."""
    state = request.app["state"]
    q = request.query.get("q", "")
    if not q:
        return _json_error(400, "'q' query parameter is required")
    try:
        limit = min(int(request.query.get("limit", "20")), 50)
    except ValueError:
        return _json_error(400, "'limit' must be an integer")

    url = f"{hf_base_url()}/api/models"
    try:
        async with state.http.get(
            url, params={"search": q, "limit": str(limit)},
            headers=_hf_headers(), timeout=aiohttp.ClientTimeout(total=30),
        ) as resp:
            if resp.status != 200:
                return _json_error(502, f"HF API returned {resp.status}")
            hits = await resp.json(content_type=None)
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        return _json_error(502, f"HF API unreachable: {type(e).__name__}")

    online = state.registry.list_online()
    downloaders = [
        {"endpoint_id": ep.id, "name": ep.name,
         "endpoint_type": ep.endpoint_type.value}
        for ep in online
        if ep.endpoint_type in (EndpointType.OLLAMA, EndpointType.XLLM,
                                EndpointType.LM_STUDIO, EndpointType.TPU)
    ]
    results = []
    for hit in hits if isinstance(hits, list) else []:
        repo = hit.get("modelId") or hit.get("id") or ""
        results.append({
            "repo": repo,
            "downloads": hit.get("downloads", 0),
            "likes": hit.get("likes", 0),
            "tags": hit.get("tags", [])[:8],
            # engine-local name derivation (models/mapping.rs heuristics)
            "ollama_name": repo.split("/")[-1].lower().replace("_", "-"),
            "recommended_endpoints": downloaders,
        })
    return web.json_response({"results": results})


# ---------------------------------------------------------------------------
# Per-endpoint model management (download/ delete/ metadata/ parity)
# ---------------------------------------------------------------------------

_DOWNLOAD_TASKS: dict[str, dict] = {}  # in-memory task store (pruned)
# Strong references to in-flight download asyncio.Tasks: the event loop only
# keeps weak refs, so without this a long pull can be GC'd mid-flight.
_ACTIVE_DOWNLOADS: set[asyncio.Task] = set()


def _prune_tasks(max_tasks: int = 200) -> None:
    if len(_DOWNLOAD_TASKS) > max_tasks:
        evictable = [k for k, t in _DOWNLOAD_TASKS.items()
                     if t["status"] != "running"]
        for key in sorted(evictable,
                          key=lambda k: _DOWNLOAD_TASKS[k]["started_at"])[:50]:
            _DOWNLOAD_TASKS.pop(key, None)


async def download_endpoint_model(request: web.Request) -> web.Response:
    """POST /api/endpoints/{endpoint_id}/models/download {model} — kick a
    pull on the endpoint's engine (Ollama `/api/pull`; generic engines that
    expose `/api/models/download`)."""
    state = request.app["state"]
    ep = state.registry.get(request.match_info["endpoint_id"])
    if ep is None:
        return _json_error(404, "endpoint not found")
    try:
        body = await request.json()
    except Exception:
        return _json_error(400, "invalid JSON body")
    model = body.get("model")
    if not model:
        return _json_error(400, "'model' is required")

    task_id = uuid.uuid4().hex
    task = {
        "id": task_id, "endpoint_id": ep.id, "model": model,
        "status": "running", "progress": 0.0, "error": None,
        "started_at": time.time(),
    }
    _DOWNLOAD_TASKS[task_id] = task
    _prune_tasks()

    async def run():
        try:
            if ep.endpoint_type == EndpointType.OLLAMA:
                path, payload = "/api/pull", {"name": model, "stream": False}
            elif ep.endpoint_type == EndpointType.LM_STUDIO:
                # LM Studio wants a HF URL (download/lm_studio.rs:52-62)
                from llmlb_tpu.gateway.model_names import guess_hf_repo

                repo = guess_hf_repo(model) or model
                hf_url = (repo if repo.startswith("https://")
                          else f"https://huggingface.co/{repo}")
                path, payload = "/api/v1/models/download", {"model": hf_url}
            elif ep.endpoint_type == EndpointType.XLLM:
                # xLLM pulls from HF by repo id (xllm/download.rs:87)
                from llmlb_tpu.gateway.model_names import guess_hf_repo

                path = "/api/models/download"
                payload = {"model": guess_hf_repo(model) or model}
            else:
                path, payload = "/api/models/download", {"model": model}
            headers = {}
            if ep.api_key:
                headers["Authorization"] = f"Bearer {ep.api_key}"
            async with state.http.post(
                ep.url + path, json=payload, headers=headers,
                timeout=aiohttp.ClientTimeout(total=3600),
            ) as resp:
                if resp.status >= 400:
                    raise RuntimeError(f"engine returned {resp.status}")
                await resp.read()
            task["status"] = "completed"
            task["progress"] = 1.0
            # refresh the endpoint's model list so the new model is routable
            from llmlb_tpu.gateway.model_sync import sync_endpoint_models

            await sync_endpoint_models(ep, state.registry, state.http)
        except Exception as e:
            task["status"] = "failed"
            task["error"] = str(e)

    t = asyncio.create_task(run())
    _ACTIVE_DOWNLOADS.add(t)
    t.add_done_callback(_ACTIVE_DOWNLOADS.discard)
    return web.json_response({"task_id": task_id}, status=202)


async def download_progress(request: web.Request) -> web.Response:
    task = _DOWNLOAD_TASKS.get(request.match_info["task_id"])
    if task is None:
        return _json_error(404, "unknown download task")
    return web.json_response(task)


async def delete_endpoint_model(request: web.Request) -> web.Response:
    """DELETE /api/endpoints/{endpoint_id}/models/{model} (Ollama
    `/api/delete`; generic engines' DELETE /api/models/{model})."""
    state = request.app["state"]
    ep = state.registry.get(request.match_info["endpoint_id"])
    if ep is None:
        return _json_error(404, "endpoint not found")
    model = request.match_info["model"]
    headers = {}
    if ep.api_key:
        headers["Authorization"] = f"Bearer {ep.api_key}"
    try:
        if ep.endpoint_type == EndpointType.OLLAMA:
            resp = await state.http.delete(
                ep.url + "/api/delete", json={"name": model}, headers=headers,
                timeout=aiohttp.ClientTimeout(total=60),
            )
        else:
            resp = await state.http.delete(
                ep.url + f"/api/models/{model}", headers=headers,
                timeout=aiohttp.ClientTimeout(total=60),
            )
        status = resp.status
        resp.release()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        return _json_error(502, f"endpoint unreachable: {type(e).__name__}")
    if status >= 400:
        return _json_error(502, f"engine refused delete ({status})")
    from llmlb_tpu.gateway.model_sync import sync_endpoint_models

    try:
        await sync_endpoint_models(ep, state.registry, state.http)
    except Exception:  # allow-silent: best-effort resync; the periodic
        pass           # sync loop reconciles on its next pass
    return web.json_response({"deleted": model})


async def endpoint_model_info(request: web.Request) -> web.Response:
    """GET /api/endpoints/{endpoint_id}/models/{model}/info (Ollama
    `/api/show` parity; others get the synced registry record)."""
    state = request.app["state"]
    ep = state.registry.get(request.match_info["endpoint_id"])
    if ep is None:
        return _json_error(404, "endpoint not found")
    model = request.match_info["model"]
    if ep.endpoint_type == EndpointType.OLLAMA:
        headers = {}
        if ep.api_key:
            headers["Authorization"] = f"Bearer {ep.api_key}"
        try:
            async with state.http.post(
                ep.url + "/api/show", json={"name": model}, headers=headers,
                timeout=aiohttp.ClientTimeout(total=30),
            ) as resp:
                if resp.status == 200:
                    return web.json_response(await resp.json(content_type=None))
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            pass
    for m in state.registry.models_for(ep.id):
        if m.model_id == model or m.canonical_name == model:
            return web.json_response({
                "model": m.model_id,
                "canonical_name": m.canonical_name,
                "capabilities": [c.value for c in m.capabilities],
                "context_length": m.context_length,
            })
    return _json_error(404, f"model {model!r} not found on endpoint")


async def playground_chat_proxy(request: web.Request) -> web.Response:
    """POST /api/endpoints/{endpoint_id}/chat/completions — dashboard
    playground pinned-endpoint proxy (reference api/endpoints.rs:1079,
    route-gated as inference in api/mod.rs:460-479)."""
    state = request.app["state"]
    ep = state.registry.get(request.match_info["endpoint_id"])
    if ep is None:
        return _json_error(404, "endpoint not found")
    try:
        body = await request.json()
    except Exception:
        return _json_error(400, "invalid JSON body")
    body["stream"] = False  # playground uses non-stream responses
    headers = {}
    if ep.api_key:
        headers["Authorization"] = f"Bearer {ep.api_key}"
    try:
        async with state.http.post(
            ep.url + "/v1/chat/completions", json=body, headers=headers,
            timeout=aiohttp.ClientTimeout(total=state.config.inference_timeout_s),
        ) as resp:
            raw = await resp.read()
            return web.Response(
                body=raw, status=resp.status,
                content_type=(resp.headers.get("Content-Type", "application/json")
                              .split(";")[0]),
            )
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        return _json_error(502, f"endpoint unreachable: {type(e).__name__}")
