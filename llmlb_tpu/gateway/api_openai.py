"""OpenAI-compatible proxy handlers: the gateway's hot path.

Parity with reference api/openai.rs (chat_completions :155, proxy_openai_post
:761-1341, list_models :261) and api/proxy.rs (SSE passthrough with TPS
tracking :120-270): validate model + capability, resolve aliases, TPS-select an
endpoint, rewrite the payload's `model` to the engine-local name, inject
stream_options.include_usage, forward with per-endpoint timeout/auth, stream
bytes through untouched while accounting tokens, normalize upstream failures to
502, and record history/stats fire-and-forget.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid

import aiohttp
from aiohttp import web

from llmlb_tpu.gateway.app_state import AppState, record_daily_stat
from llmlb_tpu.gateway.balancer import RequestRecord, prefix_affinity_hash
from llmlb_tpu.gateway.model_names import to_canonical, to_engine_name
from llmlb_tpu.gateway.replay import (
    REPLAY_OBJECT,
    RESUMABLE_ENDPOINT_TYPES,
    ChunkSplicer,
    FrameSplitter,
    ReplayState,
    encode_chunk_frame,
    is_done_frame,
    parse_data_frame,
)
from llmlb_tpu.gateway.resilience import (
    RETRYABLE_EXCEPTIONS,
    FailoverController,
    PreStreamFailure,
    book_stream_outcome,
    retry_after_seconds,
    upstream_post,
)
from llmlb_tpu.gateway.sanitize import sanitize_request_body
from llmlb_tpu.gateway.token_accounting import (
    StreamingTokenAccumulator,
    estimate_tokens,
    extract_usage_from_response,
)
from llmlb_tpu.gateway.tracing import (
    REQUEST_ID_HEADER,
    TokenTimeline,
    observe_first_token,
)
from llmlb_tpu.gateway.types import (
    Capability,
    Endpoint,
    EndpointStatus,
    TpsApiKind,
)
from llmlb_tpu.structured import inspect_request as inspect_structured

log = logging.getLogger("llmlb_tpu.gateway.openai")

CLOUD_PREFIXES = ("openai:", "google:", "anthropic:")


def error_response(status: int, message: str,
                   err_type: str = "invalid_request_error",
                   headers: dict | None = None) -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": err_type, "code": None}},
        status=status,
        headers=headers,
    )


def parse_cloud_prefix(model: str) -> tuple[str | None, str]:
    for prefix in CLOUD_PREFIXES:
        if model.startswith(prefix):
            return prefix[:-1], model[len(prefix):]
    return None, model


def affinity_text_from_body(body: dict) -> str:
    """The prompt head used for prefix-affinity hashing: the request's
    LEADING SHARED BLOCK — explicit instructions/system when present,
    otherwise the first message (or the prompt/input string). The varying
    tail (this turn's user message) must stay out of the hash, or a short
    system prompt with per-request questions would hash every request
    differently and spray one warm prefix across the fleet. The hash
    itself caps the text at PREFIX_AFFINITY_CHARS, which also keeps long
    multi-turn histories hashing stably turn over turn. Best-effort —
    unknown shapes hash to nothing and simply skip affinity."""
    def text_of(content) -> str:
        if isinstance(content, str):
            return content
        if isinstance(content, list):  # multimodal / typed content blocks
            return "\n".join(
                b["text"] for b in content
                if isinstance(b, dict) and isinstance(b.get("text"), str)
            )
        return ""

    if isinstance(body.get("instructions"), str):  # responses API
        return body["instructions"]
    if body.get("system") is not None:  # anthropic: string or block list
        system = text_of(body["system"])
        if system:
            return system
    msgs = body.get("messages") or body.get("input")
    if isinstance(msgs, list):
        for m in msgs:
            if isinstance(m, dict):
                text = text_of(m.get("content"))
                if text:
                    return f"{m.get('role', 'user')}:{text}"
        return ""
    if isinstance(msgs, str):
        return msgs
    prompt = body.get("prompt")
    if isinstance(prompt, str):
        return prompt
    if isinstance(prompt, list) and prompt and isinstance(prompt[0], str):
        return prompt[0]
    return ""


def _tenant_id(auth: dict | None, client_ip: str | None) -> str:
    auth = auth or {}
    kid = auth.get("api_key_id")
    if kid:
        return str(kid)
    uid = auth.get("user_id")
    if uid:
        return f"user:{uid}"
    return f"ip:{client_ip or 'unknown'}"


def _key_name(auth: dict) -> str | None:
    """Human key name for per-key rate-limit overrides. Every RateLimiter
    call for a tenant must pass this — a bucket pair rebuilt after idle
    eviction with name=None would silently fall back to the global
    defaults, dropping the tenant's override."""
    if not auth.get("api_key_id"):
        return None
    actor = auth.get("actor") or ""
    return actor[4:] if actor.startswith("key:") else (actor or None)


def tenant_of(request: web.Request) -> tuple[str, str | None]:
    """(stable tenant id, human key name) for rate limiting and weighted
    fair queuing: the API key id when one authenticated, else the user id
    (dashboard JWT), else the client IP — so unauthenticated surfaces still
    bucket per source."""
    auth = request.get("auth") or {}
    return _tenant_id(auth, request.remote), _key_name(auth)


_PRIORITY_LABELS = {0: "high", 1: "normal", 2: "low"}


def priority_label(body: dict) -> str:
    """The request's priority class as a metrics label (goodput-by-priority;
    validation proper happens at the engine)."""
    p = body.get("priority")
    if isinstance(p, str) and p in ("high", "normal", "low"):
        return p
    if isinstance(p, int) and not isinstance(p, bool):
        return _PRIORITY_LABELS.get(p, "normal")
    return "normal"


def deadline_at_of(request: web.Request, state: AppState,
                   started: float) -> float | None:
    """Absolute monotonic deadline for this request: the client's
    X-Request-Deadline-Ms header, else LLMLB_REQUEST_DEADLINE_MS, else
    none. Work that cannot meet its deadline is shed before it burns a
    prefill, and the REMAINING budget propagates to the engine on the
    forwarded request (docs/scheduling.md). Raises ValueError (→ 400) on a
    malformed header."""
    raw = request.headers.get("X-Request-Deadline-Ms")
    ms: float | None = None
    if raw:
        try:
            ms = float(raw)
        except ValueError:
            raise ValueError("X-Request-Deadline-Ms must be a number")
        if ms <= 0:
            raise ValueError("X-Request-Deadline-Ms must be positive")
    if ms is None:
        default = state.config.request_deadline_ms
        ms = default if default > 0 else None
    return started + ms / 1000.0 if ms else None


def ratelimit_verdict(state: AppState, request: web.Request,
                      est_tokens: int) -> "tuple[str, int] | None":
    """Shared admission check for BOTH dialects (gateway/ratelimit.py):
    None when admitted, else (reason, retry_after_seconds) with the
    rejection already counted — each dialect shapes its own error body."""
    limiter = state.ratelimit
    if limiter is None or not limiter.enabled:
        return None
    tenant, name = tenant_of(request)
    verdict = limiter.acquire(tenant, name, est_tokens)
    if verdict.allowed:
        return None
    reason = verdict.reason or "requests"
    state.metrics.record_ratelimit_rejection(reason)
    return reason, max(1, int(verdict.retry_after_s + 0.999))


def check_ratelimit(state: AppState, request: web.Request,
                    est_tokens: int) -> "web.Response | None":
    """Per-API-key token buckets: a refused request gets 429 with
    Retry-After from the bucket's computed refill time. Returns the 429
    response (OpenAI error shape), or None when admitted."""
    refused = ratelimit_verdict(state, request, est_tokens)
    if refused is None:
        return None
    reason, retry_after = refused
    return error_response(
        429,
        f"rate limit exceeded ({reason}); retry after {retry_after}s",
        "rate_limit_error",
        headers={"Retry-After": str(retry_after)},
    )


async def select_endpoint_with_queue(
    state: AppState, model: str, capability: Capability, api_kind: TpsApiKind,
    trace=None, prefix_hash: str | None = None,
    exclude: set[str] | None = None, queue_timeout_s: float | None = None,
    tenant: str | None = None, weight: float = 1.0,
    prefill_heavy: bool | None = None,
) -> "tuple[Endpoint, str, RequestLease, object] | None":
    """Atomically TPS-select and lease an endpoint serving the model; if all
    are at the admission cap, park on the AdmissionQueue until a lease release
    wakes us or the queue timeout passes (notify-based, no polling — parity:
    balancer/mod.rs:2273-2427). `prefix_hash` steers toward the endpoint
    whose engine-side prefix KV cache is warm for this prompt. Records
    admission/queue_wait/endpoint_select spans on `trace` and feeds the
    gateway queue-wait histogram.

    `exclude` drops endpoints that already failed this request (failover
    re-selection); breaker-open endpoints are ejected inside the LoadManager
    itself. Both reduce the candidate set, never the 404 decision: a model
    whose endpoints are all excluded or breaker-open queues (and eventually
    503s with queue semantics), it does not 404. `queue_timeout_s` overrides
    the configured queue timeout (failover re-selection uses a short one).

    `prefill_heavy` engages disaggregation role steering
    (docs/disaggregation.md): True prefers prefill-capable endpoints, False
    prefers non-prefill-only ones, None (non-generation traffic) skips role
    filtering. The filter is soft — it falls back to the full candidate set
    rather than making a servable model unroutable — and prefix affinity
    composes with it (the hash steers within the filtered list)."""
    from llmlb_tpu.disagg.gateway import role_filter

    if not state.registry.find_by_model(model, capability):
        return None

    def get_endpoints() -> list[Endpoint]:
        pairs = [
            (ep, m) for ep, m in state.registry.find_by_model(model,
                                                             capability)
            if not exclude or ep.id not in exclude
        ]
        eps = [ep for ep, _ in pairs]
        if prefill_heavy is not None:
            eps = role_filter(eps, prefill_heavy=prefill_heavy,
                              models=[m for _, m in pairs])
        return eps

    if trace is not None:
        trace.begin("admission")
    admit_start = time.monotonic()
    result = await state.admission.admit(get_endpoints, model, api_kind,
                                         timeout_s=queue_timeout_s,
                                         prefix_hash=prefix_hash,
                                         tenant=tenant, weight=weight)
    if not result.admitted:
        state.metrics.record_queue_timeout(model)
        state.metrics.record_queue_wait(model, "none", result.waited_s)
        if trace is not None:
            trace.end("admission")
            trace.add_span("queue_wait", start_monotonic=admit_start,
                           duration_s=result.waited_s)
        raise QueueTimeout(result.queue_position, result.waited_s)
    state.metrics.record_queue_wait(model, result.endpoint.name,
                                    result.waited_s)
    if trace is not None:
        trace.end("admission")
        trace.add_span("queue_wait", start_monotonic=admit_start,
                       duration_s=result.waited_s)
        trace.mark("endpoint_select", endpoint=result.endpoint.name)
        trace.set_endpoint(result.endpoint)
    pairs = state.registry.find_by_model(model, capability)
    model_rec = next(
        (m for ep, m in pairs if ep.id == result.endpoint.id), None,
    )
    engine_model = model_rec.model_id if model_rec is not None else model
    # model_rec rides along so callers can read the endpoint's capability
    # advertisement (disagg role fallback) without re-scanning the registry
    # on every attempt
    return result.endpoint, engine_model, result.lease, model_rec


class QueueTimeout(Exception):
    def __init__(self, queue_position: int = 0, waited_s: float = 0.0):
        super().__init__(f"queue timeout at position {queue_position} "
                         f"after {waited_s:.1f}s")
        self.queue_position = queue_position
        self.waited_s = waited_s


class HandoffOrchestrationError(Exception):
    """Phase-2 (adoption) failure of a two-phase disaggregated handoff:
    carries WHICH endpoint failed (the adopter — its lease has already been
    failed) so the retry loop can book the failure there instead of against
    the prefill endpoint that did its half of the work."""

    def __init__(self, endpoint: Endpoint, lease, reason: str):
        super().__init__(reason)
        self.endpoint = endpoint
        self.lease = lease
        self.reason = reason


async def _handoff_upstream(
    state: AppState, fo: "FailoverController", endpoint: Endpoint, lease,
    model: str, capability: Capability, api_kind: TpsApiKind,
    payload: dict, headers: dict, deadline_at: float | None, is_stream: bool,
    engine_model: str, trace=None,
):
    """The two-phase disaggregated handoff (docs/disaggregation.md):

    1. POST the chat body to the prefill-only endpoint's /v1/handoff/prefill
       — it admits, prefills, commits the first token(s), and answers with
       the wire payload (prompt + committed ids + full sampling block).
    2. POST the payload to a decode-capable adopter's /v1/handoff — it
       replays prompt+committed (the PR 10 park/resume path, so the
       continuation is token-identical) and streams the FULL completion in
       the normal chat-completions shape.

    Returns ``(upstream_response, serving_endpoint, serving_lease,
    engine_model)`` — the caller's existing status/stream/usage handling
    applies unchanged, now accounting against the adopter. Phase-1 failures
    surface exactly like a normal upstream failure on the prefill endpoint
    (non-200 responses are returned as-is; transport errors propagate).
    Phase-2 failures raise HandoffOrchestrationError with the adopter's
    identity. When no decode-capable endpoint has a free slot the prefill
    endpoint adopts its own payload — it keeps a combined step loop under
    ``--role prefill``, so the request never strands."""
    timeout = aiohttp.ClientTimeout(
        total=state.config.inference_timeout_s, sock_connect=10
    )
    resp1 = await upstream_post(
        state, endpoint, "/v1/handoff/prefill",
        json=payload, headers=headers, timeout=timeout,
    )
    if resp1.status != 200:
        return resp1, endpoint, lease, engine_model
    try:
        body1 = await resp1.json(content_type=None)
    except RETRYABLE_EXCEPTIONS + (ValueError,):
        raise aiohttp.ClientPayloadError(
            "handoff prefill response was not JSON"
        )
    finally:
        resp1.release()
    if not isinstance(body1, dict) or body1.get("object") != "llmlb.handoff":
        raise aiohttp.ClientPayloadError(
            "handoff prefill returned an unexpected shape"
        )

    # the prefill endpoint's half is done and successful: settle its lease
    # with the committed-token usage so its TPS EMA reflects real work
    usage = body1.get("usage") or {}
    lease.complete_with_tokens(
        int(usage.get("prompt_tokens") or 0),
        int(usage.get("completion_tokens") or 0),
    )
    fo.record_success(endpoint)

    from llmlb_tpu.disagg.gateway import adopter_candidates

    adopter = None
    adopter_lease = None
    candidates = adopter_candidates(state, model, capability,
                                    exclude=fo.failed_ids)
    if candidates:
        got = state.load_manager.try_admit(candidates, model, api_kind)
        if got is not None:
            adopter, adopter_lease = got
    if adopter is None:
        # no decode pool has a free slot right now: the prefill engine
        # adopts its own payload rather than bouncing the request
        adopter = endpoint
        adopter_lease = state.load_manager.begin_request(
            endpoint, model, api_kind
        )
    state.metrics.record_handoff(
        "self" if adopter.id == endpoint.id else "adopted"
    )
    if trace is not None:
        # names the phase-2 engine so ?view=timeline knows to fetch its
        # flight record too (tracing.endpoints_touched)
        trace.mark("handoff_adopt", endpoint=adopter.name,
                   self_adopt=adopter.id == endpoint.id)

    adopt_headers = {"Content-Type": "application/json"}
    if adopter.api_key:
        adopt_headers["Authorization"] = f"Bearer {adopter.api_key}"
    rid = headers.get(REQUEST_ID_HEADER)
    if rid:
        adopt_headers[REQUEST_ID_HEADER] = rid
    if deadline_at is not None:
        # the wire carries the ORIGINAL (partly spent) deadline; the header
        # overrides it with what actually remains
        remaining_ms = (deadline_at - time.monotonic()) * 1000.0
        adopt_headers["X-Request-Deadline-Ms"] = str(
            max(1, int(remaining_ms))
        )
    pairs = state.registry.find_by_model(model, capability)
    adopt_model = next(
        (m.model_id for ep2, m in pairs if ep2.id == adopter.id),
        engine_model,
    )
    try:
        resp2 = await upstream_post(
            state, adopter, "/v1/handoff",
            json={
                "handoff": body1.get("handoff"),
                "stream": is_stream,
                "model": adopt_model,
                "tool_name": body1.get("tool_name"),
                # durable streams: the adopted stream carries replay frames
                # too, so a cut mid-continuation can resume elsewhere
                "llmlb_replay": bool(payload.get("llmlb_replay")),
            },
            headers=adopt_headers, timeout=timeout,
        )
    except RETRYABLE_EXCEPTIONS as e:
        adopter_lease.fail()
        raise HandoffOrchestrationError(
            adopter, adopter_lease,
            "adopt_timeout" if isinstance(e, asyncio.TimeoutError)
            else "adopt_connect_error",
        )
    return resp2, adopter, adopter_lease, adopt_model


def _record(
    state: AppState, *, endpoint: Endpoint | None, model: str,
    api_kind: TpsApiKind, path: str, status: int, started: float,
    prompt_tokens: int = 0, completion_tokens: int = 0,
    client_ip: str | None = None, auth: dict | None = None,
    error: str | None = None, stream: bool = False,
    request_body: str | None = None,
) -> None:
    duration_ms = (time.monotonic() - started) * 1000.0
    eid = endpoint.id if endpoint else None
    state.metrics.record_e2e(
        model, endpoint.name if endpoint else "none", duration_ms / 1000.0
    )
    state.load_manager.record_request(RequestRecord(
        ts=time.time(), endpoint_id=eid or "", model=model, api_kind=api_kind,
        status_code=status, duration_ms=duration_ms,
        prompt_tokens=prompt_tokens, completion_tokens=completion_tokens,
    ))
    auth = auth or {}
    state.history.add_history(
        (uuid.uuid4().hex, time.time(), eid,
         endpoint.name if endpoint else None, model, api_kind.value, path,
         status, duration_ms, prompt_tokens, completion_tokens, client_ip,
         auth.get("api_key_id"), auth.get("user_id"), int(stream), error,
         request_body),
    )
    if endpoint is not None:
        record_daily_stat(
            state, endpoint.id, model, api_kind,
            error=status >= 400, prompt_tokens=prompt_tokens,
            completion_tokens=completion_tokens, duration_ms=duration_ms,
        )
    if (state.ratelimit is not None and state.ratelimit.enabled
            and completion_tokens > 0):
        # post-paid token debit: the admission check could only estimate the
        # prompt; the completion throttles this tenant's NEXT request
        state.ratelimit.charge_tokens(
            _tenant_id(auth, client_ip), completion_tokens,
            name=_key_name(auth),
        )


async def proxy_openai_post(
    request: web.Request,
    path: str,
    api_kind: TpsApiKind,
    capability: Capability = Capability.CHAT_COMPLETION,
    prompt_text_fn=None,
) -> web.StreamResponse:
    """The generic select→rewrite→forward→account pipeline for /v1/* POSTs."""
    state: AppState = request.app["state"]
    started = time.monotonic()
    trace = request.get("trace")
    if trace is not None:
        trace.end("auth")
    try:
        body = await request.json()
    except Exception:
        return error_response(400, "invalid JSON body")
    if not isinstance(body, dict):
        return error_response(400, "body must be a JSON object")
    model = body.get("model")
    if not model or not isinstance(model, str):
        return error_response(400, "'model' is required")

    provider, bare_model = parse_cloud_prefix(model)
    if provider is not None:
        from llmlb_tpu.gateway.api_cloud import proxy_cloud_request

        return await proxy_cloud_request(
            request, provider, bare_model, body, path
        )

    canonical = to_canonical(model)
    if trace is not None:
        trace.model = canonical
    # Multi-LoRA routing (docs/lora.md): a `lora` field or `model:adapter`
    # suffix steers to endpoints where the adapter is already HOT, falls
    # back to any lora-capable endpoint (triggering a hot-load), and 400s
    # naming the field when the fleet cannot serve the adapter — before a
    # blind proxy could turn it into an engine-side error. Malformed
    # values 400 here with the same message the engine would produce
    # (shared validator, llmlb_tpu/lora/api.py).
    lora_route = None
    if capability == Capability.CHAT_COMPLETION:
        from llmlb_tpu.lora.gateway import lora_route_for

        try:
            lora_route = lora_route_for(state, body)
        except ValueError as e:
            state.metrics.record_lora_route("rejected")
            return error_response(400, str(e))
        if lora_route is not None:
            canonical = lora_route.canonical
            state.metrics.record_lora_route(lora_route.kind)
            if trace is not None:
                trace.model = canonical
    # Affinity only for generation traffic: embeddings (and other non-chat
    # capabilities) never touch the engine's prefix KV cache, and hashing
    # their inputs would churn the shared affinity map and pin their routing
    # for zero benefit. The adapter id folds into the hash — under LoRA the
    # prompt KV depends on the adapter, so two adapters sharing a system
    # prompt must pin to caches independently (docs/lora.md).
    prefix_hash = (
        prefix_affinity_hash(
            lora_route.base_canonical if lora_route is not None
            else canonical,
            affinity_text_from_body(body),
            lora=lora_route.adapter if lora_route is not None else None,
        )
        if capability == Capability.CHAT_COMPLETION else None
    )

    # Structured outputs (chat dialect only — /v1/responses spells these
    # fields differently and passes through untouched): validate
    # response_format / tool_choice HERE so malformed shapes and unsupported
    # JSON-Schema features 400 with the feature named instead of being
    # proxied blind, and steer compilable requests to endpoints advertising
    # the structured_outputs capability (tpu:// engines; an endpoint without
    # it would silently ignore the constraint). Cloud-prefixed models never
    # reach this point — they passed through above untouched.
    if path == "/v1/chat/completions":
        try:
            structured = inspect_structured(body)
        except ValueError as e:
            state.metrics.record_structured_rejected()
            return error_response(400, str(e))
        if structured is not None:
            state.metrics.record_structured_request(structured.kind)
            if state.registry.find_by_model(
                canonical, Capability.STRUCTURED_OUTPUTS
            ):
                capability = Capability.STRUCTURED_OUTPUTS
    if lora_route is not None and lora_route.capability is not None:
        # cold-load route: only endpoints WITH an adapter store are
        # eligible (a capability-blind pick would 400 at the engine).
        # Wins over structured steering — tpu lora engines advertise
        # structured_outputs too, so nothing is lost on a pure-TPU fleet.
        capability = lora_route.capability

    client_ip = request.remote
    auth = request.get("auth")
    prompt_text = prompt_text_fn(body) if prompt_text_fn else ""
    # stored for the dashboard request-detail view, inline media redacted
    # (the reference's sanitization contract, implemented)
    stored_body = sanitize_request_body(body)
    is_stream = bool(body.get("stream"))

    # ---- overload protection (docs/scheduling.md) ------------------------
    # Per-key token buckets first: a greedy tenant's excess load bounces
    # with 429 + honest Retry-After before it can queue in front of anyone.
    # Then the request deadline: the admission wait is capped at the
    # remaining budget, and expiry sheds the request (504) before it burns
    # a prefill — the remaining budget rides to the engine on the header.
    try:
        deadline_at = deadline_at_of(request, state, started)
    except ValueError as e:
        return error_response(400, str(e))
    refused = check_ratelimit(state, request, estimate_tokens(prompt_text))
    if refused is not None:
        return refused
    tenant, tenant_name = tenant_of(request)
    wfq_weight = state.admission.weight_for(tenant_name)
    prio = priority_label(body)

    # Disaggregation role steering (docs/disaggregation.md): long-prompt,
    # cold-prefix requests prefer prefill-capable endpoints; everything
    # else steers away from prefill-only ones, keeping their slots free
    # for prefill bursts. None for non-generation capabilities —
    # embeddings never touch the prefill/decode split.
    from llmlb_tpu.disagg.gateway import endpoint_role, is_prefill_heavy

    prefill_heavy: bool | None = None
    if capability in (Capability.CHAT_COMPLETION,
                      Capability.STRUCTURED_OUTPUTS):
        prefill_heavy = is_prefill_heavy(
            state, canonical, estimate_tokens(prompt_text), prefix_hash
        )

    # Failover loop: each attempt re-selects (excluding endpoints that
    # already failed this request), and a failed attempt retries on another
    # endpoint with backoff while the attempt cap and global retry budget
    # allow. Streams are retryable only until the first byte reaches the
    # client (_forward_stream pulls the first upstream chunk before
    # preparing the client response for exactly this reason).
    fo = FailoverController(
        state, canonical, trace=trace,
        candidates_fn=lambda: [
            ep for ep, _ in state.registry.find_by_model(canonical, capability)
        ],
    )
    while True:
        queue_timeout = (fo.config.failover_queue_timeout_s
                         if fo.failed_ids else None)
        if deadline_at is not None:
            remaining = deadline_at - time.monotonic()
            if remaining <= 0:
                state.metrics.record_deadline_shed(canonical)
                return error_response(
                    504, "request deadline exceeded before an endpoint "
                    "was available", "timeout_error",
                )
            cap = (queue_timeout if queue_timeout is not None
                   else state.load_manager.queue_config.queue_timeout_s)
            queue_timeout = min(cap, remaining)
        try:
            selection = await select_endpoint_with_queue(
                state, canonical, capability, api_kind, trace=trace,
                prefix_hash=prefix_hash, exclude=fo.failed_ids,
                queue_timeout_s=queue_timeout,
                tenant=tenant, weight=wfq_weight,
                prefill_heavy=prefill_heavy,
            )
        except QueueTimeout as qt:
            if deadline_at is not None and time.monotonic() >= deadline_at:
                state.metrics.record_deadline_shed(canonical)
                return error_response(
                    504, "request deadline exceeded while queued for an "
                    "endpoint", "timeout_error",
                )
            return error_response(
                503,
                f"all endpoints busy; queue timeout exceeded "
                f"(position {qt.queue_position})",
                "server_error",
                headers={"Retry-After": str(
                    retry_after_seconds(state, canonical, capability)
                )},
            )
        if selection is None:
            return error_response(
                404, f"model {model!r} is not available on any online endpoint",
                "invalid_request_error",
            )
        endpoint, engine_model, lease, chosen_model = selection

        payload = dict(body)
        # registry knows the engine-local name; fall back to the static alias
        # table
        payload["model"] = engine_model or to_engine_name(
            canonical, endpoint.endpoint_type.value
        )
        if lora_route is not None:
            # the engine must see the adapter whichever route won: its own
            # hot `base:adapter` entry, or `base:adapter` synthesized so a
            # load-route engine hot-loads at admission; the explicit field
            # rides along (both dialects accept either — they must agree)
            from llmlb_tpu.lora.gateway import forward_model_name

            payload["model"] = forward_model_name(
                lora_route, engine_model,
                to_engine_name(lora_route.base_canonical,
                               endpoint.endpoint_type.value),
            )
            payload["lora"] = lora_route.adapter
        if is_stream:
            # usage in the final chunk feeds the TPS tracker
            # (api/openai.rs:981-992)
            opts = dict(payload.get("stream_options") or {})
            opts["include_usage"] = True
            payload["stream_options"] = opts

        # Durable streams (gateway/replay.py, docs/resilience.md): arm
        # tpu:// engine streams with gateway-internal replay frames so a
        # mid-stream engine death becomes a token-identical resume on
        # another engine instead of a terminal error frame.
        arm_replay = (
            is_stream
            and path == "/v1/chat/completions"
            and state.config.stream_resume
            and state.config.stream_resume_attempts > 0
            and endpoint.endpoint_type.value in RESUMABLE_ENDPOINT_TYPES
        )
        if arm_replay:
            payload["llmlb_replay"] = True
        else:
            # a client-supplied flag must not reach the engine unarmed: the
            # byte-for-byte passthrough would forward the gateway-internal
            # replay frames straight to the client
            payload.pop("llmlb_replay", None)

        headers = {"Content-Type": "application/json"}
        if endpoint.api_key:
            headers["Authorization"] = f"Bearer {endpoint.api_key}"
        rid = request.get("request_id")
        if rid:
            # the engine scheduler adopts this id, joining the gateway trace
            headers[REQUEST_ID_HEADER] = rid
        if deadline_at is not None:
            remaining_ms = (deadline_at - time.monotonic()) * 1000.0
            if remaining_ms <= 0:
                lease.fail()
                state.metrics.record_deadline_shed(canonical)
                return error_response(
                    504, "request deadline exceeded before forwarding",
                    "timeout_error",
                )
            # the engine sheds the request if it is still queued there when
            # this remaining budget runs out (docs/scheduling.md)
            headers["X-Request-Deadline-Ms"] = str(max(1, int(remaining_ms)))

        if trace is not None:
            trace.begin("proxy")
        try:
            if (path == "/v1/chat/completions"
                    and endpoint_role(endpoint, chosen_model) == "prefill"):
                # Two-phase disaggregated handoff: the selected endpoint
                # only prefills — it commits the first token(s) and hands
                # the stream to a decode-capable adopter over the wire
                # (docs/disaggregation.md). Accounting moves with the
                # stream: the prefill lease completes at the payload, the
                # adopter's lease rides the continuation.
                upstream, endpoint, lease, engine_model = (
                    await _handoff_upstream(
                        state, fo, endpoint, lease, canonical, capability,
                        api_kind, payload, headers, deadline_at, is_stream,
                        engine_model, trace=trace,
                    )
                )
            else:
                upstream = await upstream_post(
                    state, endpoint, path,
                    json=payload,
                    headers=headers,
                    timeout=aiohttp.ClientTimeout(
                        total=state.config.inference_timeout_s,
                        sock_connect=10
                    ),
                )
        except HandoffOrchestrationError as e:
            # phase-2 (adoption) failure: the failure books against the
            # ADOPTER (its lease already failed inside the orchestrator);
            # the retry loop re-selects from scratch, excluding it.
            fo.record_failure(e.endpoint, e.lease, e.reason)
            if trace is not None:
                trace.end("proxy")
            if await fo.should_retry(e.reason):
                continue
            _record(state, endpoint=e.endpoint, model=canonical,
                    api_kind=api_kind, path=path, status=502, started=started,
                    client_ip=client_ip, auth=auth, error=e.reason,
                    request_body=stored_body)
            return error_response(
                502, f"handoff adoption failed: {e.reason}", "server_error",
            )
        except RETRYABLE_EXCEPTIONS as e:
            reason = ("timeout" if isinstance(e, asyncio.TimeoutError)
                      else "connect_error")
            fo.record_failure(endpoint, lease, reason)
            if trace is not None:
                trace.end("proxy")
            if await fo.should_retry(reason):
                continue
            _record(state, endpoint=endpoint, model=canonical,
                    api_kind=api_kind, path=path, status=502, started=started,
                    client_ip=client_ip, auth=auth,
                    error=f"{type(e).__name__}: {e}",
                    request_body=stored_body)
            return error_response(
                502, f"upstream endpoint unreachable: {type(e).__name__}",
                "server_error",
            )

        if upstream.status != 200:
            # normalize non-2xx upstream to 502 (api/openai.rs:1180)
            status_code = upstream.status
            try:
                detail = (await upstream.read())[:2048].decode(errors="replace")
            except RETRYABLE_EXCEPTIONS:
                detail = "<error body unreadable>"
            upstream.release()
            if trace is not None:
                trace.end("proxy")
            if status_code in fo.config.retryable_statuses:
                reason = f"http_{status_code}"
                fo.record_failure(endpoint, lease, reason)
                if await fo.should_retry(reason):
                    continue
            else:
                # a 4xx the endpoint rejected is not endpoint sickness; it
                # must not feed the breaker (or burn failover attempts) —
                # but it IS liveness evidence, which resolves a half-open
                # probe instead of leaking its slot
                lease.fail()
                fo.record_alive(endpoint)
            _record(state, endpoint=endpoint, model=canonical,
                    api_kind=api_kind, path=path, status=502, started=started,
                    client_ip=client_ip, auth=auth,
                    error=f"upstream HTTP {status_code}: {detail}",
                    request_body=stored_body)
            return error_response(
                502, f"upstream returned {status_code}: {detail}",
                "server_error",
            )

        content_type = upstream.headers.get("Content-Type", "")
        if is_stream and "text/event-stream" in content_type:
            replay = None
            if arm_replay:
                replay = ReplayState(
                    payload, capability=capability, api_kind=api_kind,
                    tenant=tenant, weight=wfq_weight,
                    deadline_at=deadline_at, rid=rid,
                    prefix_hash=prefix_hash,
                    max_attempts=state.config.stream_resume_attempts,
                )
                replay.origin = endpoint  # kv-export source if cut here
            result = await _forward_stream(
                request, state, upstream, endpoint, canonical, api_kind, path,
                started, lease, prompt_text, client_ip, auth, stored_body,
                trace=trace, failover=fo, priority=prio, replay=replay,
            )
            if isinstance(result, PreStreamFailure):
                fo.record_failure(endpoint, lease, "stream_pre_byte")
                if trace is not None:
                    trace.end("proxy")
                if await fo.should_retry("stream_pre_byte"):
                    continue
                _record(state, endpoint=endpoint, model=canonical,
                        api_kind=api_kind, path=path, status=502,
                        started=started, client_ip=client_ip, auth=auth,
                        error=result.error, stream=True,
                        request_body=stored_body)
                return error_response(
                    502,
                    f"upstream stream failed before first byte: "
                    f"{result.error}",
                    "server_error",
                )
            return result

        observe_first_token(state, trace, canonical, endpoint.name, started)
        try:
            raw = await upstream.read()
        except RETRYABLE_EXCEPTIONS as e:
            # endpoint died mid-body: nothing reached the client, so this
            # fails over like a connect failure (and must book an outcome,
            # or a half-open probe slot would wedge)
            upstream.release()
            fo.record_failure(endpoint, lease, "read_error")
            if trace is not None:
                trace.end("proxy")
            if await fo.should_retry("read_error"):
                continue
            _record(state, endpoint=endpoint, model=canonical,
                    api_kind=api_kind, path=path, status=502, started=started,
                    client_ip=client_ip, auth=auth,
                    error=f"response read failed: {type(e).__name__}: {e}",
                    request_body=stored_body)
            return error_response(
                502, f"upstream response read failed: {type(e).__name__}",
                "server_error",
            )
        upstream.release()
        if trace is not None:
            trace.end("proxy")
        try:
            parsed = json.loads(raw)
        except ValueError:
            parsed = None
        usage = (extract_usage_from_response(parsed)
                 if isinstance(parsed, dict) else None)
        if usage is None:
            completion_text = _extract_completion_text(parsed) if parsed else ""
            usage = (estimate_tokens(prompt_text),
                     estimate_tokens(completion_text))
        lease.complete_with_tokens(*usage)
        fo.record_success(endpoint)
        _record(state, endpoint=endpoint, model=canonical, api_kind=api_kind,
                path=path, status=200, started=started,
                prompt_tokens=usage[0], completion_tokens=usage[1],
                client_ip=client_ip, auth=auth, request_body=stored_body)
        # non-streaming goodput: the whole response IS the first token, so
        # only the TTFT target applies (generation APIs only — embeddings
        # and media have no latency SLO here)
        if api_kind in (TpsApiKind.CHAT, TpsApiKind.COMPLETION,
                        TpsApiKind.RESPONSES):
            state.metrics.record_slo(canonical,
                                     time.monotonic() - started, None,
                                     priority=prio)
        state.events.publish("MetricsUpdated", {"endpoint_id": endpoint.id})
        return web.Response(
            body=raw, status=200,
            content_type="application/json",
        )


def sse_error_frame(message: str, code: str = "stream_interrupted") -> bytes:
    """Final SSE `event: error` frame written before closing a cut stream,
    so clients can distinguish an interrupted stream from a completed one
    (a bare close is indistinguishable from normal EOF to most SSE
    consumers). Leads with a blank line: the passthrough is byte-for-byte,
    so the cut may land mid-line — the terminator ends any dangling partial
    event, otherwise `event: error` would be absorbed into it."""
    payload = {"error": {"message": message, "type": "server_error",
                         "code": code}}
    return (
        f"\n\nevent: error\ndata: "
        f"{json.dumps(payload, separators=(',', ':'))}\n\n"
    ).encode()


class StreamWriteTimeout(Exception):
    """A client write stalled past LLMLB_STREAM_WRITE_TIMEOUT: the reader
    stopped draining the SSE stream (slow-loris). The pump aborts — which
    releases the upstream response and thereby cancels the engine slot —
    instead of holding a decode slot hostage for the inference timeout."""


class StreamWriteGuard:
    """Slow-loris protection for the per-chunk SSE hot loop, shared by the
    OpenAI passthrough and the Anthropic transform (docs/scheduling.md).

    ONE watchdog timer per STREAM instead of an asyncio.wait_for per chunk:
    the guarded write costs two timestamp assignments on the fast path — no
    Task/TimerHandle allocation per chunk, so the loop PR 9 reduced to one
    C scan + one socket write stays that way. The watchdog wakes every
    timeout/2; a write pending past the timeout cancels the pump task and
    `write` converts that cancellation into StreamWriteTimeout (worst-case
    detection latency 1.5x the configured timeout). A cancellation that
    lands after the write completed surfaces at the pump's next await —
    pumps must check `fired` in their CancelledError handler.

    The stalled_reader fault rule (gateway/faults.py) simulates a
    non-draining client as a deterministic sleep inside the guarded write,
    so the timeout is testable without real sockets."""

    __slots__ = ("_resp", "_timeout", "_stall_rules", "_loop", "_task",
                 "_handle", "_pending_since", "fired", "_sent")

    def __init__(self, resp, timeout: float, stall_rules=()):
        self._resp = resp
        self._timeout = timeout
        # Every fired rule applies (like upstream_post), each stalling once
        # when the stream passes its after_bytes threshold.
        self._stall_rules = sorted(stall_rules, key=lambda r: r.after_bytes)
        self._loop = asyncio.get_running_loop()
        self._task = asyncio.current_task()
        self._pending_since: float | None = None
        self.fired = False
        self._sent = 0
        self._handle = (self._loop.call_later(timeout / 2, self._check)
                        if timeout > 0 else None)

    def active(self) -> bool:
        """False when neither timeout nor fault applies — callers then keep
        the raw resp.write bound method in the hot loop."""
        return self._timeout > 0 or bool(self._stall_rules)

    def _check(self) -> None:
        started = self._pending_since
        if (started is not None
                and self._loop.time() - started > self._timeout):
            self.fired = True
            self._handle = None
            self._task.cancel()
            return
        self._handle = self._loop.call_later(self._timeout / 2, self._check)

    def close(self) -> None:
        """Disarm the watchdog (call from the pump's finally)."""
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None

    def timeout_error(self) -> StreamWriteTimeout:
        return StreamWriteTimeout(
            f"client stopped reading for {self._timeout:.0f}s"
        )

    async def write(self, data: bytes) -> None:
        self._pending_since = self._loop.time()
        try:
            while (self._stall_rules
                   and self._sent >= self._stall_rules[0].after_bytes):
                rule = self._stall_rules.pop(0)
                await asyncio.sleep(rule.latency_ms / 1000.0)
            await self._resp.write(data)
        except asyncio.CancelledError:
            if self.fired:
                raise self.timeout_error() from None
            raise
        finally:
            self._pending_since = None
        self._sent += len(data)


def stream_write_guard(state: AppState, resp, endpoint,
                       path: str) -> StreamWriteGuard:
    """Build the guard for one stream: configured timeout + every matching
    stalled_reader fault rule (each counted as injected and applied)."""
    stall_rules = []
    if state.faults is not None:
        for rule in state.faults.decide(endpoint, path,
                                        kinds=("stalled_reader",)):
            state.metrics.record_fault_injected(rule.kind)
            stall_rules.append(rule)
    return StreamWriteGuard(resp, state.config.stream_write_timeout_s,
                            stall_rules)


async def _fetch_kv_export(state: AppState, replay: ReplayState,
                           park: bool = False):
    """Collect the cut stream's serialized KV pages from its origin engine
    (POST /v1/kv/export, docs/kv-cache.md) so the resume moves bytes
    instead of re-prefilling. Strictly best-effort with a short clock: a
    SIGKILL'd origin refuses the connect, an old build 404s, a finished
    drain holds nothing — every such case returns None fast and the
    token-identical replay path proceeds exactly as before.

    ``park=True`` is the proactive-migration variant (gateway/rebalance.py):
    the origin is LIVE, so the engine first parks the decoding slot (KV
    spilled, request requeued) and then serves the export. A refusal leaves
    the origin stream untouched — the parked copy re-inserts and keeps
    streaming on the same connection."""
    origin = replay.origin
    if origin is None or not replay.rid or not replay.committed:
        return None
    headers = {"Content-Type": "application/json"}
    if origin.api_key:
        headers["Authorization"] = f"Bearer {origin.api_key}"
    body_json = {"request_id": replay.rid}
    if park:
        body_json["park"] = True
    timeout = aiohttp.ClientTimeout(total=5, sock_connect=2)
    try:
        resp = await upstream_post(
            state, origin, "/v1/kv/export",
            json=body_json,
            headers=headers, timeout=timeout,
        )
    except Exception:
        return None
    try:
        if resp.status != 200:
            return None
        body = await resp.json()
    except Exception:
        return None
    finally:
        resp.release()
    pages = body.get("kv_pages") if isinstance(body, dict) else None
    return pages if isinstance(pages, dict) else None


async def _acquire_resume(
    state: AppState, fo: FailoverController, replay: ReplayState, model: str,
    trace=None,
):
    """Open a token-identical continuation stream for a cut armed stream
    (docs/resilience.md "mid-stream recovery"): re-run endpoint selection
    excluding every endpoint that already failed this request, POST the
    ORIGINAL chat body + the committed token ids to the new engine's
    /v1/resume, and pull its first chunk. Returns ``(upstream, endpoint,
    iterator, first_chunk)`` on success, or None when the gateway must give
    up and emit the terminal error frame instead — attempts capped by
    LLMLB_STREAM_RESUME_ATTEMPTS, each attempt spending the shared retry
    budget, each outcome counted in stream_resumes_total{outcome}."""
    timeout = aiohttp.ClientTimeout(
        total=state.config.inference_timeout_s, sock_connect=10
    )
    # one-shot pickup from the (possibly draining) origin; the payload is
    # reused across resume-attempt retries — the origin no longer holds it
    kv_pages = await _fetch_kv_export(state, replay)
    while True:
        if replay.attempts >= replay.max_attempts:
            state.metrics.record_stream_resume("exhausted")
            return None
        if (replay.deadline_at is not None
                and time.monotonic() >= replay.deadline_at):
            state.metrics.record_stream_resume("exhausted")
            return None
        try:
            selection = await select_endpoint_with_queue(
                state, model, replay.capability, replay.api_kind, trace=trace,
                prefix_hash=replay.prefix_hash, exclude=fo.failed_ids,
                queue_timeout_s=fo.config.failover_queue_timeout_s,
                tenant=replay.tenant, weight=replay.weight,
                prefill_heavy=False,
            )
        except QueueTimeout:
            state.metrics.record_stream_resume("no_endpoint")
            return None
        if selection is None:
            state.metrics.record_stream_resume("no_endpoint")
            return None
        endpoint, engine_model, lease, _rec = selection
        if endpoint.endpoint_type.value not in RESUMABLE_ENDPOINT_TYPES:
            # a live candidate that simply does not speak /v1/resume: not a
            # failure (no breaker, no interruption counters) — just not a
            # resume target for this stream
            lease.fail()
            fo.failed_ids.add(endpoint.id)
            continue
        resilience = state.resilience
        if resilience is not None and not resilience.budget.try_spend():
            lease.fail()
            state.metrics.record_retry_budget_exhausted()
            state.metrics.record_stream_resume("budget")
            return None
        replay.attempts += 1
        headers = {"Content-Type": "application/json"}
        if endpoint.api_key:
            headers["Authorization"] = f"Bearer {endpoint.api_key}"
        if replay.rid:
            headers[REQUEST_ID_HEADER] = replay.rid
        if replay.deadline_at is not None:
            remaining_ms = (replay.deadline_at - time.monotonic()) * 1000.0
            headers["X-Request-Deadline-Ms"] = str(max(1, int(remaining_ms)))
        try:
            resumed = await upstream_post(
                state, endpoint, "/v1/resume",
                json=replay.resume_body(engine_model, kv_pages=kv_pages),
                headers=headers, timeout=timeout,
            )
        except RETRYABLE_EXCEPTIONS as e:
            reason = ("timeout" if isinstance(e, asyncio.TimeoutError)
                      else "connect_error")
            fo.record_failure(endpoint, lease, reason)
            continue
        if resumed.status != 200:
            status_code = resumed.status
            resumed.release()
            if status_code in fo.config.retryable_statuses:
                fo.record_failure(endpoint, lease, f"http_{status_code}")
                continue
            # the engine answered (e.g. an old build 404ing /v1/resume):
            # alive, but this stream cannot resume there
            lease.fail()
            fo.record_alive(endpoint)
            state.metrics.record_stream_resume("failed")
            return None
        iterator = resumed.content.iter_any()
        try:
            first_chunk = await iterator.__anext__()
        except StopAsyncIteration:
            resumed.release()
            fo.record_failure(endpoint, lease, "stream_pre_byte")
            continue
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                ConnectionResetError):
            resumed.release()
            fo.record_failure(endpoint, lease, "stream_pre_byte")
            continue
        lease.complete()  # stream accepted; active slot released, as ever
        replay.origin = endpoint  # a second cut asks THIS engine for pages
        replay.resumes += 1
        state.metrics.record_stream_resume("success")
        state.metrics.record_stream_resumed_tokens(model,
                                                   len(replay.committed))
        if trace is not None:
            trace.mark("stream_resume", endpoint=endpoint.name,
                       committed_tokens=len(replay.committed))
        return resumed, endpoint, iterator, first_chunk


async def _migrate_stream(state: AppState, replay: ReplayState,
                          target_id: str, model: str):
    """Planner-directed live migration (gateway/rebalance.py): park the
    stream on its healthy origin (POST /v1/kv/export {"park": true}),
    collect the KV snapshot, and open a token-identical continuation on
    the rebalancer's pinned target — the exact /v1/resume machinery the
    reactive cut path uses, minus every failure-side effect. Returns
    ``((upstream, endpoint, iterator, first_chunk), "success")`` or
    ``(None, "aborted"|"refused")``: "aborted" means the migration never
    touched the origin's stream (ineligible target, origin would not
    park), "refused" means the target rejected the adopt — in which case
    the origin's parked copy re-inserts and keeps streaming on the SAME
    connection, so either failure is client-invisible. Unlike
    _acquire_resume this books no endpoint failures, spends no retry
    budget and counts nothing in stream_resumes: both engines are
    healthy, and a refusal is planner feedback, not sickness."""
    origin = replay.origin
    target = state.registry.get(target_id)
    if (target is None or origin is None or target.id == origin.id
            or target.status != EndpointStatus.ONLINE
            or target.endpoint_type.value not in RESUMABLE_ENDPOINT_TYPES):
        return None, "aborted"
    engine_model = None
    for m in state.registry.models_for(target.id):
        if model in (m.canonical_name, m.model_id):
            engine_model = m.model_id
            break
    if engine_model is None:
        return None, "aborted"  # target does not serve this model
    if (replay.deadline_at is not None
            and replay.deadline_at - time.monotonic() <= 0):
        return None, "aborted"
    pages = await _fetch_kv_export(state, replay, park=True)
    if pages is None:
        return None, "aborted"
    headers = {"Content-Type": "application/json"}
    if target.api_key:
        headers["Authorization"] = f"Bearer {target.api_key}"
    if replay.rid:
        headers[REQUEST_ID_HEADER] = replay.rid
    if replay.deadline_at is not None:
        remaining_ms = (replay.deadline_at - time.monotonic()) * 1000.0
        headers["X-Request-Deadline-Ms"] = str(max(1, int(remaining_ms)))
    timeout = aiohttp.ClientTimeout(
        total=state.config.inference_timeout_s, sock_connect=10
    )
    try:
        resumed = await upstream_post(
            state, target, "/v1/resume",
            json=replay.resume_body(engine_model, kv_pages=pages),
            headers=headers, timeout=timeout,
        )
    except RETRYABLE_EXCEPTIONS:
        return None, "refused"
    if resumed.status != 200:
        resumed.release()
        return None, "refused"
    iterator = resumed.content.iter_any()
    try:
        first_chunk = await iterator.__anext__()
    except StopAsyncIteration:
        resumed.release()
        return None, "refused"
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
            ConnectionResetError):
        resumed.release()
        return None, "refused"
    replay.origin = target  # a later cut asks THIS engine for pages
    return (resumed, target, iterator, first_chunk), "success"


def _replay_frame_out(replay: ReplayState, splicer: "ChunkSplicer | None",
                      frame: bytes) -> bytes | None:
    """One complete upstream SSE frame → the bytes to forward to the client
    (None = gateway-internal or fully duplicated, drop it). Before the first
    resume (`splicer` is None) client frames pass through byte-verbatim and
    are only ACCOUNTED; after a resume every chunk is spliced."""
    obj = parse_data_frame(frame)
    if obj is None:
        return frame  # [DONE], comments, blank keep-alives: forward as-is
    if "error" in obj:
        # engine-side terminal error frames pass through untouched in both
        # modes — they are client-facing, not duplicated content
        if splicer is not None and obj.get("object") != REPLAY_OBJECT:
            return frame
    if splicer is None:
        return frame if replay.note_openai_chunk(obj) else None
    if obj.get("object") == REPLAY_OBJECT:
        replay.note_openai_chunk(obj)  # extends the committed ledger only
        return None
    spliced = splicer.splice(obj)
    return encode_chunk_frame(spliced) if spliced is not None else None


async def _forward_stream(
    request, state: AppState, upstream, endpoint, model, api_kind, path,
    started, lease, prompt_text, client_ip, auth, stored_body=None,
    trace=None, failover: FailoverController | None = None,
    priority: str = "normal", replay: ReplayState | None = None,
) -> "web.StreamResponse | PreStreamFailure":
    """Byte-for-byte SSE passthrough with token accounting (api/proxy.rs:120).

    The first upstream chunk is pulled BEFORE the client response is
    prepared: a failure there returns PreStreamFailure (retryable by the
    caller, nothing was sent). After the first byte the stream is committed —
    an upstream cut emits a final `event: error` frame, counts against the
    endpoint (breaker + balancer per-endpoint stats), and records 502; a
    client disconnect counts against nobody. Every client write runs under
    LLMLB_STREAM_WRITE_TIMEOUT (docs/scheduling.md): a reader that stops
    draining aborts the stream (freeing the engine slot) instead of pinning
    it until the inference timeout."""
    iterator = upstream.content.iter_any()
    first_chunk: bytes | None = None
    try:
        first_chunk = await iterator.__anext__()
    except StopAsyncIteration:
        first_chunk = None  # empty-but-clean stream: forward the EOF as-is
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
            ConnectionResetError) as e:
        upstream.release()
        return PreStreamFailure(f"{type(e).__name__}: {e}")

    headers = {
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
    }
    rid = request.get("request_id")
    if rid:  # set pre-prepare; the middleware cannot amend a sent stream
        headers[REQUEST_ID_HEADER] = rid
    resp = web.StreamResponse(status=200, headers=headers)
    await resp.prepare(request)
    lease.complete()  # endpoint accepted the stream; active slot released
    acc = StreamingTokenAccumulator()
    # Sampled token timeline for the trace: one mark per SSE data chunk
    # reaching the client, so /api/traces/<id> shows WHERE a slow stream
    # stalled. ttft_s additionally feeds the SLO goodput ledger.
    timeline = (TokenTimeline()
                if trace is not None and state.traces.sample_timeline()
                else None)
    # Slow-loris protection (StreamWriteGuard): one watchdog per stream, a
    # non-draining client aborts the pump instead of pinning the slot.
    guard = stream_write_guard(state, resp, endpoint, path)
    ttft_s: float | None = None
    status = 200
    error = None
    upstream_failed = False
    # Durable streams: once a cut's outcome has been booked in-line (victim
    # breaker + interruption counters at the moment of the cut), the finally
    # block must not book anything for it again.
    outcome_booked = False
    # Rebalancer visibility (gateway/rebalance.py): armed streams register
    # in the worker's StreamDirectory so migration directives can find
    # them; None when LLMLB_REBALANCE=0 or the stream is not resumable.
    handle = None
    try:
        if first_chunk is not None:
            observe_first_token(state, trace, model, endpoint.name,
                                started, streaming=True)
            ttft_s = time.monotonic() - started
            feed = acc.feed
            # Per-chunk hot loop: with the native scanner built, each chunk
            # costs one C scan (frame split + usage extract) and one socket
            # write — bound methods hoisted so the loop does no attribute
            # walks, and the timeline branch is a single identity test
            # unless this request was sampled for a token timeline. The
            # guarded write adds two timestamp stores per chunk (the
            # watchdog timer is per-stream, never per-chunk).
            write = guard.write if guard.active() else resp.write
            next_chunk = iterator.__anext__
            if replay is None:
                feed(first_chunk)
                await write(first_chunk)
                if timeline is not None and b"data:" in first_chunk:
                    timeline.mark()
                while True:
                    try:
                        chunk = await next_chunk()
                    except StopAsyncIteration:
                        break
                    except (aiohttp.ClientError, asyncio.TimeoutError,
                            OSError) as e:
                        # mid-stream upstream cut: tell the client, then
                        # count it against the endpoint
                        status = 502
                        error = f"stream interrupted: {type(e).__name__}"
                        upstream_failed = True
                        # guarded: a stalled client must not pin the handler
                        # on the farewell frame either
                        await write(sse_error_frame(error))
                        break
                    feed(chunk)
                    await write(chunk)
                    if timeline is not None and b"data:" in chunk:
                        timeline.mark()
            else:
                # Armed (resumable) pump: frames forward whole (a cut never
                # leaks a partial event), gateway-internal llmlb.replay
                # frames feed the committed-token ledger, and a mid-stream
                # cut books the dead endpoint once then splices a
                # token-identical continuation from another engine into
                # THIS response (docs/resilience.md "mid-stream recovery").
                splitter = FrameSplitter()
                splicer: ChunkSplicer | None = None
                chunk = first_chunk
                terminal_sent = False
                if state.streams is not None and replay.rid:
                    handle = state.streams.register(
                        replay.rid, model, endpoint.id)
                while True:
                    for frame in splitter.push(chunk):
                        out = _replay_frame_out(replay, splicer, frame)
                        if out is None:
                            continue
                        feed(out)
                        await write(out)
                        if is_done_frame(out):
                            terminal_sent = True
                        if timeline is not None and b"data:" in out:
                            timeline.mark()
                    # Frame boundary: a pending rebalance directive moves
                    # this stream NOW — park on the (healthy) origin, adopt
                    # on the planner's target, splice. Any failure leaves
                    # the origin stream pumping exactly as before.
                    migrated = None
                    if handle is not None and not terminal_sent:
                        directive = state.streams.claim(handle)
                        if directive is not None:
                            target_id, why, _did = directive
                            migrated, outcome = await _migrate_stream(
                                state, replay, target_id, model)
                            state.streams.note_outcome(
                                handle, success=migrated is not None,
                                target=target_id)
                            state.metrics.record_rebalance_migration(
                                why, outcome)
                            if trace is not None:
                                trace.mark("stream_migrate", reason=why,
                                           outcome=outcome,
                                           target=target_id)
                    if migrated is not None:
                        upstream.release()
                        upstream, endpoint, iterator, chunk = migrated
                        next_chunk = iterator.__anext__
                        # same splice mechanics as the reactive cut below:
                        # the adopter re-reports the full committed run and
                        # the splicer forwards only the unseen suffix
                        splitter = FrameSplitter()
                        splicer = ChunkSplicer(replay)
                        replay.mark_ledger_stale()
                        continue
                    try:
                        chunk = await next_chunk()
                    except StopAsyncIteration:
                        break
                    except (aiohttp.ClientError, asyncio.TimeoutError,
                            OSError) as e:
                        if terminal_sent:
                            break  # the stream already completed cleanly
                        # book the victim exactly once: breaker failure +
                        # per-endpoint stats + one stream_interruption, and
                        # exclusion from the re-selection below (a resume
                        # must never burn a half-open probe on the victim)
                        failover.record_failure(
                            endpoint, None, "stream_interrupted",
                            stream_interrupted=True,
                        )
                        resumed = await _acquire_resume(
                            state, failover, replay, model, trace=trace,
                        )
                        if resumed is None:
                            status = 502
                            error = (f"stream interrupted: "
                                     f"{type(e).__name__}")
                            outcome_booked = True  # victim booked above
                            await write(sse_error_frame(error))
                            break
                        upstream.release()
                        upstream, endpoint, iterator, chunk = resumed
                        next_chunk = iterator.__anext__
                        if handle is not None:
                            # keep the directory honest: a reactive resume
                            # re-homed this stream (not a migration — no
                            # window stamp, no migration count)
                            handle.endpoint_id = endpoint.id
                        # snapshot the forwarded offsets BEFORE resetting
                        # the ledger: the adopter re-reports the full
                        # committed sequence for a possible second cut
                        splitter = FrameSplitter()
                        splicer = ChunkSplicer(replay)
                        replay.mark_ledger_stale()
    except asyncio.CancelledError:
        # the watchdog's cancel can land at any await once it fires (e.g.
        # the next upstream read, if the write completed in the race) —
        # only a fired guard converts; anything else propagates
        if not guard.fired:
            raise
        status = 502
        error = f"stream write timeout: {guard.timeout_error()}"
        state.metrics.record_stream_write_timeout(model)
    except StreamWriteTimeout as e:
        # the client stopped draining (slow-loris): abort the stream — the
        # upstream release below closes the engine connection, which
        # cancels the slot — and count it. Not endpoint sickness.
        status = 502
        error = f"stream write timeout: {e}"
        state.metrics.record_stream_write_timeout(model)
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
            ConnectionResetError) as e:
        # resp.write failed: the CLIENT went away — not endpoint sickness,
        # so neither breaker nor per-endpoint failure stats move.
        status = 502
        error = error or f"client disconnected: {type(e).__name__}"
    finally:
        guard.close()
        upstream.release()
        if state.streams is not None:
            # a directive racing this natural finish dies here un-acted-on
            state.streams.unregister(handle)
        if trace is not None:
            trace.end("decode")
            trace.end("proxy")
        # lease already completed at stream start; this books the breaker +
        # balancer stats + interruption metric (and resolves a half-open
        # probe even when the CLIENT was the one that went away). A cut
        # whose outcome was already booked in-line (armed pump: the victim
        # was charged at the moment of the cut) books nothing further here.
        if not outcome_booked:
            book_stream_outcome(state, failover, endpoint, model,
                                upstream_failed=upstream_failed,
                                completed=status == 200)
        pt, ct, reported = acc.finalize(prompt_text)
        duration_s = time.monotonic() - started
        if trace is not None and timeline is not None:
            trace.attach_timeline(timeline)
        if status == 200 and ttft_s is not None:
            # mean inter-token gap over the stream (None for single-token
            # responses: only the TTFT target applies)
            itl_mean = (max(0.0, duration_s - ttft_s) / (ct - 1)
                        if ct > 1 else None)
            state.metrics.record_slo(model, ttft_s, itl_mean,
                                     priority=priority)
        if ct > 0:
            state.load_manager.update_tps(
                endpoint.id, model, api_kind, ct, duration_s
            )
            state.events.publish(
                "TpsUpdated",
                {"endpoint_id": endpoint.id, "model": model,
                 "tps": round(ct / duration_s, 2) if duration_s > 0 else None},
            )
        _record(state, endpoint=endpoint, model=model, api_kind=api_kind,
                path=path, status=status, started=started, prompt_tokens=pt,
                completion_tokens=ct, client_ip=client_ip, auth=auth,
                error=error, stream=True, request_body=stored_body)
    return resp


def _extract_completion_text(parsed: dict) -> str:
    parts = []
    for choice in parsed.get("choices") or []:
        if not isinstance(choice, dict):
            continue
        msg = choice.get("message") or {}
        if isinstance(msg.get("content"), str):
            parts.append(msg["content"])
        if isinstance(choice.get("text"), str):
            parts.append(choice["text"])
    for item in parsed.get("output") or []:  # responses API
        if isinstance(item, dict):
            for c in item.get("content") or []:
                if isinstance(c, dict) and isinstance(c.get("text"), str):
                    parts.append(c["text"])
    return "".join(parts)


def _chat_prompt_text(body: dict) -> str:
    parts = []
    for m in body.get("messages") or []:
        if isinstance(m, dict):
            c = m.get("content")
            if isinstance(c, str):
                parts.append(c)
            elif isinstance(c, list):
                parts.extend(
                    p.get("text", "") for p in c if isinstance(p, dict)
                )
    return "\n".join(parts)


def _completion_prompt_text(body: dict) -> str:
    p = body.get("prompt")
    if isinstance(p, str):
        return p
    if isinstance(p, list):
        return "\n".join(str(x) for x in p)
    return ""


def _responses_prompt_text(body: dict) -> str:
    i = body.get("input")
    if isinstance(i, str):
        return i
    if isinstance(i, list):
        return _chat_prompt_text({"messages": i})
    return ""


# ------------------------------------------------------------------ handlers


async def chat_completions(request: web.Request) -> web.StreamResponse:
    return await proxy_openai_post(
        request, "/v1/chat/completions", TpsApiKind.CHAT,
        Capability.CHAT_COMPLETION, _chat_prompt_text,
    )


async def completions(request: web.Request) -> web.StreamResponse:
    return await proxy_openai_post(
        request, "/v1/completions", TpsApiKind.COMPLETION,
        Capability.CHAT_COMPLETION, _completion_prompt_text,
    )


async def embeddings(request: web.Request) -> web.StreamResponse:
    return await proxy_openai_post(
        request, "/v1/embeddings", TpsApiKind.EMBEDDINGS,
        Capability.EMBEDDINGS,
    )


async def responses(request: web.Request) -> web.StreamResponse:
    return await proxy_openai_post(
        request, "/v1/responses", TpsApiKind.RESPONSES,
        Capability.CHAT_COMPLETION, _responses_prompt_text,
    )


async def list_models(request: web.Request) -> web.Response:
    """Union of canonical models across online endpoints (api/openai.rs:261)."""
    state: AppState = request.app["state"]
    seen: dict[str, dict] = {}
    for ep in state.registry.list_online():
        for m in state.registry.models_for(ep.id):
            entry = seen.setdefault(
                m.canonical_name,
                {
                    "id": m.canonical_name,
                    "object": "model",
                    "created": int(m.created_at),
                    "owned_by": "llmlb",
                    "metadata": {
                        "endpoints": [],
                        "capabilities": [c.value for c in m.capabilities],
                        "context_length": m.context_length,
                    },
                },
            )
            entry["metadata"]["endpoints"].append(ep.name)
            # capability UNION across endpoints: with role-split fleets the
            # first endpoint synced may be prefill-only — the model still
            # has "decode" somewhere, and clients read this list to know
            # what the FLEET can do (docs/disaggregation.md)
            for c in m.capabilities:
                if c.value not in entry["metadata"]["capabilities"]:
                    entry["metadata"]["capabilities"].append(c.value)
    return web.json_response({"object": "list", "data": list(seen.values())})


async def get_model(request: web.Request) -> web.Response:
    state: AppState = request.app["state"]
    model_id = request.match_info["model_id"]
    canonical = to_canonical(model_id)
    pairs = state.registry.find_by_model(canonical)
    if not pairs:
        return error_response(404, f"model {model_id!r} not found")
    _, m = pairs[0]
    return web.json_response(
        {
            "id": m.canonical_name,
            "object": "model",
            "created": int(m.created_at),
            "owned_by": "llmlb",
        }
    )
