"""Gateway application: full route tree + middleware stack.

Parity with reference api/mod.rs:70-635 (route table) with the same middleware
order as §3.2: audit capture (outermost) → inference gate (update drain) →
auth (JWT / API-key / Anthropic x-api-key) → handler. Dashboard SPA served
from static files when present (the reference embeds a built React bundle).
"""

from __future__ import annotations

import logging
import os
import time

from aiohttp import web

from llmlb_tpu.gateway import (
    api_admin,
    api_anthropic,
    api_benchmarks,
    api_cloud,
    api_dashboard,
    api_media,
    api_models,
    api_openai,
    tracing,
)
from llmlb_tpu.gateway.app_state import AppState
from llmlb_tpu.gateway.audit import AuditEntry
from llmlb_tpu.gateway.auth import (
    CSRF_COOKIE,
    JWT_COOKIE,
    AuthError,
    verify_jwt,
)
from llmlb_tpu.gateway.tracing import REQUEST_ID_HEADER, mint_request_id
from llmlb_tpu.gateway.types import Permission

log = logging.getLogger("llmlb_tpu.gateway.app")

MAX_BODY_BYTES = 20 * 1024 * 1024  # parity: api/mod.rs:58

PUBLIC_PATHS = {
    ("POST", "/api/auth/login"),
    ("POST", "/api/auth/register"),
    ("GET", "/health"),
    ("GET", "/api/health"),  # fleet health + breaker state, same stance
    ("GET", "/metrics"),  # Prometheus scrape, same stance as the engine's
    ("GET", "/"),
}

# method+prefix → permission required when authenticating with an API key
_API_KEY_PERMS: list[tuple[str, str, Permission]] = [
    ("GET", "/api/endpoints", Permission.ENDPOINTS_READ),
    ("*", "/api/endpoints", Permission.ENDPOINTS_MANAGE),
    ("*", "/api/users", Permission.USERS_MANAGE),
    ("*", "/api/invitations", Permission.INVITATIONS_MANAGE),
    ("GET", "/api/audit", Permission.LOGS_READ),
    ("GET", "/api/dashboard/logs", Permission.LOGS_READ),
    ("GET", "/api/dashboard", Permission.METRICS_READ),
    ("GET", "/api/metrics", Permission.METRICS_READ),
    ("GET", "/api/models/registry", Permission.REGISTRY_READ),
    ("GET", "/api/benchmarks", Permission.METRICS_READ),
    ("GET", "/api/traces", Permission.METRICS_READ),
]


def _is_traced_path(path: str) -> bool:
    """Inference paths get full lifecycle traces (every request gets an id)."""
    return path.startswith("/v1/") or (
        path.startswith("/api/endpoints/")
        and path.endswith("/chat/completions")
    )


def _route_label(request: web.Request) -> str | None:
    """Matched route pattern (e.g. '/v1/chat/completions') — a bounded label
    set; unmatched requests return None and are not counted."""
    resource = getattr(request.match_info.route, "resource", None)
    return getattr(resource, "canonical", None)


@web.middleware
async def tracing_middleware(request: web.Request, handler):
    """Outermost: mints/reuses X-Request-Id, echoes it on every response
    (success and error paths), records the lifecycle trace for inference
    requests, and counts requests/errors per route in GatewayMetrics."""
    state: AppState = request.app["state"]
    rid = mint_request_id(request.headers.get(REQUEST_ID_HEADER))
    request["request_id"] = rid
    trace = None
    if _is_traced_path(request.path):
        trace = state.traces.start(rid, request.method, request.path)
        # auth covers the middleware stack up to the handler; the inference
        # handlers close it on entry, finish() closes it on rejection.
        trace.begin("auth")
        request["trace"] = trace
    status = 500
    error = None
    try:
        response = await handler(request)
        status = response.status
        if not response.prepared:  # streamed responses set it pre-prepare
            response.headers[REQUEST_ID_HEADER] = rid
        return response
    except web.HTTPException as e:
        status = e.status
        e.headers[REQUEST_ID_HEADER] = rid
        raise
    except Exception as e:
        error = f"{type(e).__name__}: {e}"
        raise
    finally:
        if trace is not None:
            state.traces.finish(trace, status, error)
        route = _route_label(request)
        if route is not None and request.path != "/metrics":
            state.metrics.record_request(route, status)


@web.middleware
async def audit_middleware(request: web.Request, handler):
    """Directly inside tracing: every request lands in the tamper-evident
    audit log."""
    state: AppState = request.app["state"]
    start = time.monotonic()
    status = 500
    detail = None
    try:
        response = await handler(request)
        status = response.status
        return response
    except web.HTTPException as e:
        status = e.status
        raise
    except Exception as e:
        detail = f"{type(e).__name__}: {e}"
        raise
    finally:
        if request.path != "/ws/dashboard":
            auth = request.get("auth") or {}
            state.audit.record(AuditEntry(
                ts=time.time(),
                method=request.method,
                path=request.path,
                status=status,
                duration_ms=(time.monotonic() - start) * 1000.0,
                actor=auth.get("actor"),
                actor_type=auth.get("actor_type", "anonymous"),
                ip=request.remote,
                detail=detail,
            ))


@web.middleware
async def gate_middleware(request: web.Request, handler):
    """Inference gate: during update drain, /v1/* rejects with 503+Retry-After
    (inference_gate.rs:200-230); otherwise counts the request in flight for the
    full (streaming) response lifetime."""
    state: AppState = request.app["state"]
    # Playground proxy is inference too (reference gates it: api/mod.rs:460-479).
    is_inference = request.path.startswith("/v1/") or (
        request.path.startswith("/api/endpoints/")
        and request.path.endswith("/chat/completions")
    )
    if is_inference:
        if state.gate.rejecting:
            return web.json_response(
                {"error": {"message": "server is draining for update",
                           "type": "server_error", "code": "draining"}},
                status=503,
                headers={"Retry-After": "30"},
            )
        with state.gate.track():
            return await handler(request)
    return await handler(request)


def _required_api_key_perm(method: str, path: str) -> Permission | None:
    for m, prefix, perm in _API_KEY_PERMS:
        if path.startswith(prefix) and (m == "*" or m == method):
            return perm
    return None


def _origin_matches(request: web.Request) -> bool:
    """Origin/Referer must match the Host the request arrived on (parity:
    auth/middleware.rs origin_matches). Missing both headers fails closed."""
    origin = request.headers.get("Origin")
    if origin is None:
        referer = request.headers.get("Referer")
        if referer and "://" in referer:
            scheme, _, rest = referer.partition("://")
            origin = f"{scheme}://{rest.split('/', 1)[0]}"
    if not origin or "://" not in origin:
        return False
    host = request.headers.get("X-Forwarded-Host", request.host)
    host = host.split(",")[0].strip()
    proto = request.headers.get(
        "X-Forwarded-Proto", request.scheme or "http"
    ).split(",")[0].strip()

    def norm(scheme: str, authority: str) -> tuple[str, str, str]:
        scheme = scheme.lower()
        authority = authority.lower().rstrip(".")
        default_port = {"http": "80", "https": "443"}.get(scheme, "")
        if authority.startswith("["):  # bracketed IPv6: [::1] or [::1]:8080
            h, _, rest = authority.partition("]")
            h += "]"
            p = rest[1:] if rest.startswith(":") else default_port
        elif ":" in authority:
            h, _, p = authority.rpartition(":")
        else:
            h, p = authority, default_port
        return scheme, h.rstrip("."), p or default_port

    o_scheme, _, o_rest = origin.partition("://")
    return norm(o_scheme, o_rest.split("/", 1)[0]) == norm(proto, host)


@web.middleware
async def csrf_middleware(request: web.Request, handler):
    """Double-submit CSRF for cookie-authenticated state changes (parity:
    auth/middleware.rs:431-479 csrf_protect_middleware). Header-authenticated
    requests (Authorization / x-api-key) are exempt — only the browser cookie
    session is forgeable cross-site."""
    if request.method not in ("POST", "PUT", "PATCH", "DELETE"):
        return await handler(request)
    if not request.path.startswith("/api/"):
        return await handler(request)
    if (request.method, request.path) in PUBLIC_PATHS:
        return await handler(request)  # login/register establish the session
    if "Authorization" in request.headers or "x-api-key" in request.headers:
        return await handler(request)
    if request.cookies.get(JWT_COOKIE) is None:
        return await handler(request)  # not a cookie session; auth will 401

    cookie_token = request.cookies.get(CSRF_COOKIE)
    if not cookie_token:
        return web.json_response({"error": "missing CSRF cookie"}, status=403)
    header_token = request.headers.get("x-csrf-token")
    if not header_token:
        return web.json_response({"error": "missing CSRF header"}, status=403)
    if cookie_token != header_token:
        return web.json_response({"error": "invalid CSRF token"}, status=403)
    if not _origin_matches(request):
        return web.json_response(
            {"error": "origin validation failed"}, status=403
        )
    return await handler(request)


@web.middleware
async def auth_middleware(request: web.Request, handler):
    state: AppState = request.app["state"]
    method, path = request.method, request.path

    if method == "OPTIONS" or (method, path) in PUBLIC_PATHS or path.startswith(
        "/dashboard"
    ):
        return await handler(request)
    if path == "/ws/dashboard":  # WS does its own token auth (query/cookie)
        return await handler(request)

    # ---- credential extraction
    bearer = None
    authz = request.headers.get("Authorization", "")
    if authz.startswith("Bearer "):
        bearer = authz[7:].strip()
    # Dashboard cookie session — accepted only on the /api/* surface, where
    # csrf_middleware guards state changes. /v1/* stays header-auth-only so a
    # cross-site form POST can never ride the browser cookie into inference.
    if not bearer and path.startswith("/api/"):
        bearer = request.cookies.get(JWT_COOKIE)
    anthropic_key = request.headers.get("x-api-key")  # Anthropic-style

    auth_ctx: dict | None = None
    if bearer and bearer.startswith("sk_"):
        key = state.api_keys.verify(bearer)
        if key:
            auth_ctx = {
                "actor": f"key:{key.name}", "actor_type": "api_key",
                "api_key_id": key.id, "user_id": key.user_id,
                "permissions": set(key.permissions), "role": None,
            }
    elif bearer:
        try:
            payload = verify_jwt(state.jwt_secret, bearer)
            auth_ctx = {
                "actor": payload.get("username"), "actor_type": "jwt",
                "user_id": payload.get("sub"), "api_key_id": None,
                "permissions": None, "role": payload.get("role"),
            }
        except AuthError:
            auth_ctx = None
    if auth_ctx is None and anthropic_key and anthropic_key.startswith("sk_"):
        key = state.api_keys.verify(anthropic_key)
        if key:
            auth_ctx = {
                "actor": f"key:{key.name}", "actor_type": "api_key",
                "api_key_id": key.id, "user_id": key.user_id,
                "permissions": set(key.permissions), "role": None,
            }

    if auth_ctx is None:
        if path.startswith("/v1/"):
            return web.json_response(
                {"error": {"message": "missing or invalid API key",
                           "type": "authentication_error", "code": None}},
                status=401,
            )
        return web.json_response({"error": "authentication required"}, status=401)

    request["auth"] = auth_ctx

    # ---- authorization
    if path.startswith("/v1/"):
        if auth_ctx["actor_type"] == "api_key":
            needed = (
                Permission.OPENAI_MODELS_READ
                if path.startswith("/v1/models") and method == "GET"
                else Permission.OPENAI_INFERENCE
            )
            perms = auth_ctx["permissions"] or set()
            if needed not in perms and Permission.OPENAI_INFERENCE not in perms:
                return web.json_response(
                    {"error": {"message": f"API key lacks {needed.value}",
                               "type": "permission_error", "code": None}},
                    status=403,
                )
        return await handler(request)

    # /api/* surface
    if auth_ctx["actor_type"] == "api_key":
        needed = _required_api_key_perm(method, path)
        if needed is None or needed not in (auth_ctx["permissions"] or set()):
            return web.json_response(
                {"error": f"API key lacks permission for {method} {path}"},
                status=403,
            )
        return await handler(request)

    # JWT: viewers read, admins everything; self-service paths exempt
    if auth_ctx["role"] != "admin":
        self_service = path in (
            "/api/auth/me", "/api/auth/change-password", "/api/api-keys"
        ) or path.startswith("/api/api-keys/")
        if method not in ("GET", "HEAD") and not self_service:
            return web.json_response(
                {"error": "admin role required"}, status=403
            )
    return await handler(request)


def create_app(state: AppState) -> web.Application:
    app = web.Application(
        client_max_size=MAX_BODY_BYTES,
        middlewares=[
            tracing_middleware, audit_middleware, gate_middleware,
            csrf_middleware, auth_middleware,
        ],
    )
    app["state"] = state
    r = app.router

    # ---- OpenAI surface (api/mod.rs:523-535)
    r.add_post("/v1/chat/completions", api_openai.chat_completions)
    r.add_post("/v1/completions", api_openai.completions)
    r.add_post("/v1/embeddings", api_openai.embeddings)
    r.add_post("/v1/responses", api_openai.responses)
    r.add_get("/v1/models", api_openai.list_models)
    r.add_get("/v1/models/{model_id:.+}", api_openai.get_model)
    r.add_post("/v1/audio/transcriptions", api_media.audio_transcriptions)
    r.add_post("/v1/audio/speech", api_media.audio_speech)
    r.add_post("/v1/images/generations", api_media.images_generations)
    r.add_post("/v1/images/edits", api_media.images_edits)
    r.add_post("/v1/images/variations", api_media.images_variations)

    # ---- Anthropic surface (api/mod.rs:553)
    r.add_post("/v1/messages", api_anthropic.messages)

    # ---- auth
    r.add_post("/api/auth/login", api_admin.login)
    r.add_post("/api/auth/logout", api_admin.logout)
    r.add_post("/api/auth/register", api_admin.register_with_invitation)
    r.add_get("/api/auth/me", api_admin.me)
    r.add_post("/api/auth/change-password", api_admin.change_password)

    # ---- endpoints admin
    r.add_get("/api/endpoints", api_admin.list_endpoints)
    r.add_post("/api/endpoints", api_admin.create_endpoint)
    r.add_get("/api/endpoints/{endpoint_id}", api_admin.get_endpoint)
    r.add_get(
        "/api/endpoints/{endpoint_id}/system-info",
        api_admin.get_endpoint_system_info,
    )
    r.add_put("/api/endpoints/{endpoint_id}", api_admin.update_endpoint)
    r.add_delete("/api/endpoints/{endpoint_id}", api_admin.delete_endpoint)
    r.add_post("/api/endpoints/{endpoint_id}/test", api_admin.test_endpoint)
    r.add_post("/api/endpoints/{endpoint_id}/sync", api_admin.sync_endpoint)
    r.add_get(
        "/api/endpoints/{endpoint_id}/health",
        api_admin.endpoint_health_history,
    )

    # ---- users / keys / invitations
    r.add_get("/api/users", api_admin.list_users)
    r.add_post("/api/users", api_admin.create_user)
    r.add_delete("/api/users/{user_id}", api_admin.delete_user)
    r.add_put("/api/users/{user_id}/role", api_admin.set_user_role)
    r.add_get("/api/api-keys", api_admin.list_api_keys)
    r.add_post("/api/api-keys", api_admin.create_api_key)
    r.add_delete("/api/api-keys/{key_id}", api_admin.revoke_api_key)
    r.add_get("/api/invitations", api_admin.list_invitations)
    r.add_post("/api/invitations", api_admin.create_invitation)
    r.add_delete(
        "/api/invitations/{invitation_id}", api_admin.delete_invitation
    )

    # ---- audit / settings / system
    # ---- model registry + catalog + per-endpoint model management
    r.add_post("/api/models/register", api_models.register_model)
    r.add_get("/api/models", api_models.list_registered_models)
    r.add_delete("/api/models/{name}", api_models.delete_registered_model)
    r.add_get(
        "/api/models/registry/{model}/manifest.json",
        api_models.get_model_manifest,
    )
    r.add_get("/api/catalog/search", api_models.catalog_search)
    r.add_post(
        "/api/endpoints/{endpoint_id}/models/download",
        api_models.download_endpoint_model,
    )
    r.add_get(
        "/api/endpoints/models/download/{task_id}",
        api_models.download_progress,
    )
    r.add_delete(
        "/api/endpoints/{endpoint_id}/models/{model}",
        api_models.delete_endpoint_model,
    )
    r.add_get(
        "/api/endpoints/{endpoint_id}/models/{model}/info",
        api_models.endpoint_model_info,
    )
    r.add_post(
        "/api/endpoints/{endpoint_id}/chat/completions",
        api_models.playground_chat_proxy,
    )

    r.add_get("/api/audit-log", api_admin.query_audit_log)
    r.add_post("/api/audit-log/verify", api_admin.verify_audit_chain)
    r.add_get("/api/dashboard/settings", api_admin.get_settings)
    r.add_put("/api/dashboard/settings", api_admin.update_setting)
    r.add_get("/api/system", api_admin.system_info)
    r.add_get("/api/system/tray", api_admin.tray_status)
    r.add_post("/api/system/tray/activate", api_admin.tray_activate)

    # ---- dashboard data + WS
    r.add_get("/api/dashboard/overview", api_dashboard.overview)
    r.add_get(
        "/api/dashboard/request-history", api_dashboard.request_history_minutes
    )
    r.add_get("/api/dashboard/requests", api_dashboard.request_records)
    r.add_get(
        "/api/dashboard/requests/{record_id}",
        api_dashboard.request_record_detail,
    )
    r.add_get("/api/dashboard/token-stats", api_dashboard.token_stats)
    r.add_get(
        "/api/dashboard/endpoints/{endpoint_id}/stats",
        api_dashboard.endpoint_stats,
    )
    r.add_get("/api/dashboard/model-tps", api_dashboard.model_tps)
    r.add_get("/api/dashboard/clients", api_dashboard.client_analytics)
    r.add_get("/api/dashboard/logs/lb", api_dashboard.tail_lb_logs)
    r.add_get("/ws/dashboard", api_dashboard.dashboard_ws)

    # ---- benchmarks + cloud metrics
    r.add_post("/api/benchmarks/tps", api_benchmarks.start_tps_benchmark)
    r.add_get("/api/benchmarks/tps", api_benchmarks.list_tps_benchmarks)
    r.add_get("/api/benchmarks/tps/{run_id}", api_benchmarks.get_tps_benchmark)
    r.add_get("/api/metrics/cloud", api_cloud.cloud_metrics_handler)

    # ---- observability: request traces + gateway-wide Prometheus metrics
    r.add_get("/api/traces", tracing.list_traces)
    r.add_get("/api/traces/{trace_id}", tracing.get_trace)
    r.add_get("/metrics", _gateway_metrics)

    # ---- update lifecycle
    r.add_post("/api/system/update/check", _update_check)
    r.add_post("/api/system/update/apply", _update_apply)
    r.add_post("/api/system/update/cancel", _update_cancel)
    r.add_put("/api/system/update/schedule", _update_schedule)

    # ---- liveness + root
    r.add_get("/health", _health)
    r.add_get("/api/health", _api_health)
    r.add_get("/", _root)

    # ---- dashboard SPA (static bundle, embedded in the reference binary)
    static_dir = os.path.join(os.path.dirname(__file__), "dashboard_static")
    if os.path.isdir(static_dir):
        r.add_get("/dashboard", _dashboard_index)
        r.add_get("/dashboard/{tail:.*}", _dashboard_asset)
        app["dashboard_static"] = static_dir

    async def on_shutdown(app):
        await state.close()

    app.on_shutdown.append(on_shutdown)
    return app


async def _health(request: web.Request) -> web.Response:
    return web.json_response({"status": "ok"})


async def _api_health(request: web.Request) -> web.Response:
    """GET /api/health — fleet-level health: per-endpoint status as the
    scheduler sees it right now (pull-checker status AND in-band breaker
    state + outcome counters), admission pressure, and the retry budget.
    The gateway-side counterpart of the engine's /api/health.

    Public (same stance as /metrics, which already exposes endpoint names
    and breaker states as labels) — but only names, never endpoint ids:
    ids are admin-API identifiers and stay behind auth."""
    state: AppState = request.app["state"]
    endpoints = []
    for ep in state.registry.list_all():
        breaker = (state.resilience.breaker_info(ep.id)
                   if state.resilience is not None
                   else {"state": ep.breaker_state})
        endpoints.append({
            "name": ep.name,
            "status": ep.status.value,
            # serving role from the last engine probe (docs/disaggregation.md)
            "role": ep.accelerator.role or "both",
            # graceful-drain advertisement from the last probe: a draining
            # engine is online but ejected from selection
            # (docs/deployment.md)
            "draining": ep.accelerator.draining,
            "breaker": breaker,
            "latency_ms": ep.latency_ms,
            "consecutive_probe_failures": ep.consecutive_failures,
            "outcomes": state.load_manager.endpoint_outcomes(ep.id),
            "active_requests": state.load_manager.active_count(ep.id),
        })
    online = sum(1 for e in endpoints if e["status"] == "online")
    serving = sum(
        1 for e in endpoints
        if (e["status"] == "online" and e["breaker"]["state"] != "open"
            and not e["draining"])
    )
    body = {
        "status": "ok" if serving or not endpoints else "degraded",
        "uptime_s": round(time.time() - state.started_at, 1),
        "endpoints_online": online,
        "endpoints_serving": serving,  # online AND breaker not open
        "endpoints": endpoints,
        "admission": {
            "queue_depth": state.admission.queue_depth(),
            "active_requests": state.load_manager.total_active(),
            "wfq_enabled": state.admission.wfq_enabled,
        },
    }
    if state.ratelimit is not None and state.ratelimit.enabled:
        body["ratelimit"] = state.ratelimit.snapshot()
    if state.worker.multi:
        body["worker"] = {"index": state.worker.index,
                          "count": state.worker.count}
    # a single-worker host federated over the mesh still has peers worth
    # showing (docs/deployment.md cross-host topology)
    if state.gossip is not None:
        body["gossip"] = state.gossip.stats()
    if state.resilience is not None:
        cfg = state.resilience.config
        body["resilience"] = {
            "enabled": cfg.enabled,
            "max_attempts": cfg.max_attempts,
            "breaker_failure_threshold": cfg.breaker_failure_threshold,
            "breaker_open_s": cfg.breaker_open_s,
            "retry_budget": state.resilience.budget.snapshot(),
        }
    if state.faults is not None:
        body["faults"] = state.faults.snapshot()
    return web.json_response(body)


async def _gateway_metrics(request: web.Request) -> web.Response:
    """GET /metrics — gateway-wide Prometheus exposition: per-model/endpoint
    TTFT, e2e, and queue-wait histograms, per-route counters, plus
    scrape-time gauges owned by the balancer and event bus.

    Multi-worker: SO_REUSEPORT hands the scrape to ONE arbitrary worker, so
    the serving worker labels its own series worker="i", refreshes its
    spool, and appends its siblings' spooled (already-labeled) series —
    Prometheus sees the whole group on every scrape, attributable per
    worker (docs/deployment.md)."""
    from llmlb_tpu.gateway.app_state import (
        gateway_exposition,
        read_peer_metrics,
        write_metrics_spool,
    )
    from llmlb_tpu.gateway.config import env_float
    from llmlb_tpu.gateway.metrics import label_exposition

    state: AppState = request.app["state"]
    text = gateway_exposition(state)
    if state.worker.multi:
        from llmlb_tpu.gateway.app_state import METRICS_SPOOL_DEFAULT_S

        text = label_exposition(text, "worker", state.worker.label)
        try:
            # scrape-fresh spool for whoever serves the next scrape, from
            # the text already rendered above (no second exposition build)
            write_metrics_spool(state, labeled_text=text)
        except OSError:
            pass
        interval = env_float("LLMLB_METRICS_SPOOL_SECS",
                             METRICS_SPOOL_DEFAULT_S)
        text += read_peer_metrics(state, max_age_s=3 * interval + 2.0)
    return web.Response(text=text, content_type="text/plain", charset="utf-8")


async def _root(request: web.Request) -> web.Response:
    return web.json_response({
        "name": "llmlb_tpu",
        "endpoints": ["/v1/chat/completions", "/v1/responses", "/v1/models",
                      "/v1/messages", "/api/endpoints", "/dashboard"],
    })


async def _dashboard_index(request: web.Request) -> web.FileResponse:
    return web.FileResponse(
        os.path.join(request.app["dashboard_static"], "index.html")
    )


async def _dashboard_asset(request: web.Request) -> web.StreamResponse:
    static_dir = request.app["dashboard_static"]
    tail = request.match_info["tail"] or "index.html"
    full = os.path.normpath(os.path.join(static_dir, tail))
    if not full.startswith(os.path.abspath(static_dir)) or not os.path.isfile(full):
        return await _dashboard_index(request)  # SPA fallback
    return web.FileResponse(full)


async def _update_check(request: web.Request) -> web.Response:
    state: AppState = request.app["state"]
    if state.update_manager is None:
        return web.json_response({"error": "updates not configured"}, status=501)
    return web.json_response(await state.update_manager.check(force=True))


async def _update_apply(request: web.Request) -> web.Response:
    from llmlb_tpu.gateway.update import ApplyMode

    state: AppState = request.app["state"]
    if state.update_manager is None:
        return web.json_response({"error": "updates not configured"}, status=501)
    try:
        body = await request.json() if request.can_read_body else {}
    except Exception:
        body = {}
    mode = ApplyMode.FORCE if body.get("force") else ApplyMode.NORMAL
    started = state.update_manager.request_apply(mode)
    return web.json_response(
        {"applying": started, **state.update_manager.status()},
        status=202 if started else 409,
    )


async def _update_cancel(request: web.Request) -> web.Response:
    state: AppState = request.app["state"]
    if state.update_manager is None:
        return web.json_response({"error": "updates not configured"}, status=501)
    return web.json_response({"cancelled": state.update_manager.cancel_drain()})


async def _update_schedule(request: web.Request) -> web.Response:
    state: AppState = request.app["state"]
    if state.update_manager is None:
        return web.json_response({"error": "updates not configured"}, status=501)
    try:
        body = await request.json()
        state.update_manager.set_schedule(
            body.get("mode", "immediate"), body.get("at_time")
        )
    except Exception as e:
        return web.json_response({"error": str(e)}, status=400)
    return web.json_response(state.update_manager.status())
