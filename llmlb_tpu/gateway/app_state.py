"""AppState: shared state injected into all handlers + bootstrap sequence.

Parity with reference lib.rs:106-141 (AppState) and bootstrap.rs:42-345
(initialize): DB + schema, registry cache load, LoadManager seeding from daily
stats, shared HTTP client, admin bootstrap, JWT secret provisioning, audit init
+ startup chain verification, health checker, background maintenance tasks.
"""

from __future__ import annotations

import asyncio
import dataclasses
import datetime
import logging
import os
import secrets
import threading
import time

import aiohttp

from llmlb_tpu.gateway.audit import AuditLog
from llmlb_tpu.gateway.auth import (
    ApiKeyStore,
    InvitationStore,
    UserStore,
    ensure_admin_exists,
)
from llmlb_tpu.gateway.balancer import (
    AdmissionQueue,
    LoadManager,
    default_affinity_mode,
)
from llmlb_tpu.gateway.config import (
    QueueConfig,
    RateLimitConfig,
    ResilienceConfig,
    ServerConfig,
    SloConfig,
    env_bool,
    env_float,
    env_int,
    wfq_weights_from_env,
)
from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.events import DashboardEventBus
from llmlb_tpu.gateway.faults import FaultInjector
from llmlb_tpu.gateway.gate import InferenceGate
from llmlb_tpu.gateway.gossip import (
    MEMBER_KEY_PREFIX,
    GossipBus,
    GossipFaults,
    MeshConfig,
    default_gossip_dir,
)
from llmlb_tpu.gateway.health import EndpointHealthChecker
from llmlb_tpu.gateway.metrics import GatewayMetrics
from llmlb_tpu.gateway.ratelimit import RateLimiter
from llmlb_tpu.gateway.rebalance import (
    RebalanceConfig,
    Rebalancer,
    StreamDirectory,
)
from llmlb_tpu.gateway.registry import EndpointRegistry
from llmlb_tpu.gateway.resilience import ResilienceManager
from llmlb_tpu.gateway.tracing import TraceStore
from llmlb_tpu.gateway.types import TpsApiKind
from llmlb_tpu.gateway.worker import WorkerInfo, current_worker

log = logging.getLogger("llmlb_tpu.gateway")


class HistoryWriter:
    """Request-history + daily-stat DB writes.

    Synchronous by default (bit-identical to the historical per-request
    execute). In multi-worker mode (or with LLMLB_HISTORY_FLUSH_SECS set)
    rows buffer in memory and a periodic task flushes them in one
    transaction each — N workers' hot paths then take the WAL writer lock a
    couple of times per second instead of three times per request, which is
    the difference between near-linear scaling and serializing on SQLite.
    """

    _HISTORY_SQL = (
        "INSERT INTO request_history "
        "(id, ts, endpoint_id, endpoint_name, model, api_kind, path, "
        " status_code, duration_ms, prompt_tokens, completion_tokens, "
        " client_ip, api_key_id, user_id, stream, error, request_body) "
        "VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)"
    )
    _DAILY_SQL = (
        "INSERT INTO endpoint_daily_stats "
        "(endpoint_id, date, model, api_kind, request_count, error_count, "
        " prompt_tokens, completion_tokens, total_duration_ms) "
        "VALUES (?,?,?,?,1,?,?,?,?) "
        "ON CONFLICT(endpoint_id, date, model, api_kind) DO UPDATE SET "
        "request_count = request_count + 1, "
        "error_count = error_count + excluded.error_count, "
        "prompt_tokens = prompt_tokens + excluded.prompt_tokens, "
        "completion_tokens = completion_tokens + excluded.completion_tokens, "
        "total_duration_ms = total_duration_ms + excluded.total_duration_ms"
    )

    def __init__(self, db: Database, batched: bool = False,
                 flush_interval_s: float = 0.5):
        self.db = db
        self.batched = batched
        self.flush_interval_s = flush_interval_s
        self._lock = threading.Lock()
        self._history_rows: list[tuple] = []
        self._daily_rows: list[tuple] = []
        self._task: asyncio.Task | None = None

    # Backstop for batched writers whose flush task is not running (an
    # embedder building a multi-worker state with start_background=False):
    # past this many buffered rows, add_* flushes inline instead of
    # growing without bound.
    MAX_BUFFERED_ROWS = 10_000

    def add_history(self, params: tuple) -> None:
        if not self.batched:
            self.db.execute(self._HISTORY_SQL, params)
            return
        with self._lock:
            self._history_rows.append(params)
            overflow = len(self._history_rows) >= self.MAX_BUFFERED_ROWS
        if overflow:
            self.flush()

    def add_daily(self, params: tuple) -> None:
        if not self.batched:
            self.db.execute(self._DAILY_SQL, params)
            return
        with self._lock:
            self._daily_rows.append(params)
            overflow = len(self._daily_rows) >= self.MAX_BUFFERED_ROWS
        if overflow:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            history, self._history_rows = self._history_rows, []
            daily, self._daily_rows = self._daily_rows, []
        if not history and not daily:
            return
        try:
            with self.db.transaction():
                if history:
                    self.db.executemany(self._HISTORY_SQL, history)
                for row in daily:  # UPSERT rows may collide per key
                    self.db.execute(self._DAILY_SQL, row)
        except Exception:
            # transient WAL contention must not silently lose a flush
            # window of history: put the rows back for the next attempt
            with self._lock:
                self._history_rows[:0] = history
                self._daily_rows[:0] = daily
            raise

    def start(self) -> None:
        if self.batched and self._task is None:
            self._task = asyncio.create_task(
                self._flush_loop(), name="history-writer"
            )

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.flush_interval_s)
            try:
                self.flush()
            except Exception:
                log.exception("request-history flush failed")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        try:
            self.flush()
        except Exception:
            log.exception("final request-history flush failed")


@dataclasses.dataclass
class AppState:
    config: ServerConfig
    db: Database
    registry: EndpointRegistry
    load_manager: LoadManager
    admission: AdmissionQueue
    events: DashboardEventBus
    gate: InferenceGate
    audit: AuditLog
    users: UserStore
    api_keys: ApiKeyStore
    invitations: InvitationStore
    jwt_secret: str
    http: aiohttp.ClientSession
    metrics: GatewayMetrics
    traces: TraceStore
    resilience: ResilienceManager | None = None
    faults: FaultInjector | None = None
    # Per-API-key token buckets (gateway/ratelimit.py, docs/scheduling.md);
    # always constructed — zero hot-path work unless limits are configured.
    ratelimit: RateLimiter | None = None
    health_checker: EndpointHealthChecker | None = None
    update_manager: object | None = None  # set by gateway.update
    tray: object | None = None  # TrayController when LLMLB_TRAY=1
    worker: WorkerInfo = dataclasses.field(default_factory=WorkerInfo)
    gossip: GossipBus | None = None  # multi-worker state replication
    # Fleet rebalancing (gateway/rebalance.py): every worker tracks its live
    # streams in `streams`; the elected primary additionally runs the
    # planner loop in `rebalancer`. LLMLB_REBALANCE=0 leaves the directory
    # inert (register returns None) and the planner unconstructed.
    streams: StreamDirectory | None = None
    rebalancer: Rebalancer | None = None
    history: "HistoryWriter | None" = None
    started_at: float = dataclasses.field(default_factory=time.time)
    _tasks: list[asyncio.Task] = dataclasses.field(default_factory=list)

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass  # allow-silent: shutdown teardown of cancelled tasks
        if self.rebalancer is not None:
            await self.rebalancer.stop()
        if self.health_checker:
            await self.health_checker.stop()
        if self.history is not None:
            await self.history.stop()
        if self.gossip is not None:
            self.gossip.close()
        await self.audit.stop()
        await self.http.close()
        self.db.close()


async def build_app_state(
    config: ServerConfig | None = None,
    *,
    db: Database | None = None,
    start_background: bool = True,
    worker: WorkerInfo | None = None,
) -> AppState:
    config = config or ServerConfig.from_env()
    if db is None:
        db = Database(config.database_url or ":memory:")
    if worker is None:
        worker = current_worker()

    registry = EndpointRegistry(db)
    load_manager = LoadManager(
        QueueConfig.from_env(),
        affinity_mode=default_affinity_mode(worker.count),
    )
    admission = AdmissionQueue(load_manager)
    # Weighted fair queuing (docs/scheduling.md): per-tenant virtual-time
    # ordering of the contended admission queue; LLMLB_WFQ=0 restores the
    # historical pure-FIFO order.
    admission.wfq_enabled = env_bool("LLMLB_WFQ", True)
    admission.weights = wfq_weights_from_env()
    events = DashboardEventBus()
    gate = InferenceGate()
    audit = AuditLog(db)
    # SLO targets ride inside the metrics registry: every proxy path that
    # finishes a successful request judges it there (record_slo)
    metrics = GatewayMetrics(slo=SloConfig.from_env())
    admission.metrics = metrics  # admission-retry counter (balancer.py)
    # Multi-worker: spool completed traces to the gossip dir so ANY worker
    # answers /api/traces/{id} regardless of which sibling served the
    # request (same sibling-merge pattern as the /metrics spool below).
    traces = TraceStore(
        capacity=env_int("LLMLB_TRACE_BUFFER", 256), events=events,
        spool_dir=(default_gossip_dir(config.port) if worker.multi else None),
    )

    users = UserStore(db)
    api_keys = ApiKeyStore(db, cache_ttl_s=env_float(
        "LLMLB_AUTH_CACHE_TTL",
        ApiKeyStore.MULTI_WORKER_DEFAULT_TTL_S if worker.multi else 0.0,
    ))
    invitations = InvitationStore(db)

    # admin bootstrap (reference auth/bootstrap.rs)
    admin, generated = ensure_admin_exists(
        users, config.admin_username, config.admin_password
    )
    if generated:
        log.warning(
            "bootstrap admin %r created with generated password: %s "
            "(change it on first login)",
            admin.username, generated,
        )

    # JWT secret: env > persisted setting > fresh random (persisted).
    # Insert-if-absent then re-read: N workers booting concurrently must all
    # adopt ONE secret, or a session minted by worker A would 401 on
    # worker B behind the shared SO_REUSEPORT port.
    jwt_secret = config.jwt_secret or db.get_setting("auth.jwt_secret")
    if not jwt_secret:
        db.execute(
            """INSERT INTO settings (key, value, updated_at)
               VALUES ('auth.jwt_secret', ?, ?)
               ON CONFLICT(key) DO NOTHING""",
            (secrets.token_urlsafe(32), time.time()),
        )
        jwt_secret = db.get_setting("auth.jwt_secret")

    # startup audit chain verification (bootstrap.rs:211-265)
    ok, err = audit.verify()
    if not ok:
        log.error("AUDIT CHAIN VERIFICATION FAILED: %s", err)

    http = aiohttp.ClientSession(
        connector=aiohttp.TCPConnector(limit_per_host=32, keepalive_timeout=60)
    )

    # Resilience layer: per-endpoint circuit breakers + the global retry
    # budget; selection consults it through load_manager.resilience. The
    # fault injector is None unless LLMLB_FAULTS configures rules (or a
    # chaos test installs them) — zero hot-path cost otherwise.
    resilience = ResilienceManager(
        ResilienceConfig.from_env(), metrics=metrics, events=events,
        registry=registry,
    )
    load_manager.resilience = resilience
    faults = FaultInjector.from_env()

    # Per-API-key rate limits: worker-local conservative shares by default
    # (limits divide by the worker count — the group never exceeds the
    # configured rate); promoted to fleet-global buckets below when the
    # gossip bus starts (attach_gossip).
    ratelimit = RateLimiter(RateLimitConfig.from_env(), workers=worker.count)

    # Per-request history/daily-stat writes: synchronous single-worker (the
    # historical behavior), batched when N workers share the WAL file or
    # when LLMLB_HISTORY_FLUSH_SECS opts in explicitly.
    flush_s = env_float("LLMLB_HISTORY_FLUSH_SECS", 0.0)
    history = HistoryWriter(
        db, batched=worker.multi or flush_s > 0,
        flush_interval_s=flush_s if flush_s > 0 else 0.5,
    )

    # Live-stream directory: every worker tracks the streams it is pumping
    # so rebalance directives (local or gossiped) can find them.
    streams = StreamDirectory(RebalanceConfig.from_env())

    state = AppState(
        config=config, db=db, registry=registry, load_manager=load_manager,
        admission=admission, events=events, gate=gate, audit=audit, users=users, api_keys=api_keys,
        invitations=invitations, jwt_secret=jwt_secret, http=http,
        metrics=metrics, traces=traces, resilience=resilience, faults=faults,
        ratelimit=ratelimit, worker=worker, history=history, streams=streams,
    )

    _seed_tps_from_daily_stats(state)

    # Gossip replication between sibling workers — and, when
    # LLMLB_GOSSIP_BIND configures the mesh, across hosts (LLMLB_GOSSIP=0
    # disables both; a single-worker gateway with no mesh has no peers and
    # skips it entirely). All replicated state is advisory: breakers, TPS,
    # retry budget, affinity pins, adapter residency, heat, rate-limit
    # spend, registry cache coherence — each converges locally without it.
    mesh = MeshConfig.from_env()
    if (worker.multi or mesh.enabled) and env_bool("LLMLB_GOSSIP", True):
        state.gossip = await _start_gossip(state, mesh)

    if start_background:
        audit.start()
        history.start()
        if worker.multi:
            interval = env_float(
                "LLMLB_METRICS_SPOOL_SECS", METRICS_SPOOL_DEFAULT_S
            )
            state._tasks.append(asyncio.create_task(
                _metrics_spool_loop(state, max(0.2, interval)),
                name="metrics-spool",
            ))
        # Single-writer discipline (docs/deployment.md): the pull health
        # checker, the hourly maintenance loop, and (in server.py) the
        # update manager's background work run in the elected primary
        # worker only — N workers probing every engine would multiply
        # fleet-wide probe load by N for zero information.
        if worker.is_primary:
            checker = EndpointHealthChecker(
                registry, load_manager, db, http, events,
                interval_s=config.health_check_interval_s,
                timeout_s=config.health_check_timeout_s,
                resilience=resilience,
            )
            checker.start()
            checker.gossip = state.gossip  # residency push (health.py)
            state.health_checker = checker
            state._tasks.append(
                asyncio.create_task(_maintenance_loop(state),
                                    name="gw-maintenance")
            )
            # Proactive rebalancer (gateway/rebalance.py): same primary-only
            # single-writer discipline as the probe loop it reads from.
            rb = Rebalancer(
                registry, load_manager, streams, metrics=metrics,
                gossip=state.gossip, config=streams.config,
            )
            rb.start()
            state.rebalancer = rb
    return state


async def _start_gossip(state: AppState,
                        mesh: MeshConfig | None = None) -> GossipBus:
    """Bind this worker's bus socket (plus the UDP/TCP mesh when configured)
    and wire every replicated-state hook. Receivers apply via
    ``apply_remote_*`` entry points that never re-publish, so a two-worker
    group cannot ping-pong a message forever. Conflict resolution is the
    (seq, origin) version in ``m["ver"]`` — never the wall stamp."""
    mesh = mesh or MeshConfig.from_env()
    db = state.db
    membership = register = None
    if mesh.enabled:
        # Membership from the endpoint-registry database: every host
        # persists its advertised mesh address under a settings key, so any
        # host that can reach the shared DB finds the fleet without config.
        def membership() -> dict:
            return {
                key[len(MEMBER_KEY_PREFIX):]: value
                for key, value in db.list_settings().items()
                if key.startswith(MEMBER_KEY_PREFIX) and value
            }

        def register(origin: str, advertise: str) -> None:
            db.set_setting(MEMBER_KEY_PREFIX + origin, advertise)

    bus = GossipBus(
        default_gossip_dir(state.config.port), state.worker.index,
        expected_peers=state.worker.count - 1,
        mesh=mesh, faults=GossipFaults.from_env(),
        membership=membership, register=register,
    )
    await bus.start()
    lm = state.load_manager
    resilience = state.resilience
    registry = state.registry
    bus.on_lag = state.metrics.observe_gossip_lag

    lm.gossip = bus
    bus.subscribe("tps", lambda d, m: lm.apply_remote_tps(
        d["eid"], d["model"], d["kind"], float(d["ema"]),
        int(d.get("samples", 1)), m["ver"],
    ))
    bus.subscribe("tps_clear", lambda d, m: lm.apply_remote_tps_clear(
        d["eid"], m["ver"],
    ))
    bus.subscribe("affinity", lambda d, m: lm.apply_remote_affinity(
        d["model"], d["hash"], d["eid"], m["ver"],
    ))
    bus.subscribe("heat", lambda d, m: lm.apply_remote_heat(
        d["model"], d.get("entries") or {}, m["ver"],
    ))
    if resilience is not None:
        resilience.gossip = bus
        resilience.budget.on_spend = lambda: bus.publish("retry_spend", {})
        bus.subscribe("breaker", lambda d, m: resilience.apply_remote_breaker(
            d["eid"], d["to"], float(d.get("remaining_s", 0.0)),
            d.get("reason"), m["ver"],
        ))
        bus.subscribe(
            "retry_spend",
            lambda d, m: resilience.budget.note_remote_spend(),
        )
    registry.on_mutate = lambda: bus.publish("registry", {})
    bus.subscribe("registry", lambda d, m: registry.reload())
    # Event-driven adapter residency: the primary's probe loop pushes
    # resident-set changes; siblings patch their model cache immediately
    # instead of waiting out a full registry reload round.
    bus.subscribe("residency", lambda d, m: registry.apply_residency(
        d["eid"], list((d.get("adapters") or {})),
    ))
    # Global token buckets: admission spend replicates fleet-wide so a
    # tenant at rps=N is admitted ≈N across all workers, not N×workers.
    if state.ratelimit is not None and state.ratelimit.enabled:
        state.ratelimit.attach_gossip(bus)
    # Rebalance directives from the (possibly remote) primary: mark up to
    # max_streams of OUR live streams on the source endpoint for migration.
    streams = state.streams
    if streams is not None and streams.config.enabled:
        bus.subscribe("migrate", lambda d, m: streams.apply_directive(
            d["eid"], d["target"], d.get("reason") or "hotspot",
            int(d.get("max_streams", 1)), int(d.get("directive_id", 0)),
        ))
    return bus


def _seed_tps_from_daily_stats(state: AppState) -> None:
    """Warm-start the TPS tracker from today's persisted stats
    (bootstrap.rs:142-159)."""
    today = datetime.date.today().isoformat()
    rows = state.db.query(
        """SELECT endpoint_id, model, api_kind, completion_tokens,
                  total_duration_ms, request_count
           FROM endpoint_daily_stats WHERE date=? AND request_count>0""",
        (today,),
    )
    for r in rows:
        if r["total_duration_ms"] and r["completion_tokens"]:
            tps = r["completion_tokens"] / (r["total_duration_ms"] / 1000.0)
            try:
                kind = TpsApiKind(r["api_kind"])
            except ValueError:
                kind = TpsApiKind.OTHER
            state.load_manager.seed_tps(
                r["endpoint_id"], r["model"], kind, tps,
                samples=r["request_count"],
            )


def gateway_exposition(state: AppState) -> str:
    """The gateway's full Prometheus text exposition: GatewayMetrics series
    plus scrape-time figures owned by the balancer, admission queue, event
    bus, and (multi-worker) the gossip bus."""
    affinity = state.load_manager.affinity_stats()
    counters = {
        "llmlb_gateway_dropped_events_total":
            state.events.dropped_events_total(),
        "llmlb_gateway_prefix_affinity_hits_total": affinity["hits_total"],
        "llmlb_gateway_prefix_affinity_misses_total":
            affinity["misses_total"],
        "llmlb_gateway_prefix_affinity_evictions_total":
            affinity["evictions_total"],
    }
    gauges = {
        "llmlb_gateway_active_requests": state.load_manager.total_active(),
        "llmlb_gateway_admission_queue_depth": state.admission.queue_depth(),
        "llmlb_gateway_traces_buffered": len(state.traces),
        "llmlb_gateway_prefix_affinity_entries": affinity["entries"],
    }
    if state.gossip is not None:
        gs = state.gossip.stats()
        counters["llmlb_gateway_gossip_messages_sent_total"] = gs["sent_total"]
        counters["llmlb_gateway_gossip_messages_received_total"] = (
            gs["received_total"]
        )
        counters["llmlb_gateway_gossip_send_errors_total"] = (
            gs["send_errors_total"]
        )
        counters["llmlb_gateway_gossip_rejected_total"] = (
            gs["recv_rejected_total"]
        )
        counters["llmlb_gateway_gossip_fault_dropped_total"] = (
            gs["fault_dropped_total"]
        )
        gauges["llmlb_gateway_gossip_peers"] = (
            gs["peers"] + gs["mesh_peers"]
        )
        gauges["llmlb_gateway_gossip_partition_suspected"] = (
            1 if gs["partition_suspected"] else 0
        )
        if gs["lag_s"] is not None:
            gauges["llmlb_gateway_gossip_lag_seconds"] = round(gs["lag_s"], 6)
    if state.rebalancer is not None:
        rb = state.rebalancer.snapshot()
        counters["llmlb_gateway_rebalance_directives_total"] = (
            rb["directives_total"]
        )
        gauges["llmlb_gateway_rebalance_inflight"] = rb["inflight"]
    return state.metrics.render(counters=counters, gauges=gauges)


# Each worker spools its worker-labeled exposition to a shared file this
# often; the worker that receives a /metrics scrape (SO_REUSEPORT picks one
# arbitrarily) merges its siblings' spools, so Prometheus always sees the
# whole group no matter which accept queue the scrape landed in.
METRICS_SPOOL_DEFAULT_S = 5.0


def _metrics_spool_path(state: AppState, index: int) -> str:
    return os.path.join(
        default_gossip_dir(state.config.port), f"metrics-w{index}.prom"
    )


def write_metrics_spool(state: AppState,
                        labeled_text: str | None = None) -> None:
    """Spool this worker's worker-labeled exposition for siblings to
    merge. The /metrics handler passes the text it just rendered so a
    scrape builds the exposition once, not twice."""
    from llmlb_tpu.gateway.metrics import label_exposition

    path = _metrics_spool_path(state, state.worker.index)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if labeled_text is None:
        labeled_text = label_exposition(
            gateway_exposition(state), "worker", state.worker.label
        )
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        f.write(labeled_text)
    os.replace(tmp, path)  # atomic: a scrape never reads a torn file


def read_peer_metrics(state: AppState, max_age_s: float) -> str:
    """Concatenated sibling expositions (comment lines stripped — the
    serving worker's own exposition already declared the families; Prom
    treats the peers' samples as additional series via their worker
    label). Stale spools (dead worker) age out instead of freezing."""
    import glob as _glob

    own = _metrics_spool_path(state, state.worker.index)
    parts: list[str] = []
    now = time.time()
    for path in sorted(_glob.glob(
        os.path.join(default_gossip_dir(state.config.port), "metrics-w*.prom")
    )):
        if path == own:
            continue
        try:
            if now - os.path.getmtime(path) > max_age_s:
                continue
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        parts.append("\n".join(
            line for line in text.splitlines()
            if line and not line.startswith("#")
        ))
    return ("\n".join(parts) + "\n") if parts else ""


async def _metrics_spool_loop(state: AppState, interval_s: float) -> None:
    while True:
        try:
            write_metrics_spool(state)
        except Exception:
            log.exception("metrics spool write failed")
        await asyncio.sleep(interval_s)


async def _maintenance_loop(state: AppState) -> None:
    """Hourly: request-history retention cleanup + periodic audit verify
    (reference: cleanup task bootstrap.rs:161, audit verify :211-265)."""
    while True:
        await asyncio.sleep(3600)
        try:
            cutoff = time.time() - state.config.request_history_retention_days * 86400
            state.db.execute("DELETE FROM request_history WHERE ts < ?", (cutoff,))
            ok, err = state.audit.verify()
            if not ok:
                log.error("periodic audit verification failed: %s", err)
        except Exception:
            log.exception("maintenance cycle failed")


def record_daily_stat(
    state: AppState,
    endpoint_id: str,
    model: str,
    api_kind: TpsApiKind,
    *,
    error: bool = False,
    prompt_tokens: int = 0,
    completion_tokens: int = 0,
    duration_ms: float = 0.0,
) -> None:
    today = datetime.date.today().isoformat()
    state.history.add_daily(
        (endpoint_id, today, model, api_kind.value, int(error),
         prompt_tokens, completion_tokens, duration_ms),
    )
