"""AppState: shared state injected into all handlers + bootstrap sequence.

Parity with reference lib.rs:106-141 (AppState) and bootstrap.rs:42-345
(initialize): DB + schema, registry cache load, LoadManager seeding from daily
stats, shared HTTP client, admin bootstrap, JWT secret provisioning, audit init
+ startup chain verification, health checker, background maintenance tasks.
"""

from __future__ import annotations

import asyncio
import dataclasses
import datetime
import logging
import secrets
import time

import aiohttp

from llmlb_tpu.gateway.audit import AuditLog
from llmlb_tpu.gateway.auth import (
    ApiKeyStore,
    InvitationStore,
    UserStore,
    ensure_admin_exists,
)
from llmlb_tpu.gateway.balancer import AdmissionQueue, LoadManager
from llmlb_tpu.gateway.config import (
    QueueConfig,
    ResilienceConfig,
    ServerConfig,
    SloConfig,
    env_int,
)
from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.events import DashboardEventBus
from llmlb_tpu.gateway.faults import FaultInjector
from llmlb_tpu.gateway.gate import InferenceGate
from llmlb_tpu.gateway.health import EndpointHealthChecker
from llmlb_tpu.gateway.metrics import GatewayMetrics
from llmlb_tpu.gateway.registry import EndpointRegistry
from llmlb_tpu.gateway.resilience import ResilienceManager
from llmlb_tpu.gateway.tracing import TraceStore
from llmlb_tpu.gateway.types import TpsApiKind

log = logging.getLogger("llmlb_tpu.gateway")


@dataclasses.dataclass
class AppState:
    config: ServerConfig
    db: Database
    registry: EndpointRegistry
    load_manager: LoadManager
    admission: AdmissionQueue
    events: DashboardEventBus
    gate: InferenceGate
    audit: AuditLog
    users: UserStore
    api_keys: ApiKeyStore
    invitations: InvitationStore
    jwt_secret: str
    http: aiohttp.ClientSession
    metrics: GatewayMetrics
    traces: TraceStore
    resilience: ResilienceManager | None = None
    faults: FaultInjector | None = None
    health_checker: EndpointHealthChecker | None = None
    update_manager: object | None = None  # set by gateway.update
    tray: object | None = None  # TrayController when LLMLB_TRAY=1
    started_at: float = dataclasses.field(default_factory=time.time)
    _tasks: list[asyncio.Task] = dataclasses.field(default_factory=list)

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        if self.health_checker:
            await self.health_checker.stop()
        await self.audit.stop()
        await self.http.close()
        self.db.close()


async def build_app_state(
    config: ServerConfig | None = None,
    *,
    db: Database | None = None,
    start_background: bool = True,
) -> AppState:
    config = config or ServerConfig.from_env()
    if db is None:
        db = Database(config.database_url or ":memory:")

    registry = EndpointRegistry(db)
    load_manager = LoadManager(QueueConfig.from_env())
    admission = AdmissionQueue(load_manager)
    events = DashboardEventBus()
    gate = InferenceGate()
    audit = AuditLog(db)
    # SLO targets ride inside the metrics registry: every proxy path that
    # finishes a successful request judges it there (record_slo)
    metrics = GatewayMetrics(slo=SloConfig.from_env())
    admission.metrics = metrics  # admission-retry counter (balancer.py)
    traces = TraceStore(capacity=env_int("LLMLB_TRACE_BUFFER", 256),
                        events=events)

    users = UserStore(db)
    api_keys = ApiKeyStore(db)
    invitations = InvitationStore(db)

    # admin bootstrap (reference auth/bootstrap.rs)
    admin, generated = ensure_admin_exists(
        users, config.admin_username, config.admin_password
    )
    if generated:
        log.warning(
            "bootstrap admin %r created with generated password: %s "
            "(change it on first login)",
            admin.username, generated,
        )

    # JWT secret: env > persisted setting > fresh random (persisted)
    jwt_secret = config.jwt_secret or db.get_setting("auth.jwt_secret")
    if not jwt_secret:
        jwt_secret = secrets.token_urlsafe(32)
        db.set_setting("auth.jwt_secret", jwt_secret)

    # startup audit chain verification (bootstrap.rs:211-265)
    ok, err = audit.verify()
    if not ok:
        log.error("AUDIT CHAIN VERIFICATION FAILED: %s", err)

    http = aiohttp.ClientSession(
        connector=aiohttp.TCPConnector(limit_per_host=32, keepalive_timeout=60)
    )

    # Resilience layer: per-endpoint circuit breakers + the global retry
    # budget; selection consults it through load_manager.resilience. The
    # fault injector is None unless LLMLB_FAULTS configures rules (or a
    # chaos test installs them) — zero hot-path cost otherwise.
    resilience = ResilienceManager(
        ResilienceConfig.from_env(), metrics=metrics, events=events,
        registry=registry,
    )
    load_manager.resilience = resilience
    faults = FaultInjector.from_env()

    state = AppState(
        config=config, db=db, registry=registry, load_manager=load_manager,
        admission=admission, events=events, gate=gate, audit=audit, users=users, api_keys=api_keys,
        invitations=invitations, jwt_secret=jwt_secret, http=http,
        metrics=metrics, traces=traces, resilience=resilience, faults=faults,
    )

    _seed_tps_from_daily_stats(state)

    if start_background:
        audit.start()
        checker = EndpointHealthChecker(
            registry, load_manager, db, http, events,
            interval_s=config.health_check_interval_s,
            timeout_s=config.health_check_timeout_s,
            resilience=resilience,
        )
        checker.start()
        state.health_checker = checker
        state._tasks.append(
            asyncio.create_task(_maintenance_loop(state), name="gw-maintenance")
        )
    return state


def _seed_tps_from_daily_stats(state: AppState) -> None:
    """Warm-start the TPS tracker from today's persisted stats
    (bootstrap.rs:142-159)."""
    today = datetime.date.today().isoformat()
    rows = state.db.query(
        """SELECT endpoint_id, model, api_kind, completion_tokens,
                  total_duration_ms, request_count
           FROM endpoint_daily_stats WHERE date=? AND request_count>0""",
        (today,),
    )
    for r in rows:
        if r["total_duration_ms"] and r["completion_tokens"]:
            tps = r["completion_tokens"] / (r["total_duration_ms"] / 1000.0)
            try:
                kind = TpsApiKind(r["api_kind"])
            except ValueError:
                kind = TpsApiKind.OTHER
            state.load_manager.seed_tps(
                r["endpoint_id"], r["model"], kind, tps,
                samples=r["request_count"],
            )


async def _maintenance_loop(state: AppState) -> None:
    """Hourly: request-history retention cleanup + periodic audit verify
    (reference: cleanup task bootstrap.rs:161, audit verify :211-265)."""
    while True:
        await asyncio.sleep(3600)
        try:
            cutoff = time.time() - state.config.request_history_retention_days * 86400
            state.db.execute("DELETE FROM request_history WHERE ts < ?", (cutoff,))
            ok, err = state.audit.verify()
            if not ok:
                log.error("periodic audit verification failed: %s", err)
        except Exception:
            log.exception("maintenance cycle failed")


def record_daily_stat(
    state: AppState,
    endpoint_id: str,
    model: str,
    api_kind: TpsApiKind,
    *,
    error: bool = False,
    prompt_tokens: int = 0,
    completion_tokens: int = 0,
    duration_ms: float = 0.0,
) -> None:
    today = datetime.date.today().isoformat()
    state.db.execute(
        """INSERT INTO endpoint_daily_stats
           (endpoint_id, date, model, api_kind, request_count, error_count,
            prompt_tokens, completion_tokens, total_duration_ms)
           VALUES (?,?,?,?,1,?,?,?,?)
           ON CONFLICT(endpoint_id, date, model, api_kind) DO UPDATE SET
               request_count = request_count + 1,
               error_count = error_count + excluded.error_count,
               prompt_tokens = prompt_tokens + excluded.prompt_tokens,
               completion_tokens = completion_tokens + excluded.completion_tokens,
               total_duration_ms = total_duration_ms + excluded.total_duration_ms""",
        (endpoint_id, today, model, api_kind.value, int(error),
         prompt_tokens, completion_tokens, duration_ms),
    )
