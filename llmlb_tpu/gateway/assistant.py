"""Assistant CLI: `llmlb assistant curl|openapi|guide`.

Parity with reference cli/assistant.rs (~1.5k LoC): a safe way for operators
(and LLM agents driving a shell) to poke the gateway API —
- `curl`: executes a curl-like command with injection prevention (shell
  metacharacters and file/credential-touching curl options rejected), a host
  whitelist pinned to the router URL (:442-450), automatic auth-header
  injection from the environment, and secret masking in everything echoed
  back (:635-649). The request itself is made with urllib — no shell, no
  curl binary — so the forbidden-pattern screen is defense in depth, not the
  only wall.
- `openapi`: a machine-readable summary of the API surface.
- `guide`: built-in usage guides per topic.
"""

from __future__ import annotations

import json
import os
import re
import shlex
import sys
import urllib.error
import urllib.parse
import urllib.request

DEFAULT_ROUTER_URL = "http://localhost:32768"
DEFAULT_TIMEOUT_S = 30.0
MAX_TIMEOUT_S = 300.0
LOCALHOST_HOSTNAMES = ("localhost", "127.0.0.1", "::1")

# Shell metacharacters and redirections have no business in a curl line we
# were handed as data (parity: FORBIDDEN_PATTERNS, assistant.rs:53-63).
_FORBIDDEN_PATTERNS = [
    re.compile(r"[;&|`]"),
    re.compile(r"\$\("),
    re.compile(r"\$\{"),
    re.compile(r">>|>\s*[/~]|<\s*[/~]"),
]

# curl options that write files, read local config, or leak credentials
# (parity: FORBIDDEN_OPTIONS, assistant.rs:28-51).
_FORBIDDEN_OPTIONS = {
    "-o", "--output", "-O", "--remote-name", "-K", "--config", "-q",
    "--disable", "-u", "--user", "--netrc", "--netrc-file",
    "--netrc-optional", "--delegation", "--libcurl", "--trace",
    "--trace-ascii", "--trace-time", "--proto", "--proto-default",
    "--proto-redir", "-T", "--upload-file", "-F", "--form",
}

_BEARER_RE = re.compile(r"(Bearer\s+)[A-Za-z0-9._\-]+")
_XAPIKEY_RE = re.compile(r"((?:x-api-key|X-API-Key)\s*:\s*)\S+")
_SK_RE = re.compile(r"sk_[A-Za-z0-9]+")


def mask_sensitive(text: str) -> str:
    """Secrets never round-trip through echoed output (assistant.rs:635-649)."""
    text = _BEARER_RE.sub(r"\1***", text)
    text = _XAPIKEY_RE.sub(r"\1***", text)
    return _SK_RE.sub("sk_***", text)


class CurlRejected(ValueError):
    pass


def parse_curl(command: str, router_url: str) -> dict:
    """Parse a restricted curl grammar into a request spec, rejecting
    anything that could touch the shell, the filesystem, or foreign hosts."""
    for pat in _FORBIDDEN_PATTERNS:
        if pat.search(command):
            raise CurlRejected(
                "command contains shell metacharacters or redirection"
            )
    try:
        tokens = shlex.split(command)
    except ValueError as e:
        raise CurlRejected(f"unparseable command: {e}")
    if not tokens or tokens[0] != "curl":
        raise CurlRejected("command must start with 'curl'")

    spec = {"method": None, "headers": {}, "data": None, "url": None,
            "timeout": DEFAULT_TIMEOUT_S}

    def arg_after(idx: int, opt: str) -> str:
        if idx + 1 >= len(tokens):
            raise CurlRejected(f"curl option {opt!r} is missing its argument")
        return tokens[idx + 1]

    i = 1
    while i < len(tokens):
        tok = tokens[i]
        if tok in _FORBIDDEN_OPTIONS or tok.split("=", 1)[0] in _FORBIDDEN_OPTIONS:
            raise CurlRejected(f"curl option {tok!r} is not allowed")
        if tok in ("-X", "--request"):
            spec["method"] = arg_after(i, tok).upper()
            i += 2
        elif tok in ("-H", "--header"):
            name, _, value = arg_after(i, tok).partition(":")
            spec["headers"][name.strip()] = value.strip()
            i += 2
        elif tok in ("-d", "--data", "--data-raw", "--data-binary",
                     "--data-ascii", "--json"):
            body = arg_after(i, tok)
            if body.startswith("@"):
                raise CurlRejected("reading request bodies from files ('@') "
                                   "is not allowed")
            spec["data"] = body
            if tok == "--json" and not any(
                h.lower() == "content-type" for h in spec["headers"]
            ):
                spec["headers"]["Content-Type"] = "application/json"
            i += 2
        elif tok in ("-m", "--max-time"):
            raw = arg_after(i, tok)
            try:
                spec["timeout"] = min(MAX_TIMEOUT_S, max(1.0, float(raw)))
            except ValueError:
                raise CurlRejected(f"invalid --max-time value {raw!r}")
            i += 2
        elif tok in ("-s", "--silent", "-S", "--show-error", "-i",
                     "--include", "-L", "--location", "-k", "--insecure",
                     "-v", "--verbose", "--compressed", "-g", "--globoff"):
            i += 1  # tolerated no-ops
        elif tok.startswith("-"):
            raise CurlRejected(f"unsupported curl option {tok!r}")
        else:
            if spec["url"] is not None:
                raise CurlRejected("multiple URLs in one command")
            spec["url"] = tok
            i += 1

    if not spec["url"]:
        raise CurlRejected("no URL in command")
    spec["url"] = _validate_url(spec["url"], router_url)
    if spec["method"] is None:
        spec["method"] = "POST" if spec["data"] is not None else "GET"
    return spec


def _validate_url(url: str, router_url: str) -> str:
    """Host whitelist: the router's own host (+ localhost aliases when the
    router is local) — the assistant never talks to foreign hosts
    (assistant.rs:442-450). Bare paths are resolved against the router."""
    if url.startswith("/"):
        return router_url.rstrip("/") + url
    parsed = urllib.parse.urlparse(url)
    if parsed.scheme not in ("http", "https"):
        raise CurlRejected(f"scheme {parsed.scheme!r} is not allowed")
    router = urllib.parse.urlparse(router_url)
    allowed = {router.hostname}
    if router.hostname in LOCALHOST_HOSTNAMES:
        allowed.update(LOCALHOST_HOSTNAMES)
    if parsed.hostname not in allowed:
        raise CurlRejected(
            f"host {parsed.hostname!r} is not the router "
            f"({router.hostname!r}); refusing"
        )
    port = parsed.port or (443 if parsed.scheme == "https" else 80)
    router_port = router.port or (443 if router.scheme == "https" else 80)
    if port != router_port:
        raise CurlRejected(
            f"port {port} is not the router port ({router_port}); refusing"
        )
    return url


def run_curl(command: str, router_url: str | None = None,
             api_key: str | None = None) -> dict:
    """Execute the sanitized request (urllib — no shell, no curl binary).
    Returns {status, body, executed_command} with secrets masked."""
    router_url = router_url or os.environ.get(
        "LLMLB_ROUTER_URL", DEFAULT_ROUTER_URL
    )
    spec = parse_curl(command, router_url)

    # auto-auth: inject the operator's key when the command carries none
    if api_key is None:
        api_key = os.environ.get("LLMLB_API_KEY") or os.environ.get(
            "LLMLB_TOKEN"
        )
    has_auth = any(h.lower() in ("authorization", "x-api-key")
                   for h in spec["headers"])
    if api_key and not has_auth:
        spec["headers"]["Authorization"] = f"Bearer {api_key}"

    data = spec["data"].encode() if spec["data"] is not None else None
    # case-insensitive: urllib canonicalizes header names, so a check on the
    # exact spelling would clobber a user-supplied 'content-type: …'
    if data is not None and not any(
        h.lower() == "content-type" for h in spec["headers"]
    ):
        spec["headers"]["Content-Type"] = "application/json"
    req = urllib.request.Request(
        spec["url"], data=data, method=spec["method"],
        headers=spec["headers"],
    )

    class _NoRedirect(urllib.request.HTTPRedirectHandler):
        # urllib would forward the injected Authorization header to whatever
        # host a 3xx points at — a credential exfil channel past the host
        # whitelist. Surface the redirect instead of following it.
        def redirect_request(self, *a, **k):
            return None

    opener = urllib.request.build_opener(_NoRedirect)
    try:
        with opener.open(req, timeout=spec["timeout"]) as resp:
            body = resp.read().decode("utf-8", "replace")
            status = resp.status
    except urllib.error.HTTPError as e:
        body = e.read().decode("utf-8", "replace")
        status = e.code
    except (urllib.error.URLError, OSError) as e:
        return {
            "status": None,
            "error": str(getattr(e, "reason", e)),
            "executed_command": mask_sensitive(command),
        }
    return {
        "status": status,
        "body": body[:65536],
        "executed_command": mask_sensitive(command),
    }


# --------------------------------------------------------------------- openapi

def openapi_summary() -> dict:
    """Machine-readable sketch of the API surface (enough for an agent to
    orient; the dashboard and guides carry the human detail)."""
    return {
        "openapi": "3.0.0",
        "info": {"title": "llmlb-tpu gateway", "version": "1"},
        "paths": {
            "/v1/chat/completions": {"post": {
                "summary": "OpenAI-compatible chat (SSE when stream=true)"}},
            "/v1/completions": {"post": {"summary": "legacy completions"}},
            "/v1/responses": {"post": {"summary": "responses API"}},
            "/v1/embeddings": {"post": {"summary": "embeddings"}},
            "/v1/models": {"get": {"summary": "models served by any online endpoint"}},
            "/v1/messages": {"post": {"summary": "Anthropic Messages adapter"}},
            "/v1/audio/transcriptions": {"post": {"summary": "ASR (multipart)"}},
            "/v1/audio/speech": {"post": {"summary": "TTS"}},
            "/v1/images/generations": {"post": {"summary": "image generation"}},
            "/api/auth/login": {"post": {"summary": "JWT + cookie session"}},
            "/api/endpoints": {"get": {"summary": "list endpoints"},
                               "post": {"summary": "register endpoint"}},
            "/api/api-keys": {"post": {"summary": "create scoped API key"}},
            "/api/audit-log": {"get": {"summary": "FTS audit search"}},
            "/api/dashboard/overview": {"get": {"summary": "serving overview"}},
            "/api/benchmarks/tps": {"post": {"summary": "TPS benchmark run"}},
            "/api/system/update/check": {"post": {"summary": "release check"}},
        },
    }


# ---------------------------------------------------------------------- guides

GUIDES = {
    "quickstart": """\
llmlb-tpu quickstart
  1. serve the gateway:   llmlb serve --port 32768
  2. serve a TPU engine:  python -m llmlb_tpu.engine.server --preset llama-3-8b
  3. register it:         llmlb assistant curl "curl -X POST /api/endpoints \
-d '{\\"base_url\\": \\"http://127.0.0.1:8100\\"}'"
  4. chat through it:     llmlb assistant curl "curl /v1/models"
Set LLMLB_API_KEY (an sk_... key) or LLMLB_TOKEN (a JWT) for auto-auth.""",
    "auth": """\
auth guide
  - POST /api/auth/login {username,password} -> {token} + session cookies
  - API keys: POST /api/api-keys {name, permissions:[...]} (admin)
    scopes: openai.inference, openai.models.read, endpoints.read,
            endpoints.manage, users.manage, invitations.manage,
            logs.read, metrics.read, registry.read
  - /v1/* accepts ONLY header auth (Bearer sk_... or JWT); browser cookies
    work on /api/* behind CSRF (x-csrf-token header = llmlb_csrf cookie).""",
    "endpoints": """\
endpoints guide
  - register:  POST /api/endpoints {base_url, endpoint_type?, api_key?}
    types auto-detected in priority order: tpu, xllm, ollama, vllm,
    lm_studio, llama_cpp, openai_compatible
  - test:      POST /api/endpoints/{id}/test
  - sync:      POST /api/endpoints/{id}/sync (pull /v1/models)
  - health:    checked every 30s; 2 strikes -> offline; TPU engines report
    chip/HBM + queue telemetry that demotes pressured endpoints.""",
    "serving": """\
serving guide (tpu:// engine)
  - python -m llmlb_tpu.engine.server --preset llama-3-8b --checkpoint DIR
  - continuous batching over slot cache; chunked prefill beyond the largest
    bucket; --slot-capacity 4096 default (see scheduler.kv_cache_bytes)
  - multi-host: LLMLB_COORDINATOR/LLMLB_NUM_HOSTS/LLMLB_HOST_ID (leader
    serves HTTP, followers run the lockstep loop)
  - metrics: GET /metrics (Prometheus), GET /api/health (JSON).""",
    "update": """\
self-update guide
  - env: LLMLB_UPDATE_REPO=owner/name, LLMLB_UPDATE_ARTIFACT=/path/to/app
  - POST /api/system/update/check -> {available, version}
  - POST /api/system/update/apply {force?} -> drain (503 on /v1/*) -> swap
    with .bak -> exit for supervisor restart -> 30s health watch; unhealthy
    rolls back from .bak and blocklists the release.""",
}


def main(argv: list[str]) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: llmlb assistant {curl,openapi,guide} ...\n"
              f"guides: {', '.join(sorted(GUIDES))}")
        return 0
    cmd, rest = argv[0], argv[1:]
    if cmd == "curl":
        if not rest:
            print("usage: llmlb assistant curl \"curl ... URL\"",
                  file=sys.stderr)
            return 2
        try:
            result = run_curl(" ".join(rest))
        except CurlRejected as e:
            print(json.dumps({"rejected": str(e)}), file=sys.stderr)
            return 2
        print(json.dumps(result, indent=2))
        return 0 if result.get("status") and result["status"] < 400 else 1
    if cmd == "openapi":
        print(json.dumps(openapi_summary(), indent=2))
        return 0
    if cmd == "guide":
        topic = rest[0] if rest else "quickstart"
        if topic not in GUIDES:
            print(f"unknown guide {topic!r}; available: "
                  f"{', '.join(sorted(GUIDES))}", file=sys.stderr)
            return 2
        print(GUIDES[topic])
        return 0
    print(f"unknown assistant command {cmd!r}", file=sys.stderr)
    return 2
