"""Tamper-evident audit log: capture middleware + batched writer + hash chain.

Parity with reference audit/ (middleware.rs:51-130 outermost capture,
writer.rs:48-63 batched async writer, hash_chain.rs:33-91 SHA-256 chain over
batches, verified at startup and periodically per bootstrap.rs:211-265).
Each flushed batch's hash covers its entries plus the previous batch hash, so
any retro-edit of a persisted entry breaks verification from that batch on.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import logging
import time

from llmlb_tpu.gateway.db import Database

log = logging.getLogger("llmlb_tpu.gateway.audit")

GENESIS_HASH = "0" * 64
FLUSH_INTERVAL_S = 1.0
FLUSH_MAX_ENTRIES = 64


def fts_quote(q: str) -> str:
    """Quote each whitespace-separated term so user input is matched as plain
    terms (AND semantics), never parsed as FTS5 syntax (NEAR, *, ^, etc.).
    Each term is a prefix query ("tok"*) so partial identifiers keep working
    the way the LIKE fallback's substring match mostly did."""
    terms = [t.replace('"', '""') for t in q.split()]
    return " ".join(f'"{t}"*' for t in terms if t)


@dataclasses.dataclass
class AuditEntry:
    ts: float
    method: str
    path: str
    status: int
    duration_ms: float
    actor: str | None = None
    actor_type: str | None = None  # "jwt" | "api_key" | "anonymous"
    ip: str | None = None
    detail: str | None = None

    def canonical(self) -> str:
        return json.dumps(
            [
                round(self.ts, 6), self.method, self.path, self.status,
                round(self.duration_ms, 3), self.actor or "", self.actor_type or "",
                self.ip or "", self.detail or "",
            ],
            separators=(",", ":"),
        )


def batch_hash(prev_hash: str, entries: list[AuditEntry]) -> str:
    canon = [e.canonical().encode() for e in entries]
    try:
        from llmlb_tpu.native import native_chain_hash

        digest = native_chain_hash(prev_hash, canon)
        if digest is not None:
            return digest
    except Exception:  # allow-silent: native lib unavailable/broken —
        pass               # the identical Python path below serves
    h = hashlib.sha256()
    h.update(prev_hash.encode())
    for c in canon:
        h.update(c)
    return h.hexdigest()


class AuditLog:
    """Batched writer with a SHA-256 hash chain over flushed batches."""

    def __init__(self, db: Database):
        self.db = db
        self._pending: list[AuditEntry] = []
        self._task: asyncio.Task | None = None
        self._closed = False

    # ------------------------------------------------------------- ingestion

    def record(self, entry: AuditEntry) -> None:
        if self._closed:
            return
        self._pending.append(entry)
        if len(self._pending) >= FLUSH_MAX_ENTRIES:
            self.flush()

    def start(self) -> None:
        self._task = asyncio.create_task(self._flush_loop(), name="audit-writer")

    async def stop(self) -> None:
        self._closed = True
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        self.flush()

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(FLUSH_INTERVAL_S)
            try:
                self.flush()
            except Exception:
                log.exception("audit flush failed")

    # ----------------------------------------------------------------- chain

    def _last_hash(self) -> str:
        row = self.db.query_one(
            "SELECT batch_hash FROM audit_batches ORDER BY id DESC LIMIT 1"
        )
        return row["batch_hash"] if row else GENESIS_HASH

    def flush(self) -> int | None:
        """Write pending entries as one chained batch; returns batch id.

        The prev-hash read and the batch insert run in one BEGIN IMMEDIATE
        transaction: with N gateway workers appending to one WAL file, two
        concurrent flushes would otherwise both read the same chain head and
        fork the hash chain (verify() would flag the second batch forever).
        """
        if not self._pending:
            return None
        entries, self._pending = self._pending, []
        with self.db.transaction():
            prev = self._last_hash()
            digest = batch_hash(prev, entries)
            cur = self.db.execute(
                """INSERT INTO audit_batches (batch_hash, prev_hash, entry_count,
                   created_at) VALUES (?,?,?,?)""",
                (digest, prev, len(entries), time.time()),
            )
            batch_id = cur.lastrowid
            self.db.executemany(
                """INSERT INTO audit_log (ts, method, path, status, duration_ms,
                   actor, actor_type, ip, detail, batch_id)
                   VALUES (?,?,?,?,?,?,?,?,?,?)""",
                [
                    (e.ts, e.method, e.path, e.status, e.duration_ms, e.actor,
                     e.actor_type, e.ip, e.detail, batch_id)
                    for e in entries
                ],
            )
        return batch_id

    # ----------------------------------------------------------------- query

    def search(
        self,
        q: str | None = None,
        actor: str | None = None,
        path_prefix: str | None = None,
        since: float | None = None,
        until: float | None = None,
        limit: int = 100,
        offset: int = 0,
    ) -> list[dict]:
        """Free-text `q` uses the FTS5 index over (path, actor, detail)
        (parity: db/audit_log.rs:82-98); LIKE fallback when sqlite lacks
        fts5. User text is quoted per-term so FTS operators can't inject."""
        clauses, params = [], []
        if q and q.strip():
            if getattr(self.db, "fts_enabled", False):
                clauses.append(
                    "id IN (SELECT rowid FROM audit_log_fts "
                    "WHERE audit_log_fts MATCH ?)"
                )
                params.append(fts_quote(q))
            else:
                clauses.append("(path LIKE ? OR detail LIKE ? OR actor LIKE ?)")
                like = f"%{q}%"
                params += [like, like, like]
        if actor:
            clauses.append("actor=?")
            params.append(actor)
        if path_prefix:
            clauses.append("path LIKE ?")
            params.append(path_prefix + "%")
        if since is not None:
            clauses.append("ts>=?")
            params.append(since)
        if until is not None:
            clauses.append("ts<=?")
            params.append(until)
        where = ("WHERE " + " AND ".join(clauses)) if clauses else ""
        rows = self.db.query(
            f"SELECT * FROM audit_log {where} ORDER BY ts DESC LIMIT ? OFFSET ?",
            tuple(params) + (limit, offset),
        )
        return [dict(r) for r in rows]

    def archive_older_than(self, cutoff_ts: float, archive_path: str) -> int:
        """Move old entries to a separate SQLite file (90-day archive parity,
        bootstrap.rs:267-318). Chain verification applies to live data only
        after archival, matching the reference's archive semantics."""
        import sqlite3

        rows = self.db.query(
            "SELECT * FROM audit_log WHERE ts < ? ORDER BY id", (cutoff_ts,)
        )
        if not rows:
            return 0
        archive = sqlite3.connect(archive_path)
        archive.execute(
            """CREATE TABLE IF NOT EXISTS audit_log (
                id INTEGER, ts REAL, method TEXT, path TEXT, status INTEGER,
                duration_ms REAL, actor TEXT, actor_type TEXT, ip TEXT,
                detail TEXT, batch_id INTEGER)"""
        )
        archive.executemany(
            "INSERT INTO audit_log VALUES (?,?,?,?,?,?,?,?,?,?,?)",
            [tuple(r) for r in rows],
        )
        archive.commit()
        archive.close()
        batch_ids = {r["batch_id"] for r in rows}
        self.db.execute("DELETE FROM audit_log WHERE ts < ?", (cutoff_ts,))
        # drop fully-archived batches from the chain head; re-anchor genesis
        for bid in sorted(b for b in batch_ids if b is not None):
            remaining = self.db.query_one(
                "SELECT COUNT(*) AS n FROM audit_log WHERE batch_id=?", (bid,)
            )
            if remaining and remaining["n"] == 0:
                self.db.execute("DELETE FROM audit_batches WHERE id=?", (bid,))
        self._reanchor()
        return len(rows)

    def _reanchor(self) -> None:
        """After archival the first remaining batch must link to genesis."""
        first = self.db.query_one(
            "SELECT id, prev_hash FROM audit_batches ORDER BY id LIMIT 1"
        )
        if first and first["prev_hash"] != GENESIS_HASH:
            # chain now starts mid-history; mark the anchor so verify() can
            # start from the stored prev_hash instead of genesis
            self.db.set_setting("audit.anchor_hash", first["prev_hash"])

    def verify(self) -> tuple[bool, str | None]:
        """Chain verification honoring a re-anchored head after archival."""
        anchor = self.db.get_setting("audit.anchor_hash") or GENESIS_HASH
        prev = anchor
        for batch in self.db.query("SELECT * FROM audit_batches ORDER BY id"):
            rows = self.db.query(
                "SELECT * FROM audit_log WHERE batch_id=? ORDER BY id",
                (batch["id"],),
            )
            entries = [
                AuditEntry(
                    ts=r["ts"], method=r["method"], path=r["path"],
                    status=r["status"], duration_ms=r["duration_ms"],
                    actor=r["actor"], actor_type=r["actor_type"], ip=r["ip"],
                    detail=r["detail"],
                )
                for r in rows
            ]
            if batch["prev_hash"] != prev:
                return False, f"batch {batch['id']}: broken chain link"
            if len(entries) != batch["entry_count"]:
                return False, f"batch {batch['id']}: entry count mismatch"
            digest = batch_hash(prev, entries)
            if digest != batch["batch_hash"]:
                return False, f"batch {batch['id']}: hash mismatch"
            prev = digest
        return True, None
