"""Authentication & authorization: JWT, passwords, API keys, middlewares.

Parity with reference auth/ (jwt.rs HS256 create/verify :21-95, password.rs
Argon2 + policy :17-50, common/auth.rs roles + sk_ keys with 9 permission
scopes :59-97, middleware.rs combined JWT-or-API-key guards :335-700,
bootstrap admin). JWT is implemented directly over hmac/sha256 (no external
dependency); API keys are stored as SHA-256 hashes with a display prefix.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import hmac
import json
import secrets
import sqlite3
import time
import uuid

from argon2 import PasswordHasher
from argon2.exceptions import VerifyMismatchError

from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.types import Permission, Role

_hasher = PasswordHasher()

JWT_TTL_S = 24 * 3600
MIN_PASSWORD_LENGTH = 8


class AuthError(Exception):
    pass


def constant_time_equal(a: bytes, b: bytes) -> bool:
    """Constant-time byte equality for auth tokens/signatures.

    hmac.compare_digest is already compiled constant-time C and beats a
    ctypes FFI round trip for digest-sized inputs, so it IS the hot path.
    native/router_core.cpp's ct_equal is the C twin for native-first
    callers, held bit-compatible by tests/test_native.py's parity case —
    this wrapper exists so every auth compare goes through one audited
    entry point rather than ad-hoc == comparisons."""
    return hmac.compare_digest(a, b)


# ---------------------------------------------------------------------- JWT


# Dashboard cookie names (parity: reference auth/mod.rs DASHBOARD_*_COOKIE).
JWT_COOKIE = "llmlb_jwt"
CSRF_COOKIE = "llmlb_csrf"


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def create_jwt(
    secret: str,
    user_id: str,
    username: str,
    role: Role,
    ttl_s: int = JWT_TTL_S,
    now: float | None = None,
) -> str:
    now = now if now is not None else time.time()
    header = {"alg": "HS256", "typ": "JWT"}
    payload = {
        "sub": user_id,
        "username": username,
        "role": role.value,
        "iat": int(now),
        "exp": int(now + ttl_s),
    }
    signing_input = (
        _b64url(json.dumps(header, separators=(",", ":")).encode())
        + "."
        + _b64url(json.dumps(payload, separators=(",", ":")).encode())
    )
    sig = hmac.new(secret.encode(), signing_input.encode(), hashlib.sha256).digest()
    return signing_input + "." + _b64url(sig)


def verify_jwt(secret: str, token: str, now: float | None = None) -> dict:
    now = now if now is not None else time.time()
    try:
        signing_input, sig_part = token.rsplit(".", 1)
        header_part, payload_part = signing_input.split(".", 1)
        header = json.loads(_b64url_decode(header_part))
        payload = json.loads(_b64url_decode(payload_part))
        sig = _b64url_decode(sig_part)
    except (ValueError, json.JSONDecodeError) as e:
        raise AuthError(f"malformed token: {e}") from None
    if header.get("alg") != "HS256":
        raise AuthError("unsupported JWT algorithm")
    expected = hmac.new(
        secret.encode(), signing_input.encode(), hashlib.sha256
    ).digest()
    if not constant_time_equal(sig, expected):
        raise AuthError("invalid JWT signature")
    if payload.get("exp", 0) < now:
        raise AuthError("token expired")
    return payload


# ------------------------------------------------------------------ password


def hash_password(password: str) -> str:
    return _hasher.hash(password)


def verify_password(password_hash: str, password: str) -> bool:
    try:
        return _hasher.verify(password_hash, password)
    except VerifyMismatchError:
        return False
    except Exception:
        return False


def validate_password_policy(password: str) -> None:
    """Minimum policy (parity: auth/password.rs:17-50)."""
    if len(password) < MIN_PASSWORD_LENGTH:
        raise AuthError(f"password must be at least {MIN_PASSWORD_LENGTH} characters")
    if not any(c.isdigit() for c in password):
        raise AuthError("password must contain a digit")
    if not any(c.isalpha() for c in password):
        raise AuthError("password must contain a letter")


# --------------------------------------------------------------------- users


@dataclasses.dataclass
class User:
    id: str
    username: str
    role: Role
    must_change_password: bool = False
    created_at: float = 0.0


class UserStore:
    def __init__(self, db: Database):
        self.db = db

    def create(
        self, username: str, password: str, role: Role,
        must_change_password: bool = False, enforce_policy: bool = True,
    ) -> User:
        if enforce_policy:
            validate_password_policy(password)
        if self.db.query_one("SELECT id FROM users WHERE username=?", (username,)):
            raise AuthError(f"user {username!r} already exists")
        now = time.time()
        user_id = uuid.uuid4().hex
        self.db.execute(
            """INSERT INTO users (id, username, password_hash, role,
               must_change_password, created_at, updated_at) VALUES (?,?,?,?,?,?,?)""",
            (user_id, username, hash_password(password), role.value,
             int(must_change_password), now, now),
        )
        return User(user_id, username, role, must_change_password, now)

    def authenticate(self, username: str, password: str) -> User | None:
        row = self.db.query_one("SELECT * FROM users WHERE username=?", (username,))
        if row is None or not verify_password(row["password_hash"], password):
            return None
        return self._to_user(row)

    def get(self, user_id: str) -> User | None:
        row = self.db.query_one("SELECT * FROM users WHERE id=?", (user_id,))
        return self._to_user(row) if row else None

    def get_by_username(self, username: str) -> User | None:
        row = self.db.query_one("SELECT * FROM users WHERE username=?", (username,))
        return self._to_user(row) if row else None

    def list(self) -> list[User]:
        return [self._to_user(r) for r in self.db.query("SELECT * FROM users")]

    def change_password(self, user_id: str, new_password: str) -> None:
        validate_password_policy(new_password)
        self.db.execute(
            """UPDATE users SET password_hash=?, must_change_password=0,
               updated_at=? WHERE id=?""",
            (hash_password(new_password), time.time(), user_id),
        )

    def set_role(self, user_id: str, role: Role) -> None:
        self.db.execute(
            "UPDATE users SET role=?, updated_at=? WHERE id=?",
            (role.value, time.time(), user_id),
        )

    def delete(self, user_id: str) -> bool:
        cur = self.db.execute("DELETE FROM users WHERE id=?", (user_id,))
        return cur.rowcount > 0

    @staticmethod
    def _to_user(row) -> User:
        return User(
            id=row["id"], username=row["username"], role=Role(row["role"]),
            must_change_password=bool(row["must_change_password"]),
            created_at=row["created_at"],
        )


def ensure_admin_exists(
    users: UserStore, username: str = "admin", password: str | None = None
) -> tuple[User, str | None]:
    """Bootstrap admin (parity: auth/bootstrap.rs). Returns (user,
    generated_password_or_None). A generated password forces a change on login."""
    existing = users.get_by_username(username)
    if existing:
        return existing, None
    generated = None
    if password is None:
        generated = secrets.token_urlsafe(12)
        password = generated
    try:
        user = users.create(
            username, password, Role.ADMIN,
            must_change_password=generated is not None, enforce_policy=False,
        )
    except (AuthError, sqlite3.IntegrityError):
        # multi-worker boot race: a sibling worker created the admin between
        # our existence check and the INSERT — adopt its row
        existing = users.get_by_username(username)
        if existing:
            return existing, None
        raise
    return user, generated


# ------------------------------------------------------------------ API keys


@dataclasses.dataclass
class ApiKey:
    id: str
    user_id: str
    name: str
    key_prefix: str
    permissions: list[Permission]
    created_at: float
    revoked: bool = False
    expires_at: float | None = None
    last_used_at: float | None = None


def _hash_key(raw: str) -> str:
    return hashlib.sha256(raw.encode()).hexdigest()


class ApiKeyStore:
    """API keys, stored as SHA-256 hashes.

    ``LLMLB_AUTH_CACHE_TTL`` (seconds) enables an in-memory verified-key
    cache: the proxy hot path then skips one SELECT and one last_used_at
    UPDATE per request. The price is bounded revocation latency — a
    revoked key keeps working for up to the TTL on workers other than the
    one that served the revoke (which invalidates its own cache
    immediately). Default: 0 (off, bit-identical historical behavior) for
    a single-worker gateway; 60 s with --workers > 1 — N workers must not
    serialize on the shared WAL writer lock once per request just to
    refresh a dashboard timestamp (docs/deployment.md). The env knob
    overrides either default (0 disables explicitly).
    """

    MULTI_WORKER_DEFAULT_TTL_S = 60.0

    def __init__(self, db: Database, cache_ttl_s: float | None = None):
        import threading

        self.db = db
        if cache_ttl_s is None:
            # standalone construction (scripts, tests): fall back to the
            # env-derived worker identity; build_app_state passes the TTL
            # explicitly from ITS WorkerInfo so in-process multi-worker
            # states agree with forked ones
            from llmlb_tpu.gateway.config import env_float
            from llmlb_tpu.gateway.worker import current_worker

            cache_ttl_s = env_float(
                "LLMLB_AUTH_CACHE_TTL",
                self.MULTI_WORKER_DEFAULT_TTL_S
                if current_worker().multi else 0.0,
            )
        self.cache_ttl_s = cache_ttl_s
        self._cache_lock = threading.Lock()
        # key_hash -> (ApiKey, cached_at, last_used_written_at)
        self._cache: dict[str, tuple[ApiKey, float, float]] = {}

    def create(
        self, user_id: str, name: str, permissions: list[Permission],
        expires_at: float | None = None,
    ) -> tuple[ApiKey, str]:
        """Returns (record, raw_key). The raw key (sk_...) is shown exactly once."""
        raw = "sk_" + secrets.token_urlsafe(32)
        key_id = uuid.uuid4().hex
        now = time.time()
        self.db.execute(
            """INSERT INTO api_keys (id, user_id, name, key_hash, key_prefix,
               permissions, created_at, expires_at) VALUES (?,?,?,?,?,?,?,?)""",
            (key_id, user_id, name, _hash_key(raw), raw[:11],
             json.dumps([p.value for p in permissions]), now, expires_at),
        )
        return (
            ApiKey(key_id, user_id, name, raw[:11], permissions, now,
                   expires_at=expires_at),
            raw,
        )

    def verify(self, raw: str) -> ApiKey | None:
        key_hash = _hash_key(raw)
        now = time.time()
        ttl = self.cache_ttl_s
        if ttl > 0:
            with self._cache_lock:
                got = self._cache.get(key_hash)
            if got is not None:
                key, cached_at, used_written_at = got
                if now - cached_at < ttl:
                    if key.expires_at is not None and key.expires_at < now:
                        return None
                    if now - used_written_at >= ttl:
                        # last_used_at is dashboard telemetry; once per TTL
                        # keeps it honest without a write per request
                        self.db.execute(
                            "UPDATE api_keys SET last_used_at=? WHERE id=?",
                            (now, key.id),
                        )
                        with self._cache_lock:
                            self._cache[key_hash] = (key, cached_at, now)
                    return key
        row = self.db.query_one(
            "SELECT * FROM api_keys WHERE key_hash=?", (key_hash,)
        )
        if row is None or row["revoked"]:
            return None
        if row["expires_at"] is not None and row["expires_at"] < now:
            return None
        self.db.execute(
            "UPDATE api_keys SET last_used_at=? WHERE id=?", (now, row["id"])
        )
        key = self._to_key(row)
        if ttl > 0:
            with self._cache_lock:
                self._cache[key_hash] = (key, now, now)
        return key

    def list(self, user_id: str | None = None) -> list[ApiKey]:
        if user_id:
            rows = self.db.query(
                "SELECT * FROM api_keys WHERE user_id=?", (user_id,)
            )
        else:
            rows = self.db.query("SELECT * FROM api_keys")
        return [self._to_key(r) for r in rows]

    def revoke(self, key_id: str) -> bool:
        cur = self.db.execute(
            "UPDATE api_keys SET revoked=1 WHERE id=?", (key_id,)
        )
        with self._cache_lock:
            # this worker stops honoring the key immediately; siblings age
            # it out within the cache TTL
            for key_hash, (key, _, _) in list(self._cache.items()):
                if key.id == key_id:
                    del self._cache[key_hash]
        return cur.rowcount > 0

    @staticmethod
    def _to_key(row) -> ApiKey:
        perms = []
        for v in json.loads(row["permissions"] or "[]"):
            try:
                perms.append(Permission(v))
            except ValueError:
                continue
        return ApiKey(
            id=row["id"], user_id=row["user_id"], name=row["name"],
            key_prefix=row["key_prefix"], permissions=perms,
            created_at=row["created_at"], revoked=bool(row["revoked"]),
            expires_at=row["expires_at"], last_used_at=row["last_used_at"],
        )


# ---------------------------------------------------------------- invitations


class InvitationStore:
    def __init__(self, db: Database):
        self.db = db

    def create(
        self, created_by: str, role: Role = Role.VIEWER,
        ttl_s: float | None = 7 * 86400,
    ) -> dict:
        code = secrets.token_urlsafe(16)
        inv_id = uuid.uuid4().hex
        now = time.time()
        self.db.execute(
            """INSERT INTO invitations (id, code, role, created_by, created_at,
               expires_at) VALUES (?,?,?,?,?,?)""",
            (inv_id, code, role.value, created_by, now,
             now + ttl_s if ttl_s else None),
        )
        return {"id": inv_id, "code": code, "role": role.value,
                "expires_at": now + ttl_s if ttl_s else None}

    def redeem(self, code: str, username: str, password: str,
               users: UserStore) -> User:
        row = self.db.query_one(
            "SELECT * FROM invitations WHERE code=?", (code,)
        )
        if row is None or row["used_at"] is not None:
            raise AuthError("invalid or used invitation code")
        if row["expires_at"] is not None and row["expires_at"] < time.time():
            raise AuthError("invitation expired")
        user = users.create(username, password, Role(row["role"]))
        self.db.execute(
            "UPDATE invitations SET used_by=?, used_at=? WHERE id=?",
            (user.id, time.time(), row["id"]),
        )
        return user

    def list(self) -> list[dict]:
        return [dict(r) for r in self.db.query("SELECT * FROM invitations")]

    def delete(self, inv_id: str) -> bool:
        cur = self.db.execute("DELETE FROM invitations WHERE id=?", (inv_id,))
        return cur.rowcount > 0
