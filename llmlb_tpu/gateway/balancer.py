"""LoadManager: TPS-EMA scheduling, request leases, in-memory request history.

Behavior parity with the reference scheduler (reference balancer/mod.rs):
- Per-(endpoint, model, api_kind) tokens/sec tracked as an EMA with α=0.2
  (balancer/types.rs:98-121); endpoints with higher measured TPS are preferred.
- Endpoints with no measurement yet score +inf so they get probed first;
  ties (incl. all-unmeasured) break round-robin (balancer/mod.rs:1955-1984).
- RequestLease is an RAII guard: active count increments on acquire and is
  always released — explicitly via complete()/fail(), or by the finalizer if
  the holder forgets (balancer/lease.rs Drop semantics).
- 60-minute in-memory request history ring for dashboards (types.rs:22),
  seeded from the DB at boot.
- TPU-aware extension (no reference counterpart): measured TPS scores are
  multiplied by a telemetry penalty computed from the endpoint's last health
  probe — HBM pressure above HBM_PRESSURE_KNEE fades the score toward zero,
  and a non-empty engine admission queue divides it by (1 + depth). Unmeasured
  endpoints still probe first, but telemetry breaks ties among them before
  round-robin does.
- Prefix-affinity routing (no reference counterpart): requests whose prompt
  head hashes to a recently-routed prefix stick to the endpoint that last
  served it, so the engine-side prefix KV cache (engine/prefix_cache.py)
  actually gets hit. Two modes (LLMLB_AFFINITY):
    * ``lru`` (default single-worker): learned bounded LRU map with TTL —
      the historical behavior, bit-identical to pre-multi-worker gateways.
    * ``ring`` (default with --workers > 1): rendezvous/consistent hashing
      over the live endpoint set — every worker maps the same prompt head
      to the same endpoint with zero coordination, steering survives worker
      restarts, and endpoint churn remaps only ~1/E of keys.
  Both fall back to normal scoring whenever the sticky endpoint is
  unhealthy, absent, or at its cap.
- Gossip replication (gateway/gossip.py, multi-worker): TPS EMA samples and
  (in lru mode) affinity pins publish to sibling workers and apply
  last-writer-wins; a worker that misses updates only places requests
  slightly worse until its own measurements converge.
"""

from __future__ import annotations

import asyncio
import bisect
import dataclasses
import hashlib
import os
import threading
import time
import typing
from collections import OrderedDict, defaultdict, deque

from llmlb_tpu.gateway.config import QueueConfig, env_bool
from llmlb_tpu.gateway.gossip import SeqClock, Version, newer
from llmlb_tpu.gateway.types import Endpoint, TpsApiKind

TPS_EMA_ALPHA = 0.2  # parity: balancer/types.rs:109
HISTORY_WINDOW_S = 3600.0  # parity: 60-min window, balancer/types.rs:22
METRICS_STALE_S = 120.0

# Telemetry-aware placement: above this HBM fill fraction an endpoint's score
# fades linearly, reaching TELEMETRY_MIN_PENALTY at 100% full. A KV-cache-bound
# engine near HBM capacity will soon reject or thrash; prefer its peers.
HBM_PRESSURE_KNEE = 0.85
TELEMETRY_MIN_PENALTY = 0.05

# Prefix-affinity routing: the tpu:// engine keeps a prefix KV cache
# (engine/prefix_cache.py), so two requests sharing a system prompt are far
# cheaper on the SAME engine than split across two. The gateway hashes the
# head of each prompt and remembers which endpoint last served that hash;
# the next request with the same hash is steered there as long as the
# endpoint is a live candidate under its admission cap — otherwise selection
# falls back to the normal TPS/telemetry scoring and the hash is re-pinned
# to whatever endpoint wins. The map is bounded (LRU) and entries expire,
# so a dead prefix never pins routing forever.
PREFIX_AFFINITY_CAPACITY = 4096
PREFIX_AFFINITY_TTL_S = 600.0
PREFIX_AFFINITY_CHARS = 512  # ≈ the first 128 prompt tokens
# Heads shorter than this can never clear the engine's minimum cacheable
# prefix (the smallest prefill bucket — 32 tokens ≈ 128 chars on the default
# config), so pinning them would override TPS/telemetry placement for zero
# cache benefit — short prompts keep the old scoring.
PREFIX_AFFINITY_MIN_CHARS = 128


def prefix_affinity_hash(model: str, text: str,
                         lora: str | None = None) -> str | None:
    """Stable hash of a prompt's head (+ model, so two models' identical
    system prompts don't collide onto one engine's cache; + LoRA adapter
    id — under multi-LoRA the prompt KV depends on the adapter's wq/wk/wv
    deltas, so two adapters sharing a system prompt must pin and warm
    caches independently, docs/lora.md). None for heads too short to
    benefit from prefix reuse. lora=None hashes exactly as before, so
    adapter-free affinity keys are unchanged."""
    if len(text) < PREFIX_AFFINITY_MIN_CHARS:
        return None
    head = text[:PREFIX_AFFINITY_CHARS]
    key = (f"{model}\x00{head}" if lora is None
           else f"{model}\x00lora={lora}\x00{head}")
    return hashlib.sha1(key.encode("utf-8", "replace")).hexdigest()


# Gossip: one TPS message per tracked key at most this often — the EMA moves
# slowly, and per-request fan-out would put a datagram on the bus for every
# completion.
TPS_GOSSIP_MIN_INTERVAL_S = 1.0

# Prefix-heat gossip: batch locally observed (hash → endpoint, hits) deltas
# and flush at most this often, so a hot shared prefix costs one datagram
# per interval, not one per request.
HEAT_GOSSIP_MIN_INTERVAL_S = 1.0

AFFINITY_MODES = ("lru", "ring")


def hrw_weight(prefix_hash: str, endpoint_id: str) -> int:
    """Rendezvous (highest-random-weight) score of one (key, endpoint)
    pair: the first 8 bytes of sha256("hash|endpoint") as a big-endian
    integer. The native twin (router_core.cpp hrw_select) computes the
    same bytes, so Python and C++ agree bit for bit."""
    digest = hashlib.sha256(
        f"{prefix_hash}|{endpoint_id}".encode()
    ).digest()
    return int.from_bytes(digest[:8], "big")


def hrw_owner(prefix_hash: str, endpoint_ids: list[str]) -> str | None:
    """Consistent-hash owner of a prefix over the live endpoint set.

    Pure function of (key, set): every worker computes the same owner with
    no shared state, a restart loses nothing, and removing one endpoint
    remaps only the keys it owned (~1/E) — the property a mod-N hash lacks.
    Ties (astronomically unlikely) break toward the smallest endpoint id.
    """
    best: str | None = None
    best_w = -1
    for eid in endpoint_ids:
        w = hrw_weight(prefix_hash, eid)
        if w > best_w or (w == best_w and (best is None or eid < best)):
            best, best_w = eid, w
    return best


def default_affinity_mode(worker_count: int = 1) -> str:
    """LLMLB_AFFINITY beats the worker-count default: ring when several
    workers must agree without coordination, lru (the historical,
    bit-identical behavior) for a single worker."""
    raw = (os.environ.get("LLMLB_AFFINITY") or "").strip().lower()
    if raw in AFFINITY_MODES:
        return raw
    return "ring" if worker_count > 1 else "lru"


def telemetry_penalty(ep: Endpoint, now: float | None = None) -> float:
    """Multiplicative demotion factor in (0, 1] from the endpoint's last
    health-probe telemetry. 1.0 = unloaded, no telemetry, or telemetry older
    than METRICS_STALE_S (a snapshot from a probe that has since stopped
    reporting must not demote an endpoint forever)."""
    acc = ep.accelerator
    if acc is None:
        return 1.0
    if acc.sampled_at <= 0:
        return 1.0
    if ((now if now is not None else time.time()) - acc.sampled_at
            > METRICS_STALE_S):
        return 1.0
    p = 1.0
    pressure = acc.hbm_pressure
    if pressure is not None and pressure > HBM_PRESSURE_KNEE:
        span = 1.0 - HBM_PRESSURE_KNEE
        frac = min(1.0, (pressure - HBM_PRESSURE_KNEE) / span)
        p *= max(TELEMETRY_MIN_PENALTY, 1.0 - frac * (1.0 - TELEMETRY_MIN_PENALTY))
    if acc.queue_depth > 0:
        p /= 1.0 + acc.queue_depth
    return p


@dataclasses.dataclass
class ModelTpsState:
    """EMA of tokens/sec for one (endpoint, model, api_kind)."""

    ema_tps: float = 0.0
    samples: int = 0
    last_update: float = 0.0

    def update(self, tokens: int, duration_s: float, now: float | None = None) -> None:
        if duration_s <= 0 or tokens <= 0:
            return
        tps = tokens / duration_s
        if self.samples == 0:
            self.ema_tps = tps
        else:
            self.ema_tps = TPS_EMA_ALPHA * tps + (1 - TPS_EMA_ALPHA) * self.ema_tps
        self.samples += 1
        self.last_update = now if now is not None else time.time()


@dataclasses.dataclass
class RequestRecord:
    ts: float
    endpoint_id: str
    model: str
    api_kind: TpsApiKind
    status_code: int
    duration_ms: float
    prompt_tokens: int = 0
    completion_tokens: int = 0


class RequestLease:
    """Active-request guard. Release exactly once; idempotent on double release."""

    def __init__(self, manager: "LoadManager", endpoint_id: str, model: str,
                 api_kind: TpsApiKind):
        self.manager = manager
        self.endpoint_id = endpoint_id
        self.model = model
        self.api_kind = api_kind
        self.started_at = time.monotonic()
        self._released = False

    def complete(self) -> None:
        """Request handed off successfully (e.g. stream started)."""
        self._release()

    def complete_with_tokens(self, prompt_tokens: int, completion_tokens: int) -> None:
        duration = time.monotonic() - self.started_at
        self.manager.update_tps(
            self.endpoint_id, self.model, self.api_kind,
            completion_tokens, duration,
        )
        self._release()

    def fail(self) -> None:
        self._release()

    def _release(self) -> None:
        if not self._released:
            self._released = True
            self.manager._release_active(self.endpoint_id)

    def __del__(self):  # Drop-safety: never leak an active count
        self._release()

    def __enter__(self) -> "RequestLease":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._release()


class LoadManager:
    def __init__(self, queue_config: QueueConfig | None = None,
                 use_native: bool | None = None,
                 affinity_mode: str | None = None):
        self.queue_config = queue_config or QueueConfig()
        self.affinity_mode = (affinity_mode if affinity_mode in AFFINITY_MODES
                              else default_affinity_mode())
        # GossipBus | None (set by app_state in multi-worker mode): TPS
        # samples and lru-mode affinity pins replicate to sibling workers.
        # Advisory only — every consumer of this state tolerates staleness.
        self.gossip = None
        self._tps_pub_ts: dict[tuple[str, str, str], float] = {}
        self._lock = threading.Lock()
        # Seq-LWW versions (gossip.newer): per-key (seq, origin) stamps for
        # TPS/affinity state plus per-endpoint clear tombstones, so a
        # delayed datagram from before a clear can never resurrect stale
        # state — wall stamps don't order across hosts. The local clock is
        # the fallback when no bus is attached (single worker).
        self._local_clock = SeqClock()
        self._tps_ver: dict[tuple[str, str, str], Version] = {}
        self._clear_ver: dict[str, Version] = {}
        # (endpoint_id, model, api_kind) -> ModelTpsState
        self._tps: dict[tuple[str, str, str], ModelTpsState] = {}
        self._active: dict[str, int] = defaultdict(int)
        self._rr_counter: dict[str, int] = defaultdict(int)  # round-robin per model
        self._history: deque[RequestRecord] = deque()
        self._total_requests = 0
        # (model, prefix_hash) -> (endpoint_id, recorded_at, version);
        # recorded_at is LOCAL receipt time (TTL only — skew-free),
        # version is the seq-LWW stamp. Bounded LRU.
        self._affinity: OrderedDict[
            tuple[str, str], tuple[str, float, Version]
        ] = OrderedDict()
        self._affinity_hits = 0
        self._affinity_misses = 0
        self._affinity_evictions = 0
        # Prefix-heat map (LLMLB_AFFINITY_HEAT, ring mode): which endpoint
        # ACTUALLY holds each hot prefix cached, learned locally and over
        # gossip — ring selection prefers a live under-cap holder before
        # the rendezvous owner, so steering follows real cache contents
        # after endpoint churn/migration instead of pure hash topology.
        # (model, prefix_hash) -> [endpoint_id, hits, version]; bounded LRU.
        self.affinity_heat = env_bool("LLMLB_AFFINITY_HEAT", False)
        self._heat: OrderedDict[tuple[str, str], list] = OrderedDict()
        self._heat_pending: dict[str, dict[str, list]] = {}
        self._heat_pub_ts = 0.0
        # In-band per-endpoint outcome stats (resilience layer feeds these;
        # stream interruptions land here too — before this, a stream that
        # died mid-flight never counted against its endpoint because the
        # lease completes at stream start). Independent of the breaker:
        # surfaced in /api/health and stats() even with resilience disabled.
        self._endpoint_outcomes: dict[str, dict] = {}
        # ResilienceManager | None (set by app_state): selection consults
        # allow() so breaker-open endpoints are ejected immediately, and
        # reports admissions via on_admit() (half-open probe accounting).
        self.resilience = None
        # Called (outside the lock) with the endpoint id each time a lease is
        # released — the AdmissionQueue uses it to wake parked waiters instead
        # of having them poll (parity: balancer/mod.rs:2273-2427 notify path).
        self.on_release: typing.Callable[[str], None] | None = None
        # Native scheduler core (native/router_core.cpp): the same state
        # machine in C++, selection-for-selection identical to the Python
        # path below (tested side by side). Python remains the fallback and
        # the behavioral reference. LLMLB_NATIVE_ROUTER=0 disables.
        self._rc = None
        self._hrw_native = None
        if use_native is None:
            use_native = os.environ.get(
                "LLMLB_NATIVE_ROUTER", "1"
            ).lower() not in ("0", "false")
        if use_native:
            try:
                from llmlb_tpu.native import NativeRouterCore

                self._rc = NativeRouterCore(TPS_EMA_ALPHA)
            except (RuntimeError, OSError):
                self._rc = None
            try:
                from llmlb_tpu.native import native_hrw_available, native_hrw_select

                if native_hrw_available():
                    self._hrw_native = native_hrw_select
            except ImportError:
                self._hrw_native = None

    # ------------------------------------------------------------------- TPS

    def update_tps(
        self, endpoint_id: str, model: str, api_kind: TpsApiKind,
        tokens: int, duration_s: float,
    ) -> None:
        if self._rc is not None:
            self._rc.update_tps(endpoint_id, model, api_kind.value,
                                tokens, duration_s, time.time())
            self._stamp_tps(endpoint_id, model, api_kind.value)
            self._maybe_gossip_tps(endpoint_id, model, api_kind.value)
            return
        if duration_s <= 0 or tokens <= 0:
            return  # rejected samples must not create phantom tracked keys
        with self._lock:
            key = (endpoint_id, model, api_kind.value)
            state = self._tps.setdefault(key, ModelTpsState())
            state.update(tokens, duration_s)
        self._stamp_tps(endpoint_id, model, api_kind.value)
        self._maybe_gossip_tps(endpoint_id, model, api_kind.value)

    def _stamp_tps(self, endpoint_id: str, model: str, kind: str) -> None:
        """A local in-band measurement outranks every gossip message this
        worker has already witnessed (Lamport: the tick is causally after
        them) — a delayed stale datagram can never override it."""
        ver = self._next_ver()
        with self._lock:
            self._tps_ver[(endpoint_id, model, kind)] = ver

    # --------------------------------------------------------- tps replication

    def _next_ver(self) -> Version:
        """Allocate a fresh seq-LWW version: the bus's Lamport clock when
        gossip is attached (so local stamps and wire stamps share one
        order), a process-local clock otherwise."""
        g = self.gossip
        if g is not None:
            return g.next_version()
        return (self._local_clock.tick(), "local")

    def _tps_info(self, endpoint_id: str, model: str,
                  kind: str) -> tuple[float, int, float] | None:
        """(ema, samples, last_update) for one key, whichever core holds it."""
        if self._rc is not None:
            return self._rc.tps_info(endpoint_id, model, kind)
        with self._lock:
            state = self._tps.get((endpoint_id, model, kind))
            if state is None or state.samples == 0:
                return None
            return state.ema_tps, state.samples, state.last_update

    def _maybe_gossip_tps(self, endpoint_id: str, model: str,
                          kind: str) -> None:
        g = self.gossip
        if g is None:
            return
        key = (endpoint_id, model, kind)
        now = time.monotonic()
        if now - self._tps_pub_ts.get(key, 0.0) < TPS_GOSSIP_MIN_INTERVAL_S:
            return
        self._tps_pub_ts[key] = now
        info = self._tps_info(endpoint_id, model, kind)
        if info is None:
            return
        ema, samples, _last = info
        g.publish("tps", {"eid": endpoint_id, "model": model, "kind": kind,
                          "ema": ema, "samples": samples})

    def apply_remote_tps(self, endpoint_id: str, model: str, kind: str,
                         ema: float, samples: int, ver: Version) -> None:
        """A sibling worker's EMA, applied seq-LWW: not newer than this
        worker's own stamp (or the endpoint's clear tombstone) is dropped —
        wall stamps skew across hosts and silently resurrected stale state;
        (seq, origin) versions don't. Never re-gossips."""
        ver = tuple(ver)
        key = (endpoint_id, model, kind)
        with self._lock:
            if not newer(ver, self._clear_ver.get(endpoint_id)):
                return
            if not newer(ver, self._tps_ver.get(key)):
                return
            self._tps_ver[key] = ver
            if self._rc is None:
                self._tps[key] = ModelTpsState(
                    ema_tps=ema, samples=max(1, samples),
                    last_update=time.time(),
                )
        if self._rc is not None:
            # local wall only feeds the native core's same-process staleness
            # bookkeeping; cross-worker ordering was decided above
            self._rc.seed_tps(endpoint_id, model, kind, ema,
                              max(1, samples), time.time())

    def seed_tps(self, endpoint_id: str, model: str, api_kind: TpsApiKind,
                 ema_tps: float, samples: int = 1) -> None:
        """Warm-start from persisted daily stats at boot (bootstrap parity)."""
        if self._rc is not None:
            self._rc.seed_tps(endpoint_id, model, api_kind.value,
                              ema_tps, samples, time.time())
            return
        with self._lock:
            self._tps[(endpoint_id, model, api_kind.value)] = ModelTpsState(
                ema_tps=ema_tps, samples=samples, last_update=time.time()
            )

    def get_tps(self, endpoint_id: str, model: str,
                api_kind: TpsApiKind) -> float | None:
        if self._rc is not None:
            return self._rc.get_tps(endpoint_id, model, api_kind.value)
        with self._lock:
            state = self._tps.get((endpoint_id, model, api_kind.value))
            return state.ema_tps if state and state.samples else None

    def clear_tps_for_endpoint(self, endpoint_id: str,
                               _publish: bool = True) -> None:
        """On failure: a recovered endpoint must re-learn (balancer/mod.rs:1791).
        Prefix affinities pinned to it are dropped too — its engine restarts
        with a cold prefix cache, so stickiness buys nothing and would keep
        steering shared-prefix traffic at a flapping endpoint. The clear
        gossips to sibling workers (the pull checker that noticed the
        failure runs in one elected worker only)."""
        ver = self._next_ver()
        self._clear_endpoint_state(endpoint_id, ver)
        if _publish and self.gossip is not None:
            self.gossip.publish("tps_clear", {"eid": endpoint_id},
                                seq=ver[0])

    def apply_remote_tps_clear(self, endpoint_id: str, ver: Version) -> None:
        """A sibling's clear, tombstoned with the WIRE version: any tps or
        affinity datagram published before the clear (lower version) is
        dropped on arrival — no stale-state resurrection, however delayed
        or reordered the transport got. Never re-gossips."""
        ver = tuple(ver)
        with self._lock:
            if not newer(ver, self._clear_ver.get(endpoint_id)):
                return
        self._clear_endpoint_state(endpoint_id, ver)

    def _clear_endpoint_state(self, endpoint_id: str, ver: Version) -> None:
        with self._lock:
            self._clear_ver[endpoint_id] = ver
            for key in [k for k, v in self._affinity.items()
                        if v[0] == endpoint_id]:
                del self._affinity[key]
            for key in [k for k in self._tps_ver if k[0] == endpoint_id]:
                del self._tps_ver[key]
            for key in [k for k, v in self._heat.items()
                        if v[0] == endpoint_id]:
                del self._heat[key]
        if self._rc is not None:
            self._rc.clear_endpoint(endpoint_id)
        else:
            with self._lock:
                self._tps = {
                    k: v for k, v in self._tps.items() if k[0] != endpoint_id
                }

    def tps_snapshot(self) -> dict[str, dict]:
        if self._rc is not None:
            return self._rc.snapshot()
        with self._lock:
            return {
                f"{eid}:{model}:{kind}": {
                    "ema_tps": round(s.ema_tps, 3),
                    "samples": s.samples,
                    "last_update": s.last_update,
                }
                for (eid, model, kind), s in self._tps.items()
            }

    # ------------------------------------------------------- prefix affinity

    def _affinity_peek_locked(self, model: str, prefix_hash: str) -> str | None:
        key = (model, prefix_hash)
        got = self._affinity.get(key)
        if got is None:
            return None
        endpoint_id, ts, _ver = got
        if time.time() - ts > PREFIX_AFFINITY_TTL_S:
            del self._affinity[key]
            return None
        return endpoint_id

    def _affinity_note_locked(self, model: str, prefix_hash: str,
                              endpoint_id: str) -> bool:
        """Returns True when the pin is new or moved to another endpoint
        (the only cases worth gossiping — refreshes are noise)."""
        key = (model, prefix_hash)
        prev = self._affinity.get(key)
        self._affinity[key] = (endpoint_id, time.time(), self._next_ver())
        self._affinity.move_to_end(key)
        while len(self._affinity) > PREFIX_AFFINITY_CAPACITY:
            self._affinity.popitem(last=False)
            self._affinity_evictions += 1
        return prev is None or prev[0] != endpoint_id

    def _gossip_affinity(self, model: str, prefix_hash: str,
                         endpoint_id: str) -> None:
        if self.gossip is not None:
            self.gossip.publish("affinity", {
                "model": model, "hash": prefix_hash, "eid": endpoint_id,
            })

    def apply_remote_affinity(self, model: str, prefix_hash: str,
                              endpoint_id: str, ver: Version) -> None:
        """A sibling worker pinned this prefix (lru mode only — ring mode
        needs no replication, the hash IS the agreement). Seq-LWW on the
        wire version; TTL runs on LOCAL receipt time (remote wall stamps
        would expire early/late under cross-host skew). Never counted as
        hit/miss, never re-gossiped."""
        if self.affinity_mode != "lru":
            return
        ver = tuple(ver)
        with self._lock:
            if not newer(ver, self._clear_ver.get(endpoint_id)):
                return
            key = (model, prefix_hash)
            cur = self._affinity.get(key)
            if cur is not None and not newer(ver, cur[2]):
                return
            self._affinity[key] = (endpoint_id, time.time(), ver)
            self._affinity.move_to_end(key)
            while len(self._affinity) > PREFIX_AFFINITY_CAPACITY:
                self._affinity.popitem(last=False)
                self._affinity_evictions += 1

    # ----------------------------------------------------------- prefix heat

    def _heat_note_locked(self, model: str, prefix_hash: str,
                          endpoint_id: str) -> None:
        """One request for this prefix actually served by `endpoint_id` —
        its KV cache now (still) holds the prefix. Caller holds _lock."""
        key = (model, prefix_hash)
        entry = self._heat.get(key)
        if entry is not None and entry[0] == endpoint_id:
            entry[1] += 1
        else:
            entry = [endpoint_id, 1, self._next_ver()]
            self._heat[key] = entry
        self._heat.move_to_end(key)
        while len(self._heat) > PREFIX_AFFINITY_CAPACITY:
            self._heat.popitem(last=False)
        self._heat_pending.setdefault(model, {})[prefix_hash] = [
            endpoint_id, entry[1],
        ]

    def _maybe_gossip_heat(self) -> None:
        """Flush batched heat deltas at most once per interval (call sites
        must NOT hold _lock — publish writes to sockets)."""
        g = self.gossip
        if g is None or not self.affinity_heat:
            return
        now = time.monotonic()
        with self._lock:
            if (not self._heat_pending
                    or now - self._heat_pub_ts < HEAT_GOSSIP_MIN_INTERVAL_S):
                return
            self._heat_pub_ts = now
            pending, self._heat_pending = self._heat_pending, {}
        for model, entries in pending.items():
            g.publish("heat", {"model": model, "entries": entries})

    def apply_remote_heat(self, model: str, entries: dict,
                          ver: Version) -> None:
        """A sibling's heat deltas: seq-LWW per entry, hit counts merge
        monotonically when both workers agree on the holder. Never
        re-gossips."""
        ver = tuple(ver)
        with self._lock:
            for prefix_hash, value in entries.items():
                if not (isinstance(value, (list, tuple)) and len(value) >= 2):
                    continue
                eid, hits = str(value[0]), int(value[1])
                if not newer(ver, self._clear_ver.get(eid)):
                    continue
                key = (model, str(prefix_hash))
                cur = self._heat.get(key)
                if cur is not None and cur[0] == eid:
                    cur[1] = max(cur[1], hits)
                    cur[2] = max(cur[2], ver)
                elif cur is None or newer(ver, cur[2]):
                    self._heat[key] = [eid, hits, ver]
                self._heat.move_to_end(key)
            while len(self._heat) > PREFIX_AFFINITY_CAPACITY:
                self._heat.popitem(last=False)

    def _heat_endpoint_locked(self, model: str,
                              prefix_hash: str) -> str | None:
        entry = self._heat.get((model, prefix_hash))
        return entry[0] if entry is not None else None

    def _affinity_endpoint(self, model: str,
                           prefix_hash: str | None) -> str | None:
        if prefix_hash is None:
            return None
        with self._lock:
            return self._affinity_peek_locked(model, prefix_hash)

    def _hrw_owner(self, prefix_hash: str, endpoint_ids: list[str]) -> str | None:
        if self._hrw_native is not None:
            idx = self._hrw_native(prefix_hash, endpoint_ids)
            if 0 <= idx < len(endpoint_ids):
                return endpoint_ids[idx]
            return None
        return hrw_owner(prefix_hash, endpoint_ids)

    def _sticky_endpoint_id(self, endpoints: list[Endpoint], model: str,
                            prefix_hash: str | None) -> str | None:
        """The endpoint this prefix should steer to, by affinity mode: the
        learned LRU pin, or the consistent-hash owner over the candidate
        set (post-breaker, pre-cap)."""
        if prefix_hash is None:
            return None
        if self.affinity_mode == "ring":
            if self.affinity_heat:
                # steer by what is ACTUALLY cached where, when known: a
                # migrated/churned prefix keeps hitting its warm engine
                # instead of the (cold) rendezvous owner
                with self._lock:
                    hot = self._heat_endpoint_locked(model, prefix_hash)
                if hot is not None and any(ep.id == hot for ep in endpoints):
                    return hot
            return self._hrw_owner(prefix_hash, [ep.id for ep in endpoints])
        return self._affinity_endpoint(model, prefix_hash)

    def _affinity_record(self, model: str, prefix_hash: str | None,
                         endpoint_id: str, *, hit: bool) -> None:
        if prefix_hash is None:
            return
        changed = False
        with self._lock:
            if self.affinity_mode == "lru":
                changed = self._affinity_note_locked(model, prefix_hash,
                                                     endpoint_id)
            if self.affinity_heat:
                self._heat_note_locked(model, prefix_hash, endpoint_id)
            if hit:
                self._affinity_hits += 1
            else:
                self._affinity_misses += 1
        if changed:
            self._gossip_affinity(model, prefix_hash, endpoint_id)
        self._maybe_gossip_heat()

    def affinity_stats(self) -> dict:
        """Prefix-affinity figures for the gateway /metrics exposition."""
        with self._lock:
            return {
                "entries": len(self._affinity),
                "hits_total": self._affinity_hits,
                "misses_total": self._affinity_misses,
                "evictions_total": self._affinity_evictions,
                "heat_entries": len(self._heat),
            }

    # ------------------------------------------------------ endpoint outcomes

    def _outcomes_for(self, endpoint_id: str) -> dict:
        """Caller holds self._lock."""
        return self._endpoint_outcomes.setdefault(endpoint_id, {
            "successes": 0, "failures": 0, "stream_interruptions": 0,
            "consecutive_failures": 0, "last_failure_ts": None,
        })

    def note_endpoint_success(self, endpoint_id: str) -> None:
        with self._lock:
            o = self._outcomes_for(endpoint_id)
            o["successes"] += 1
            o["consecutive_failures"] = 0

    def note_endpoint_failure(self, endpoint_id: str, *,
                              stream_interruption: bool = False) -> None:
        with self._lock:
            o = self._outcomes_for(endpoint_id)
            o["failures"] += 1
            if stream_interruption:
                o["stream_interruptions"] += 1
            o["consecutive_failures"] += 1
            o["last_failure_ts"] = time.time()

    def endpoint_outcomes(self, endpoint_id: str | None = None) -> dict:
        """In-band outcome counters, per endpoint or the whole map. Pure
        read: never inserts (scrape paths must not grow the map)."""
        with self._lock:
            if endpoint_id is not None:
                o = self._endpoint_outcomes.get(endpoint_id)
                return dict(o) if o is not None else {
                    "successes": 0, "failures": 0, "stream_interruptions": 0,
                    "consecutive_failures": 0, "last_failure_ts": None,
                }
            return {eid: dict(o) for eid, o in self._endpoint_outcomes.items()}

    def drop_endpoint_outcomes(self, endpoint_id: str) -> None:
        """Endpoint deleted: stop carrying its counters (ids churn on
        re-registration; dead entries would inflate stats() forever)."""
        with self._lock:
            self._endpoint_outcomes.pop(endpoint_id, None)

    # -------------------------------------------------------------- selection

    def _permitted(self, endpoints: list[Endpoint]) -> list[Endpoint]:
        """Drop endpoints whose circuit breaker refuses traffic right now,
        and endpoints whose last health probe advertised a graceful drain
        (docs/deployment.md) — both reduce the candidate set, never the 404
        decision: a model whose endpoints are all ejected queues and 503s.
        No resilience manager wired (unit tests, resilience disabled) means
        no breaker filtering; the drain filter always applies."""
        out = [ep for ep in endpoints
               if ep.accelerator is None or not ep.accelerator.draining]
        if self.resilience is None:
            return out
        return [ep for ep in out if self.resilience.allow(ep.id)]

    def _note_admitted(self, endpoint_id: str) -> None:
        if self.resilience is not None:
            self.resilience.on_admit(endpoint_id)

    def select_endpoint(
        self,
        endpoints: list[Endpoint],
        model: str,
        api_kind: TpsApiKind = TpsApiKind.CHAT,
        prefix_hash: str | None = None,
    ) -> Endpoint | None:
        """Pick the best endpoint: prefix affinity first (the endpoint that
        last served this prompt head, while it is a live candidate under its
        cap), then telemetry-weighted measured-TPS desc; unmeasured first
        (probe), telemetry then round-robin among equals; full endpoints
        (admission cap) excluded; breaker-open endpoints ejected."""
        endpoints = self._permitted(endpoints)
        if not endpoints:
            return None
        if self._rc is not None:
            sticky = self._affinity_sticky_rc(endpoints, model, prefix_hash)
            if sticky is not None:
                return sticky
            idx = self._rc_select(endpoints, model, api_kind, admit=False)
            if idx < 0:
                return None
            self._affinity_record(model, prefix_hash, endpoints[idx].id,
                                  hit=False)
            return endpoints[idx]
        with self._lock:
            return self._select_locked(endpoints, model, api_kind,
                                       prefix_hash)

    def _affinity_sticky_rc(self, endpoints: list[Endpoint], model: str,
                            prefix_hash: str | None) -> Endpoint | None:
        """Native-router path: affinity (LRU map or consistent-hash owner)
        steers before delegating to the C++ scorer. Only honors an endpoint
        that is still a candidate and under its admission cap."""
        eid = self._sticky_endpoint_id(endpoints, model, prefix_hash)
        if eid is None:
            return None
        cap = self.queue_config.max_active_per_endpoint
        for ep in endpoints:
            if ep.id == eid and self._rc.active(eid) < cap:
                self._affinity_record(model, prefix_hash, eid, hit=True)
                return ep
        return None

    def _rc_select(self, endpoints: list[Endpoint], model: str,
                   api_kind: TpsApiKind, *, admit: bool) -> int:
        now = time.time()
        return self._rc.select(
            model, api_kind.value,
            [ep.id for ep in endpoints],
            [telemetry_penalty(ep, now) for ep in endpoints],
            self.queue_config.max_active_per_endpoint,
            admit,
        )

    def _select_locked(
        self, endpoints: list[Endpoint], model: str, api_kind: TpsApiKind,
        prefix_hash: str | None = None,
    ) -> Endpoint | None:
        cap = self.queue_config.max_active_per_endpoint
        candidates = [
            ep for ep in endpoints if self._active[ep.id] < cap
        ]
        if not candidates:
            return None

        if prefix_hash is not None:
            if self.affinity_mode == "ring":
                # Consistent-hash owner over the permitted set (not just the
                # under-cap candidates): an at-cap owner counts a miss and
                # falls through to scoring rather than silently remapping —
                # the key snaps back the moment capacity frees. With the
                # heat map on, a live under-cap endpoint KNOWN to hold the
                # prefix cached outranks the hash owner.
                owner = None
                if self.affinity_heat:
                    owner = self._heat_endpoint_locked(model, prefix_hash)
                    if not any(ep.id == owner for ep in candidates):
                        owner = None
                if owner is None:
                    owner = self._hrw_owner(prefix_hash,
                                            [ep.id for ep in endpoints])
                for ep in candidates:
                    if ep.id == owner:
                        if self.affinity_heat:
                            self._heat_note_locked(model, prefix_hash, ep.id)
                        self._affinity_hits += 1
                        return ep
                self._affinity_misses += 1
            else:
                sticky_id = self._affinity_peek_locked(model, prefix_hash)
                for ep in candidates:
                    if ep.id == sticky_id:
                        self._affinity_note_locked(model, prefix_hash, ep.id)
                        self._affinity_hits += 1
                        return ep

        now = time.time()
        scored: list[tuple[float, float, Endpoint]] = []
        for ep in candidates:
            pen = telemetry_penalty(ep, now)
            state = self._tps.get((ep.id, model, api_kind.value))
            if state is None or state.samples == 0:
                s = float("inf")  # unmeasured: probe first
            else:
                s = state.ema_tps * pen
            scored.append((s, pen, ep))

        best = max(s for s, _, _ in scored)
        top = [(pen, ep) for s, pen, ep in scored if s == best]
        if len(top) > 1:
            # inf ties (all unmeasured) and exact-score ties: let telemetry
            # discriminate before falling back to round-robin.
            best_pen = max(pen for pen, _ in top)
            top = [(pen, ep) for pen, ep in top if pen == best_pen]
        idx = self._rr_counter[model] % len(top)
        self._rr_counter[model] += 1
        chosen = top[idx][1]
        if prefix_hash is not None and self.affinity_mode == "lru":
            changed = self._affinity_note_locked(model, prefix_hash, chosen.id)
            self._affinity_misses += 1
            if changed:
                # publish-under-lock is safe: gossip sends are non-blocking
                # datagram writes, never an event-loop round trip
                self._gossip_affinity(model, prefix_hash, chosen.id)
        return chosen

    def try_admit(
        self, endpoints: list[Endpoint], model: str, api_kind: TpsApiKind,
        prefix_hash: str | None = None,
    ) -> tuple[Endpoint, RequestLease] | None:
        """Atomic select + lease under one lock: concurrent admissions cannot
        both pick the last free slot of an endpoint (the select-then-begin
        two-step had that race)."""
        endpoints = self._permitted(endpoints)
        if not endpoints:
            return None
        if self._rc is not None:
            eid = self._sticky_endpoint_id(endpoints, model, prefix_hash)
            sticky = next((ep for ep in endpoints if ep.id == eid), None)
            if sticky is not None:
                # atomic cap-check + begin in the native core, scoped to the
                # sticky endpoint alone; at-cap falls through to full scoring
                got = self._rc.select(
                    model, api_kind.value, [sticky.id],
                    [telemetry_penalty(sticky)],
                    self.queue_config.max_active_per_endpoint, True,
                )
                if got == 0:
                    self._affinity_record(model, prefix_hash, sticky.id,
                                          hit=True)
                    self._note_admitted(sticky.id)
                    return sticky, RequestLease(self, sticky.id, model,
                                                api_kind)
            idx = self._rc_select(endpoints, model, api_kind, admit=True)
            if idx < 0:
                return None
            chosen = endpoints[idx]
            self._affinity_record(model, prefix_hash, chosen.id, hit=False)
            self._note_admitted(chosen.id)
            return chosen, RequestLease(self, chosen.id, model, api_kind)
        with self._lock:
            chosen = self._select_locked(endpoints, model, api_kind,
                                         prefix_hash)
            if chosen is None:
                return None
            self._active[chosen.id] += 1
            self._total_requests += 1
        self._note_admitted(chosen.id)
        return chosen, RequestLease(self, chosen.id, model, api_kind)

    def begin_request(
        self, endpoint: Endpoint, model: str, api_kind: TpsApiKind
    ) -> RequestLease:
        # No _note_admitted here: begin_request callers (playground proxy)
        # target one explicit endpoint, bypass breaker-filtered selection,
        # and never report outcomes — consuming a half-open probe slot from
        # this path would wedge the breaker with no outcome to resolve it.
        if self._rc is not None:
            self._rc.begin(endpoint.id)
            return RequestLease(self, endpoint.id, model, api_kind)
        with self._lock:
            self._active[endpoint.id] += 1
            self._total_requests += 1
        return RequestLease(self, endpoint.id, model, api_kind)

    def _release_active(self, endpoint_id: str) -> None:
        if self._rc is not None:
            self._rc.release(endpoint_id)
        else:
            with self._lock:
                if self._active[endpoint_id] > 0:
                    self._active[endpoint_id] -= 1
        cb = self.on_release
        if cb is not None:
            try:
                cb(endpoint_id)
            except Exception:  # allow-silent: a broken listener must
                pass               # not poison releases

    def active_count(self, endpoint_id: str) -> int:
        if self._rc is not None:
            return self._rc.active(endpoint_id)
        with self._lock:
            return self._active[endpoint_id]

    def total_active(self) -> int:
        if self._rc is not None:
            return self._rc.total_active()
        with self._lock:
            return sum(self._active.values())

    # --------------------------------------------------------------- history

    def record_request(self, record: RequestRecord) -> None:
        with self._lock:
            self._history.append(record)
            cutoff = time.time() - HISTORY_WINDOW_S
            while self._history and self._history[0].ts < cutoff:
                self._history.popleft()

    def history_minute_buckets(self) -> list[dict]:
        """Requests/errors/tokens per minute over the window (dashboard feed)."""
        with self._lock:
            buckets: dict[int, dict] = {}
            for r in self._history:
                minute = int(r.ts // 60) * 60
                b = buckets.setdefault(
                    minute,
                    {"ts": minute, "requests": 0, "errors": 0,
                     "prompt_tokens": 0, "completion_tokens": 0},
                )
                b["requests"] += 1
                if r.status_code >= 400:
                    b["errors"] += 1
                b["prompt_tokens"] += r.prompt_tokens
                b["completion_tokens"] += r.completion_tokens
            return [buckets[k] for k in sorted(buckets)]

    def stats(self) -> dict:
        with self._lock:
            outcome_totals = {
                "endpoint_failures_total": sum(
                    o["failures"] for o in self._endpoint_outcomes.values()
                ),
                "stream_interruptions_total": sum(
                    o["stream_interruptions"]
                    for o in self._endpoint_outcomes.values()
                ),
            }
        if self._rc is not None:
            with self._lock:
                history_size = len(self._history)
            return {
                "total_requests": self._rc.total_requests(),
                "active_requests": self._rc.total_active(),
                "history_size": history_size,
                "tracked_tps_keys": self._rc.tracked_keys(),
                "native_router": True,
                **outcome_totals,
            }
        with self._lock:
            return {
                "total_requests": self._total_requests,
                "active_requests": sum(self._active.values()),
                "history_size": len(self._history),
                "tracked_tps_keys": len(self._tps),
                **outcome_totals,
            }


@dataclasses.dataclass
class WaitResult:
    """Outcome of a queued admission wait (parity: balancer/types.rs
    WaitResult / AdmissionDecision)."""

    admitted: bool
    endpoint: Endpoint | None = None
    lease: RequestLease | None = None
    queue_position: int = 0  # 1-based position held while waiting (0 = fast path)
    waited_s: float = 0.0


# Parked waiters re-check capacity at least this often even without a release
# wake — covers endpoints that register/recover mid-wait (no release fires).
RECHECK_INTERVAL_S = 1.0


class _Ticket:
    __slots__ = ("future", "vtime", "seq", "tenant")

    def __init__(self, vtime: float = 0.0, seq: int = 0,
                 tenant: str | None = None):
        self.future: "asyncio.Future | None" = None
        self.vtime = vtime
        self.seq = seq
        self.tenant = tenant


class AdmissionQueue:
    """Notify-based admission: waiters park on futures that lease releases
    wake, replacing a 50 ms poll loop (parity: the reference's notify-based
    begin_request/WaitResult machinery, balancer/mod.rs:2273-2427).

    Weighted fair queuing (docs/scheduling.md): each parked ticket carries a
    virtual finish time — ``max(vclock, tenant's last vtime) + 1/weight`` —
    and the queue is kept sorted by it. A release wakes every parked waiter
    IN THAT ORDER (the event loop runs their retries in wake order, so the
    smallest-vtime ticket gets first claim on the freed slot): a tenant that
    queued 50 requests advances its own virtual clock 50 steps, so another
    tenant's next request slots in right behind the greedy tenant's FIRST
    ticket, not its fiftieth — each tenant saturates only its own share of
    the contended queue. With one tenant (or ``wfq_enabled=False`` via
    LLMLB_WFQ=0) the order degenerates to exact arrival FIFO, the historical
    behavior. Wakes arriving from other threads (e.g. a lease released by a
    GC finalizer) are marshalled onto the owning event loop with
    call_soon_threadsafe.
    """

    def __init__(self, manager: LoadManager):
        self.manager = manager
        self._tickets: list[_Ticket] = []  # sorted by (vtime, seq)
        self._loop: "asyncio.AbstractEventLoop | None" = None
        # GatewayMetrics, attached by app_state: counts admission
        # re-attempts by parked waiters, labeled by API kind.
        self.metrics = None
        # WFQ state: app_state loads weights from LLMLB_WFQ_WEIGHTS and the
        # enable flag from LLMLB_WFQ (default on).
        self.wfq_enabled = True
        self.weights: dict[str, float] = {}
        self._vclock = 0.0
        self._seq = 0
        self._tenant_vtime: dict[str, float] = {}
        manager.on_release = self._on_release

    def weight_for(self, tenant_name: str | None) -> float:
        return self.weights.get(tenant_name or "", 1.0)

    # ---------------------------------------------------------------- waking

    def _on_release(self, endpoint_id: str) -> None:
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            self._wake_all()
        else:
            try:
                loop.call_soon_threadsafe(self._wake_all)
            except RuntimeError:
                pass  # loop shut down mid-release

    def _wake_all(self) -> None:
        for t in self._tickets:
            if t.future is not None and not t.future.done():
                t.future.set_result(None)

    # --------------------------------------------------------------- waiting

    def position(self, ticket: _Ticket) -> int:
        try:
            return self._tickets.index(ticket) + 1
        except ValueError:
            return 0

    def queue_depth(self) -> int:
        return len(self._tickets)

    def _enqueue(self, tenant: str | None, weight: float) -> _Ticket:
        """Assign the ticket's virtual finish time and insert in vtime
        order. FIFO mode (wfq_enabled=False) stamps the arrival sequence
        instead — bit-identical to the historical queue."""
        self._seq += 1
        if not self.wfq_enabled:
            ticket = _Ticket(vtime=float(self._seq), seq=self._seq,
                             tenant=tenant)
        else:
            key = tenant or ""
            vtime = max(self._vclock, self._tenant_vtime.get(key, 0.0))
            vtime += 1.0 / max(0.01, weight)
            self._tenant_vtime[key] = vtime
            ticket = _Ticket(vtime=vtime, seq=self._seq, tenant=tenant)
        bisect.insort(self._tickets, ticket,
                      key=lambda t: (t.vtime, t.seq))
        return ticket

    def _dequeue(self, ticket: _Ticket, serviced: bool) -> None:
        try:
            self._tickets.remove(ticket)
        except ValueError:
            return
        if serviced and self.wfq_enabled:
            self._vclock = max(self._vclock, ticket.vtime)
        if not any(t.tenant == ticket.tenant for t in self._tickets):
            # Last queued ticket for this tenant: drop its clock entry
            # unconditionally. Serviced tickets already advanced _vclock, so
            # the entry is redundant; UNserviced exits (queue timeout,
            # deadline shed, disconnect) incurred no fairness debt — keeping
            # a vtime ahead of the vclock would both penalize the tenant's
            # next request for work it never received and leak one map entry
            # per tenant whose last wait timed out (ip-keyed tenants make
            # that unbounded under exactly the overload that forms queues).
            self._tenant_vtime.pop(ticket.tenant or "", None)

    async def admit(
        self,
        get_endpoints,
        model: str,
        api_kind: TpsApiKind,
        timeout_s: float | None = None,
        prefix_hash: str | None = None,
        tenant: str | None = None,
        weight: float = 1.0,
    ) -> WaitResult:
        """Admit onto the best endpoint, parking until a slot frees or the
        queue timeout passes. `get_endpoints` is re-invoked on every retry so
        registry changes (recovered/added endpoints) are picked up.
        `prefix_hash` biases selection toward the endpoint whose prefix KV
        cache is warm for this prompt head. `tenant`/`weight` feed the
        weighted-fair queue order — the uncontended fast path below never
        touches WFQ state, so fairness costs nothing until there is a queue."""
        start = time.monotonic()
        got = self.manager.try_admit(get_endpoints(), model, api_kind,
                                     prefix_hash)
        if got is not None:
            return WaitResult(admitted=True, endpoint=got[0], lease=got[1])

        if timeout_s is None:
            timeout_s = self.manager.queue_config.queue_timeout_s
        self._loop = asyncio.get_running_loop()
        deadline = start + timeout_s
        ticket = self._enqueue(tenant, weight)
        serviced = False
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return WaitResult(
                        admitted=False,
                        queue_position=self.position(ticket),
                        waited_s=time.monotonic() - start,
                    )
                ticket.future = self._loop.create_future()
                try:
                    # The release notification is the fast path; the bounded
                    # wait is a slow safety tick so capacity that appears
                    # WITHOUT a release (an endpoint registering or
                    # recovering mid-wait) is still noticed promptly.
                    await asyncio.wait_for(
                        ticket.future,
                        timeout=min(remaining, RECHECK_INTERVAL_S),
                    )
                except asyncio.TimeoutError:
                    pass  # fall through to retry; deadline checked at top
                if self.metrics is not None:
                    self.metrics.record_retry(api_kind.value)
                got = self.manager.try_admit(get_endpoints(), model, api_kind,
                                             prefix_hash)
                if got is not None:
                    serviced = True
                    return WaitResult(
                        admitted=True, endpoint=got[0], lease=got[1],
                        queue_position=self.position(ticket),
                        waited_s=time.monotonic() - start,
                    )
        finally:
            self._dequeue(ticket, serviced)
