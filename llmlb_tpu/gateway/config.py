"""Environment-based configuration.

Parity with the reference's env scheme (reference config.rs:28-77; README
LLMLB_* table): same variable names so a reference deployment's env carries
over. No config files; runtime-mutable settings live in the DB settings table.
"""

from __future__ import annotations

import dataclasses
import logging
import os


def env_str(name: str, default: str | None = None) -> str | None:
    return os.environ.get(name, default)


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def env_bool(name: str, default: bool = False) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class QueueConfig:
    """Admission/queue behavior when all endpoints for a model are busy."""

    max_queue_size: int = 100
    queue_timeout_s: float = 30.0
    max_active_per_endpoint: int = 32

    @classmethod
    def from_env(cls) -> "QueueConfig":
        return cls(
            max_queue_size=env_int("LLMLB_QUEUE_MAX_SIZE", 100),
            queue_timeout_s=env_float("LLMLB_QUEUE_TIMEOUT_SECS", 30.0),
            max_active_per_endpoint=env_int("LLMLB_MAX_ACTIVE_PER_ENDPOINT", 32),
        )


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """In-band failover + per-endpoint circuit breaking (gateway/resilience.py).

    Retries: a failed upstream attempt (connect error, timeout, retryable
    status) re-runs endpoint selection excluding the failed endpoint, with
    capped exponential backoff + jitter, under a global retry budget —
    retries are capped as a fraction of recent request volume so a melting
    fleet is not amplified by its own failover traffic.

    Breaker: consecutive in-band failures trip an endpoint open (ejected
    from selection immediately, no 30 s health-probe wait); after the open
    interval one half-open probe request is admitted, and its outcome
    closes or re-opens (with doubled interval, capped) the breaker.
    """

    enabled: bool = True
    max_attempts: int = 3  # total tries per request, incl. the first
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    # Re-selection after a failure parks at most this long for a slot —
    # a failed request must not burn the full client queue timeout again.
    failover_queue_timeout_s: float = 5.0
    retry_budget_ratio: float = 0.2  # retries per recent request
    retry_budget_min: int = 10  # floor: always allow this many per window
    retry_budget_window_s: float = 60.0
    retryable_statuses: tuple[int, ...] = (429, 500, 502, 503, 504)
    breaker_failure_threshold: int = 5  # consecutive failures to trip
    breaker_open_s: float = 10.0
    breaker_open_max_s: float = 120.0  # repeated trips double up to this
    breaker_half_open_probes: int = 1

    @classmethod
    def from_env(cls) -> "ResilienceConfig":
        raw_statuses = env_str("LLMLB_RETRY_STATUSES", "")
        statuses = cls.retryable_statuses
        if raw_statuses:
            try:
                statuses = tuple(
                    int(s) for s in raw_statuses.split(",") if s.strip()
                )
            except ValueError:
                logging.getLogger("llmlb_tpu.gateway.config").warning(
                    "LLMLB_RETRY_STATUSES=%r is not a comma-separated list "
                    "of integers; using default %r",
                    raw_statuses, statuses,
                )
        return cls(
            enabled=env_bool("LLMLB_RESILIENCE", True),
            max_attempts=max(1, env_int("LLMLB_RETRY_MAX_ATTEMPTS", 3)),
            backoff_base_s=env_float("LLMLB_RETRY_BACKOFF_BASE", 0.05),
            backoff_cap_s=env_float("LLMLB_RETRY_BACKOFF_CAP", 2.0),
            failover_queue_timeout_s=env_float(
                "LLMLB_FAILOVER_QUEUE_TIMEOUT", 5.0
            ),
            retry_budget_ratio=env_float("LLMLB_RETRY_BUDGET_RATIO", 0.2),
            retry_budget_min=env_int("LLMLB_RETRY_BUDGET_MIN", 10),
            retry_budget_window_s=env_float("LLMLB_RETRY_BUDGET_WINDOW", 60.0),
            retryable_statuses=statuses,
            breaker_failure_threshold=max(
                1, env_int("LLMLB_BREAKER_FAILURE_THRESHOLD", 5)
            ),
            breaker_open_s=env_float("LLMLB_BREAKER_OPEN_SECS", 10.0),
            breaker_open_max_s=env_float("LLMLB_BREAKER_OPEN_MAX_SECS", 120.0),
            breaker_half_open_probes=max(
                1, env_int("LLMLB_BREAKER_HALF_OPEN_PROBES", 1)
            ),
        )


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """Latency-SLO targets for goodput accounting (gateway/metrics.py).

    Goodput — the fraction of requests meeting their TTFT and inter-token
    latency targets — is the serving figure that survives adversarial
    traffic, where raw throughput lies ("Answer Fast" framing, PAPERS.md;
    ROADMAP item 5). Targets are per model with a global default:

        LLMLB_SLO_TTFT_MS      default TTFT target (default 2000)
        LLMLB_SLO_ITL_MS       default mean-ITL target (default 200)
        LLMLB_SLO_TARGETS      JSON per-model overrides, e.g.
                               {"llama-3-8b": {"ttft_ms": 500, "itl_ms": 50}}
        LLMLB_SLO=0            disable goodput accounting entirely
    """

    enabled: bool = True
    ttft_target_s: float = 2.0
    itl_target_s: float = 0.2
    # model -> (ttft_target_s, itl_target_s); fall back to the defaults
    per_model: dict = dataclasses.field(default_factory=dict)

    def targets_for(self, model: str) -> tuple[float, float]:
        override = self.per_model.get(model)
        if override is not None:
            return override
        return self.ttft_target_s, self.itl_target_s

    @classmethod
    def from_env(cls) -> "SloConfig":
        per_model: dict = {}
        raw = env_str("LLMLB_SLO_TARGETS", "")
        default_ttft = env_float("LLMLB_SLO_TTFT_MS", 2000.0) / 1000.0
        default_itl = env_float("LLMLB_SLO_ITL_MS", 200.0) / 1000.0
        if raw:
            import json

            try:
                parsed = json.loads(raw)
                for model, t in parsed.items():
                    per_model[str(model)] = (
                        float(t.get("ttft_ms", default_ttft * 1000)) / 1000.0,
                        float(t.get("itl_ms", default_itl * 1000)) / 1000.0,
                    )
            except (ValueError, AttributeError, TypeError):
                logging.getLogger("llmlb_tpu.gateway.config").warning(
                    "LLMLB_SLO_TARGETS=%r is not a JSON object of "
                    '{"model": {"ttft_ms": N, "itl_ms": N}}; ignoring', raw,
                )
                per_model = {}
        return cls(
            enabled=env_bool("LLMLB_SLO", True),
            ttft_target_s=default_ttft,
            itl_target_s=default_itl,
            per_model=per_model,
        )


@dataclasses.dataclass(frozen=True)
class RateLimitConfig:
    """Per-API-key token-bucket rate limits (gateway/ratelimit.py).

    Two buckets per tenant: requests/second (burst-capped) and tokens/minute
    (prompt estimate debited at admission, completion tokens debited after
    the response — the bucket may go negative, throttling the NEXT request).
    A refused request gets 429 with Retry-After computed from the bucket's
    refill rate. Defaults are 0 = unlimited; per-key overrides by API-key
    name (or id):

        LLMLB_RATELIMIT_RPS        default requests/second per key (0 = off)
        LLMLB_RATELIMIT_BURST      bucket size (default 2x rps, min 1)
        LLMLB_RATELIMIT_TPM        default tokens/minute per key (0 = off)
        LLMLB_RATELIMIT_OVERRIDES  JSON per-key overrides, e.g.
                                   {"bulk-batch": {"rps": 1, "tpm": 6000}}

    Multi-worker: with the gossip bus up, buckets are fleet-GLOBAL — each
    worker enforces the full limit and replicates its admitted spends as
    `rl_spend` deltas (RateLimiter.attach_gossip), so a tenant at rps=N is
    admitted ≈N across all workers and mesh-federated hosts. With gossip
    disabled, limits divide by the worker count (each worker enforces
    limit/N) — conservative, like retry budgets.
    """

    requests_per_s: float = 0.0
    burst: float = 0.0  # 0 -> 2x rps (min 1)
    tokens_per_min: float = 0.0
    overrides: dict = dataclasses.field(default_factory=dict)

    @property
    def enabled(self) -> bool:
        return (self.requests_per_s > 0 or self.tokens_per_min > 0
                or bool(self.overrides))

    @classmethod
    def from_env(cls) -> "RateLimitConfig":
        overrides: dict = {}
        raw = env_str("LLMLB_RATELIMIT_OVERRIDES", "")
        if raw:
            import json

            try:
                parsed = json.loads(raw)
                for key, t in parsed.items():
                    # keep ONLY the keys the operator wrote: an absent key
                    # inherits the global default, an explicit 0 means
                    # unlimited for that key (see RateLimiter._limits_for)
                    overrides[str(key)] = {
                        k: float(t[k]) for k in ("rps", "burst", "tpm")
                        if k in t
                    }
            except (ValueError, AttributeError, TypeError):
                logging.getLogger("llmlb_tpu.gateway.config").warning(
                    "LLMLB_RATELIMIT_OVERRIDES=%r is not a JSON object of "
                    '{"key": {"rps": N, "burst": N, "tpm": N}}; ignoring',
                    raw,
                )
                overrides = {}
        return cls(
            requests_per_s=env_float("LLMLB_RATELIMIT_RPS", 0.0),
            burst=env_float("LLMLB_RATELIMIT_BURST", 0.0),
            tokens_per_min=env_float("LLMLB_RATELIMIT_TPM", 0.0),
            overrides=overrides,
        )


def wfq_weights_from_env() -> dict[str, float]:
    """LLMLB_WFQ_WEIGHTS: JSON of per-tenant weights for the weighted fair
    admission queue, keyed by API-key name (default weight 1.0). A weight-2
    tenant drains twice as fast through a contended queue."""
    raw = env_str("LLMLB_WFQ_WEIGHTS", "")
    if not raw:
        return {}
    import json

    try:
        parsed = json.loads(raw)
        return {str(k): max(0.01, float(v)) for k, v in parsed.items()}
    except (ValueError, AttributeError, TypeError):
        logging.getLogger("llmlb_tpu.gateway.config").warning(
            'LLMLB_WFQ_WEIGHTS=%r is not a JSON object of {"key": weight}; '
            "ignoring", raw,
        )
        return {}


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    host: str = "0.0.0.0"
    port: int = 32768  # reference default port
    database_url: str = ""
    jwt_secret: str | None = None
    log_level: str = "info"
    health_check_interval_s: float = 30.0
    health_check_timeout_s: float = 5.0
    request_history_retention_days: int = 7
    inference_timeout_s: float = 300.0
    admin_username: str = "admin"
    admin_password: str | None = None
    auto_sync_interval_s: float = 300.0
    update_drain_timeout_s: float = 300.0
    # Slow-loris protection: an SSE write that cannot reach the client
    # within this many seconds aborts the stream (freeing the engine slot)
    # instead of pinning it for the full inference timeout. 0 disables.
    stream_write_timeout_s: float = 30.0
    # Default request deadline in ms applied when the client sends none
    # (X-Request-Deadline-Ms header wins). 0 = no default deadline.
    request_deadline_ms: float = 0.0
    # Durable streams (gateway/replay.py, docs/resilience.md): when a tpu://
    # engine dies mid-stream, replay prompt+committed tokens onto another
    # engine and splice the token-identical continuation into the SAME
    # client response instead of emitting a terminal error frame.
    stream_resume: bool = True
    # Resume attempts per stream (each also spends the global retry budget).
    stream_resume_attempts: int = 2

    @classmethod
    def from_env(cls) -> "ServerConfig":
        data_dir = os.path.expanduser(env_str("LLMLB_DATA_DIR", "~/.llmlb") or "~/.llmlb")
        return cls(
            host=env_str("LLMLB_HOST", "0.0.0.0") or "0.0.0.0",
            port=env_int("LLMLB_PORT", 32768),
            database_url=env_str(
                "LLMLB_DATABASE_URL", os.path.join(data_dir, "llmlb.db")
            )
            or "",
            jwt_secret=env_str("LLMLB_JWT_SECRET"),
            log_level=env_str("LLMLB_LOG_LEVEL", "info") or "info",
            health_check_interval_s=env_float("LLMLB_HEALTH_CHECK_INTERVAL", 30.0),
            health_check_timeout_s=env_float("LLMLB_HEALTH_CHECK_TIMEOUT", 5.0),
            request_history_retention_days=env_int(
                "LLMLB_REQUEST_HISTORY_RETENTION_DAYS", 7
            ),
            inference_timeout_s=env_float("LLMLB_INFERENCE_TIMEOUT", 300.0),
            admin_username=env_str("LLMLB_ADMIN_USERNAME", "admin") or "admin",
            admin_password=env_str("LLMLB_ADMIN_PASSWORD"),
            auto_sync_interval_s=env_float("LLMLB_AUTO_SYNC_INTERVAL", 300.0),
            update_drain_timeout_s=env_float("LLMLB_UPDATE_DRAIN_TIMEOUT", 300.0),
            stream_write_timeout_s=env_float(
                "LLMLB_STREAM_WRITE_TIMEOUT", 30.0
            ),
            request_deadline_ms=env_float("LLMLB_REQUEST_DEADLINE_MS", 0.0),
            stream_resume=env_bool("LLMLB_STREAM_RESUME", True),
            stream_resume_attempts=max(
                0, env_int("LLMLB_STREAM_RESUME_ATTEMPTS", 2)
            ),
        )
