"""Environment-based configuration.

Parity with the reference's env scheme (reference config.rs:28-77; README
LLMLB_* table): same variable names so a reference deployment's env carries
over. No config files; runtime-mutable settings live in the DB settings table.
"""

from __future__ import annotations

import dataclasses
import os


def env_str(name: str, default: str | None = None) -> str | None:
    return os.environ.get(name, default)


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def env_bool(name: str, default: bool = False) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class QueueConfig:
    """Admission/queue behavior when all endpoints for a model are busy."""

    max_queue_size: int = 100
    queue_timeout_s: float = 30.0
    max_active_per_endpoint: int = 32

    @classmethod
    def from_env(cls) -> "QueueConfig":
        return cls(
            max_queue_size=env_int("LLMLB_QUEUE_MAX_SIZE", 100),
            queue_timeout_s=env_float("LLMLB_QUEUE_TIMEOUT_SECS", 30.0),
            max_active_per_endpoint=env_int("LLMLB_MAX_ACTIVE_PER_ENDPOINT", 32),
        )


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    host: str = "0.0.0.0"
    port: int = 32768  # reference default port
    database_url: str = ""
    jwt_secret: str | None = None
    log_level: str = "info"
    health_check_interval_s: float = 30.0
    health_check_timeout_s: float = 5.0
    request_history_retention_days: int = 7
    inference_timeout_s: float = 300.0
    admin_username: str = "admin"
    admin_password: str | None = None
    auto_sync_interval_s: float = 300.0
    update_drain_timeout_s: float = 300.0

    @classmethod
    def from_env(cls) -> "ServerConfig":
        data_dir = os.path.expanduser(env_str("LLMLB_DATA_DIR", "~/.llmlb") or "~/.llmlb")
        return cls(
            host=env_str("LLMLB_HOST", "0.0.0.0") or "0.0.0.0",
            port=env_int("LLMLB_PORT", 32768),
            database_url=env_str(
                "LLMLB_DATABASE_URL", os.path.join(data_dir, "llmlb.db")
            )
            or "",
            jwt_secret=env_str("LLMLB_JWT_SECRET"),
            log_level=env_str("LLMLB_LOG_LEVEL", "info") or "info",
            health_check_interval_s=env_float("LLMLB_HEALTH_CHECK_INTERVAL", 30.0),
            health_check_timeout_s=env_float("LLMLB_HEALTH_CHECK_TIMEOUT", 5.0),
            request_history_retention_days=env_int(
                "LLMLB_REQUEST_HISTORY_RETENTION_DAYS", 7
            ),
            inference_timeout_s=env_float("LLMLB_INFERENCE_TIMEOUT", 300.0),
            admin_username=env_str("LLMLB_ADMIN_USERNAME", "admin") or "admin",
            admin_password=env_str("LLMLB_ADMIN_PASSWORD"),
            auto_sync_interval_s=env_float("LLMLB_AUTO_SYNC_INTERVAL", 300.0),
            update_drain_timeout_s=env_float("LLMLB_UPDATE_DRAIN_TIMEOUT", 300.0),
        )
