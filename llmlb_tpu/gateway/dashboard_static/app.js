// SPA core: api client, auth/session, hash router, live WebSocket feed.
// Counterpart of the reference dashboard's App.tsx + hooks/useWebSocket.ts.

import * as views from "/dashboard/views.js";

// ----------------------------------------------------------------- api client

export function token() {
  return localStorage.getItem("llmlb_token") || "";
}

export function me() {
  try {
    return JSON.parse(localStorage.getItem("llmlb_user") || "null");
  } catch {
    return null;
  }
}

export async function api(path, opts = {}) {
  const headers = { ...(opts.headers || {}) };
  if (token()) headers["Authorization"] = `Bearer ${token()}`;
  if (opts.body !== undefined && !(opts.body instanceof FormData)) {
    headers["Content-Type"] = "application/json";
    opts = { ...opts, body: JSON.stringify(opts.body) };
  }
  const resp = await fetch(path, { ...opts, headers });
  if (resp.status === 401 && !path.startsWith("/api/auth/login")) {
    showLogin();
    throw new Error("authentication required");
  }
  let body = null;
  try {
    body = await resp.json();
  } catch {
    body = null;
  }
  if (!resp.ok) {
    const msg = body && (body.error?.message || body.error || resp.statusText);
    throw new Error(typeof msg === "string" ? msg : JSON.stringify(msg));
  }
  return body;
}

export function toast(message, isError = false) {
  const root = document.getElementById("toasts");
  const node = document.createElement("div");
  node.className = "toast" + (isError ? " error" : "");
  node.textContent = message;
  root.appendChild(node);
  setTimeout(() => node.remove(), 5000);
}

// --------------------------------------------------------------------- login

function showLogin() {
  closeWs();
  document.getElementById("shell").classList.add("hidden");
  const root = document.getElementById("login-root");
  root.classList.remove("hidden");
  root.innerHTML = `
    <div class="card login-card">
      <h1>llmlb<span class="brand-tpu">tpu</span></h1>
      <div class="login-error" id="login-error"></div>
      <input id="login-user" placeholder="username" autocomplete="username">
      <input id="login-pass" type="password" placeholder="password"
             autocomplete="current-password">
      <button class="primary" id="login-btn">Sign in</button>
    </div>`;
  const submit = async () => {
    const err = document.getElementById("login-error");
    err.textContent = "";
    try {
      const body = await api("/api/auth/login", {
        method: "POST",
        body: {
          username: document.getElementById("login-user").value,
          password: document.getElementById("login-pass").value,
        },
      });
      localStorage.setItem("llmlb_token", body.token);
      localStorage.setItem("llmlb_user", JSON.stringify(body.user));
      root.classList.add("hidden");
      boot();
    } catch (e) {
      err.textContent = e.message || "login failed";
    }
  };
  document.getElementById("login-btn").addEventListener("click", submit);
  // showLogin() can run many times (every 401); keep exactly one handler
  // on the persistent root node or Enter would submit N times
  if (root._onEnter) root.removeEventListener("keydown", root._onEnter);
  root._onEnter = (ev) => {
    if (ev.key === "Enter") submit();
  };
  root.addEventListener("keydown", root._onEnter);
  document.getElementById("login-user").focus();
}

// ------------------------------------------------------------------ live feed

let ws = null;
const wsListeners = new Set();

export function onEvent(fn) {
  wsListeners.add(fn);
  return () => wsListeners.delete(fn);
}

function closeWs() {
  if (ws) {
    ws.onclose = null;
    ws.close();
    ws = null;
  }
}

function connectWs() {
  closeWs();
  const proto = location.protocol === "https:" ? "wss" : "ws";
  ws = new WebSocket(
    `${proto}://${location.host}/ws/dashboard?token=${encodeURIComponent(token())}`
  );
  const dot = document.getElementById("ws-dot");
  ws.onopen = () => dot.className = "dot online";
  ws.onclose = () => {
    dot.className = "dot offline";
    setTimeout(() => {
      if (token()) connectWs();
    }, 3000);
  };
  ws.onmessage = (msg) => {
    let event;
    try {
      event = JSON.parse(msg.data);
    } catch {
      return;
    }
    for (const fn of wsListeners) {
      try {
        fn(event);
      } catch { /* a broken view listener must not kill the feed */ }
    }
  };
}

// -------------------------------------------------------------------- router

const routes = {
  overview: views.overview,
  endpoints: views.endpoints,
  requests: views.requests,
  tokens: views.tokens,
  clients: views.clients,
  playground: views.playground,
  audit: views.audit,
  access: views.access,
  system: views.system,
};

let disposeView = null;

async function render() {
  const name = (location.hash || "#/overview").replace(/^#\//, "").split("?")[0];
  const route = routes[name] || views.overview;
  document.querySelectorAll(".sidebar a").forEach((a) =>
    a.classList.toggle("active", a.dataset.nav === name));
  if (disposeView) {
    try { disposeView(); } catch { /* ignore */ }
    disposeView = null;
  }
  const view = document.getElementById("view");
  view.innerHTML = "";
  try {
    disposeView = await route(view) || null;
  } catch (e) {
    // textContent, not innerHTML: error strings can echo server/upstream
    // content and must never execute in the admin session
    view.innerHTML = "<h1>Something went wrong</h1>";
    const p = document.createElement("p");
    p.className = "muted";
    p.textContent = e.message || String(e);
    view.appendChild(p);
  }
}

function boot() {
  document.getElementById("shell").classList.remove("hidden");
  const user = me();
  document.getElementById("whoami").textContent =
    user ? `${user.username} (${user.role})` : "";
  connectWs();
  render();
}

window.addEventListener("hashchange", render);

document.addEventListener("DOMContentLoaded", () => {
  document.getElementById("logout").addEventListener("click", async () => {
    try {
      await api("/api/auth/logout", { method: "POST" });
    } catch { /* cookie may already be gone */ }
    localStorage.removeItem("llmlb_token");
    localStorage.removeItem("llmlb_user");
    showLogin();
  });
  if (token()) {
    boot();
  } else {
    showLogin();
  }
});
