// SVG chart helpers: line chart with crosshair tooltip, grouped bar chart.
// Mark specs: 2px lines, >=8px hover markers, recessive grid, legend for
// multi-series, tooltips on hover; series colors come from CSS custom
// properties so light/dark swap without touching chart code.

const NS = "http://www.w3.org/2000/svg";

function el(tag, attrs = {}) {
  const node = document.createElementNS(NS, tag);
  for (const [k, v] of Object.entries(attrs)) node.setAttribute(k, v);
  return node;
}

function cssVar(name) {
  return getComputedStyle(document.documentElement).getPropertyValue(name).trim();
}

let tooltipNode = null;
function tooltip() {
  if (!tooltipNode) {
    tooltipNode = document.createElement("div");
    tooltipNode.className = "chart-tooltip";
    document.body.appendChild(tooltipNode);
  }
  return tooltipNode;
}

function showTip(html, x, y) {
  const t = tooltip();
  t.innerHTML = html;
  t.style.display = "block";
  const w = t.offsetWidth, h = t.offsetHeight;
  const vx = Math.min(x + 14, window.innerWidth - w - 8);
  const vy = Math.max(8, y - h - 10);
  t.style.left = `${vx}px`;
  t.style.top = `${vy}px`;
}

export function hideTip() {
  if (tooltipNode) tooltipNode.style.display = "none";
}

function niceTicks(max, n = 4) {
  if (max <= 0) return [0, 1];
  const step = Math.pow(10, Math.floor(Math.log10(max / n)));
  const mult = [1, 2, 5, 10].find((m) => max / (m * step) <= n) || 10;
  const tick = mult * step;
  const ticks = [];
  for (let v = 0; v <= max + tick * 0.001; v += tick) ticks.push(v);
  return ticks;
}

export function fmtNum(v) {
  if (v >= 1e9) return (v / 1e9).toFixed(1) + "B";
  if (v >= 1e6) return (v / 1e6).toFixed(1) + "M";
  if (v >= 1e3) return (v / 1e3).toFixed(1) + "k";
  return String(Math.round(v * 100) / 100);
}

// series: [{name, color, values: [..]}], labels: x labels (same length)
export function lineChart(container, { series, labels, height = 180, title }) {
  container.innerHTML = "";
  const card = document.createElement("div");
  card.className = "card chart-card";
  if (title) {
    const t = document.createElement("div");
    t.className = "chart-title";
    t.textContent = title;
    card.appendChild(t);
  }
  if (series.length > 1) {
    const legend = document.createElement("div");
    legend.className = "chart-legend";
    for (const s of series) {
      const item = document.createElement("span");
      const sw = document.createElement("span");
      sw.className = "legend-swatch";
      sw.style.background = cssVar(s.color);
      item.appendChild(sw);
      item.appendChild(document.createTextNode(s.name));
      legend.appendChild(item);
    }
    card.appendChild(legend);
  }

  const width = Math.max(320, card.clientWidth || container.clientWidth || 640);
  const pad = { l: 42, r: 12, t: 8, b: 22 };
  const svg = el("svg", {
    viewBox: `0 0 ${width} ${height}`, class: "chart-svg", width: "100%",
    role: "img", "aria-label": title || "line chart",
  });
  const W = width - pad.l - pad.r, H = height - pad.t - pad.b;
  const n = labels.length;
  const maxY = Math.max(1, ...series.flatMap((s) => s.values));
  const x = (i) => pad.l + (n <= 1 ? W / 2 : (i / (n - 1)) * W);
  const y = (v) => pad.t + H - (v / maxY) * H;

  for (const tv of niceTicks(maxY)) {
    svg.appendChild(el("line", {
      x1: pad.l, x2: pad.l + W, y1: y(tv), y2: y(tv), class: "gridline",
    }));
    const lab = el("text", { x: pad.l - 6, y: y(tv) + 3, "text-anchor": "end" });
    lab.textContent = fmtNum(tv);
    svg.appendChild(lab);
  }
  svg.appendChild(el("line", {
    x1: pad.l, x2: pad.l + W, y1: pad.t + H, y2: pad.t + H, class: "axisline",
  }));
  const labelEvery = Math.max(1, Math.ceil(n / 8));
  labels.forEach((lb, i) => {
    if (i % labelEvery) return;
    const t = el("text", { x: x(i), y: height - 6, "text-anchor": "middle" });
    t.textContent = lb;
    svg.appendChild(t);
  });

  for (const s of series) {
    if (!n) continue;
    const d = s.values.map((v, i) =>
      `${i ? "L" : "M"}${x(i).toFixed(1)},${y(v).toFixed(1)}`).join("");
    svg.appendChild(el("path", {
      d, fill: "none", stroke: cssVar(s.color), "stroke-width": 2,
      "stroke-linejoin": "round", "stroke-linecap": "round",
    }));
  }

  // crosshair + hover markers
  const cross = el("line", {
    y1: pad.t, y2: pad.t + H, class: "axisline", "stroke-dasharray": "3,3",
    visibility: "hidden",
  });
  svg.appendChild(cross);
  const markers = series.map((s) => {
    const m = el("circle", {
      r: 4, fill: cssVar(s.color), stroke: cssVar("--surface-1"),
      "stroke-width": 2, visibility: "hidden",
    });
    svg.appendChild(m);
    return m;
  });

  svg.addEventListener("mousemove", (ev) => {
    if (!n) return;
    const rect = svg.getBoundingClientRect();
    const px = ((ev.clientX - rect.left) / rect.width) * width;
    const i = Math.round(((px - pad.l) / Math.max(W, 1)) * (n - 1));
    if (i < 0 || i >= n) return;
    cross.setAttribute("x1", x(i));
    cross.setAttribute("x2", x(i));
    cross.setAttribute("visibility", "visible");
    series.forEach((s, si) => {
      markers[si].setAttribute("cx", x(i));
      markers[si].setAttribute("cy", y(s.values[i]));
      markers[si].setAttribute("visibility", "visible");
    });
    const rows = series.map((s) =>
      `<div><span class="legend-swatch" style="background:${cssVar(s.color)}"></span>` +
      `${s.name}: <b>${fmtNum(s.values[i])}</b></div>`).join("");
    showTip(`<div class="tt-title">${labels[i]}</div>${rows}`,
            ev.clientX, ev.clientY);
  });
  svg.addEventListener("mouseleave", () => {
    cross.setAttribute("visibility", "hidden");
    markers.forEach((m) => m.setAttribute("visibility", "hidden"));
    hideTip();
  });

  card.appendChild(svg);
  container.appendChild(card);
}

// Grouped bars: series as in lineChart; 4px rounded tops, 2px gaps.
export function barChart(container, { series, labels, height = 180, title }) {
  container.innerHTML = "";
  const card = document.createElement("div");
  card.className = "card chart-card";
  if (title) {
    const t = document.createElement("div");
    t.className = "chart-title";
    t.textContent = title;
    card.appendChild(t);
  }
  if (series.length > 1) {
    const legend = document.createElement("div");
    legend.className = "chart-legend";
    for (const s of series) {
      const item = document.createElement("span");
      const sw = document.createElement("span");
      sw.className = "legend-swatch";
      sw.style.background = cssVar(s.color);
      item.appendChild(sw);
      item.appendChild(document.createTextNode(s.name));
      legend.appendChild(item);
    }
    card.appendChild(legend);
  }
  const width = Math.max(320, card.clientWidth || container.clientWidth || 640);
  const pad = { l: 46, r: 12, t: 8, b: 22 };
  const svg = el("svg", {
    viewBox: `0 0 ${width} ${height}`, class: "chart-svg", width: "100%",
    role: "img", "aria-label": title || "bar chart",
  });
  const W = width - pad.l - pad.r, H = height - pad.t - pad.b;
  const n = labels.length;
  const maxY = Math.max(1, ...series.flatMap((s) => s.values));
  const y = (v) => pad.t + H - (v / maxY) * H;

  for (const tv of niceTicks(maxY)) {
    svg.appendChild(el("line", {
      x1: pad.l, x2: pad.l + W, y1: y(tv), y2: y(tv), class: "gridline",
    }));
    const lab = el("text", { x: pad.l - 6, y: y(tv) + 3, "text-anchor": "end" });
    lab.textContent = fmtNum(tv);
    svg.appendChild(lab);
  }
  svg.appendChild(el("line", {
    x1: pad.l, x2: pad.l + W, y1: pad.t + H, y2: pad.t + H, class: "axisline",
  }));

  const group = W / Math.max(n, 1);
  const barW = Math.max(3, Math.min(26, (group - 8) / series.length - 2));
  const labelEvery = Math.max(1, Math.ceil(n / 10));
  labels.forEach((lb, i) => {
    if (i % labelEvery) return;
    const t = el("text", {
      x: pad.l + group * i + group / 2, y: height - 6, "text-anchor": "middle",
    });
    t.textContent = lb;
    svg.appendChild(t);
  });

  labels.forEach((lb, i) => {
    series.forEach((s, si) => {
      const v = s.values[i] || 0;
      const total = series.length * barW + (series.length - 1) * 2;
      const bx = pad.l + group * i + (group - total) / 2 + si * (barW + 2);
      const by = y(v), bh = pad.t + H - by;
      const r = Math.min(4, bh);
      const bar = el("path", {
        d: `M${bx},${pad.t + H} v${-(bh - r)} q0,-${r} ${r},-${r} ` +
           `h${barW - 2 * r} q${r},0 ${r},${r} v${bh - r} z`,
        fill: cssVar(s.color),
      });
      bar.addEventListener("mousemove", (ev) =>
        showTip(`<div class="tt-title">${lb}</div>` +
                `${s.name}: <b>${fmtNum(v)}</b>`, ev.clientX, ev.clientY));
      bar.addEventListener("mouseleave", hideTip);
      svg.appendChild(bar);
    });
  });

  card.appendChild(svg);
  container.appendChild(card);
}
