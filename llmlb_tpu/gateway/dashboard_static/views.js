// Page renderers. Each view gets the <main> node, returns an optional
// dispose() for timers/listeners. Counterpart of the reference pages
// (Dashboard, AuditLog, EndpointPlayground, LoadBalancerPlayground, etc).

import { api, me, onEvent, toast } from "/dashboard/app.js";
import { barChart, fmtNum, lineChart } from "/dashboard/charts.js";

function h(html) {
  const t = document.createElement("template");
  t.innerHTML = html.trim();
  return t.content.firstChild;
}

function esc(s) {
  return String(s ?? "").replace(/[&<>"']/g, (c) => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
  })[c]);
}

function fmtBytes(n) {
  if (!n) return "0";
  const units = ["B", "KiB", "MiB", "GiB", "TiB"];
  let i = 0, v = n;
  while (v >= 1024 && i < units.length - 1) { v /= 1024; i++; }
  return `${v.toFixed(v >= 10 ? 0 : 1)} ${units[i]}`;
}

function fmtTs(ts) {
  if (!ts) return "—";
  return new Date(ts * 1000).toLocaleString();
}

function statusBadge(status) {
  return `<span class="badge"><span class="dot ${esc(status)}"></span>${esc(status)}</span>`;
}

// ------------------------------------------------------------------ overview

export async function overview(view) {
  view.appendChild(h(`<h1>Overview</h1>`));
  const cards = h(`<div class="cards"></div>`);
  const chartBox = document.createElement("div");
  const tpsBox = document.createElement("div");
  view.appendChild(cards);
  view.appendChild(chartBox);
  view.appendChild(h(`<h2>Measured throughput (tokens/sec EMA)</h2>`));
  view.appendChild(tpsBox);

  async function refresh() {
    const [ov, hist, tps] = await Promise.all([
      api("/api/dashboard/overview"),
      api("/api/dashboard/request-history"),
      api("/api/dashboard/model-tps"),
    ]);
    cards.innerHTML = `
      <div class="card"><div class="tile-label">Endpoints online</div>
        <div class="tile-value">${ov.endpoints.online}<span class="muted">/${ov.endpoints.total}</span></div>
        <div class="tile-sub">${ov.models.total} models</div></div>
      <div class="card"><div class="tile-label">Requests today</div>
        <div class="tile-value">${fmtNum(ov.requests.today)}</div>
        <div class="tile-sub">${ov.requests.active} active · ${fmtNum(ov.requests.errors_today)} errors</div></div>
      <div class="card"><div class="tile-label">Tokens today</div>
        <div class="tile-value">${fmtNum(ov.tokens_today.prompt + ov.tokens_today.completion)}</div>
        <div class="tile-sub">${fmtNum(ov.tokens_today.prompt)} in · ${fmtNum(ov.tokens_today.completion)} out</div></div>
      <div class="card"><div class="tile-label">TPU HBM in use</div>
        <div class="tile-value">${ov.tpu.hbm_total_bytes
          ? Math.round(100 * ov.tpu.hbm_used_bytes / ov.tpu.hbm_total_bytes) + "%"
          : "—"}</div>
        <div class="tile-sub">${ov.tpu.total_chips} chips · ${fmtBytes(ov.tpu.hbm_used_bytes)} / ${fmtBytes(ov.tpu.hbm_total_bytes)}</div></div>`;

    const minutes = hist.minutes;
    const labels = minutes.map((m) =>
      new Date(m.ts * 1000).toLocaleTimeString([], { hour: "2-digit", minute: "2-digit" }));
    lineChart(chartBox, {
      title: "Requests per minute (last hour)",
      labels,
      series: [
        { name: "requests", color: "--series-1", values: minutes.map((m) => m.requests) },
        { name: "errors", color: "--status-serious", values: minutes.map((m) => m.errors) },
      ],
    });

    const entries = Object.entries(tps.tps);
    tpsBox.innerHTML = entries.length ? "" : `<p class="muted">No TPS measurements yet.</p>`;
    if (entries.length) {
      const rows = entries.map(([key, v]) => {
        // key is "eid:model:kind" where model itself may contain colons
        // (e.g. ollama "llama3:8b") — split at the first and last colon
        const i = key.indexOf(":"), j = key.lastIndexOf(":");
        const eid = key.slice(0, i), model = key.slice(i + 1, j),
              kind = key.slice(j + 1);
        return `<tr><td class="mono">${esc(eid.slice(0, 8))}</td>
          <td>${esc(model)}</td><td>${esc(kind)}</td>
          <td><b>${fmtNum(v.ema_tps)}</b> tok/s</td><td>${v.samples}</td></tr>`;
      }).join("");
      tpsBox.innerHTML = `<table><thead><tr><th>endpoint</th><th>model</th>
        <th>api</th><th>TPS (EMA)</th><th>samples</th></tr></thead>
        <tbody>${rows}</tbody></table>`;
    }
  }

  await refresh();
  const timer = setInterval(() => refresh().catch(() => {}), 15000);
  const off = onEvent((ev) => {
    if (["TpsUpdated", "EndpointStatusChanged", "TelemetryUpdated"].includes(ev.type)) {
      refresh().catch(() => {});
    }
  });
  return () => { clearInterval(timer); off(); };
}

// ----------------------------------------------------------------- endpoints

export async function endpoints(view) {
  view.appendChild(h(`<h1>Endpoints</h1>`));
  const form = h(`<div class="formrow">
    <input id="ep-url" placeholder="http://host:port" size="28">
    <input id="ep-name" placeholder="name (optional)" size="14">
    <select id="ep-type">
      <option value="">auto-detect</option>
      <option value="tpu">tpu</option><option value="xllm">xllm</option>
      <option value="ollama">ollama</option><option value="vllm">vllm</option>
      <option value="lm_studio">lm_studio</option>
      <option value="llama_cpp">llama_cpp</option>
      <option value="openai_compatible">openai_compatible</option>
    </select>
    <input id="ep-key" placeholder="api key (optional)" size="16">
    <button class="primary" id="ep-add">Register</button>
  </div>`);
  view.appendChild(form);
  const box = document.createElement("div");
  view.appendChild(box);

  async function refresh() {
    const body = await api("/api/endpoints");
    if (!body.endpoints.length) {
      box.innerHTML = `<p class="muted">No endpoints registered.</p>`;
      return;
    }
    box.innerHTML = "";
    const table = h(`<table><thead><tr>
      <th>status</th><th>name</th><th>type</th><th>latency</th>
      <th>HBM</th><th>models</th><th></th></tr></thead><tbody></tbody></table>`);
    const tbody = table.querySelector("tbody");
    for (const ep of body.endpoints) {
      const acc = ep.accelerator || {};
      const pct = acc.hbm_total_bytes
        ? acc.hbm_used_bytes / acc.hbm_total_bytes : null;
      const models = (ep.models || []).map((m) => m.canonical_name);
      const shown = models.slice(0, 3).map(esc).join(", ") +
        (models.length > 3 ? ` +${models.length - 3}` : "");
      const row = h(`<tr>
        <td>${statusBadge(ep.status)}</td>
        <td><b>${esc(ep.name)}</b><br><span class="muted mono">${esc(ep.base_url)}</span></td>
        <td>${esc(ep.endpoint_type)}</td>
        <td>${ep.latency_ms != null ? ep.latency_ms.toFixed(1) + " ms" : "—"}</td>
        <td>${pct == null ? "—"
          : `<div class="gauge ${pct > 0.85 ? "hot" : ""}" title="${fmtBytes(acc.hbm_used_bytes)} / ${fmtBytes(acc.hbm_total_bytes)}">
               <div style="width:${Math.min(100, pct * 100).toFixed(0)}%"></div></div>`}</td>
        <td>${shown || '<span class="muted">none</span>'}</td>
        <td>
          <button data-act="test">test</button>
          <button data-act="sync">sync</button>
          <button data-act="info">info</button>
          <button data-act="del" class="danger">remove</button>
        </td></tr>`);
      row.querySelector('[data-act="test"]').addEventListener("click", async () => {
        try {
          const r = await api(`/api/endpoints/${ep.id}/test`, { method: "POST" });
          toast(r.ok ? `OK: ${r.detected_type} in ${r.latency_ms}ms`
                     : `Failed: ${r.error}`, !r.ok);
        } catch (e) { toast(e.message, true); }
      });
      row.querySelector('[data-act="sync"]').addEventListener("click", async () => {
        try {
          const r = await api(`/api/endpoints/${ep.id}/sync`, { method: "POST" });
          toast(`Synced: +${r.added} −${r.removed}`);
          refresh();
        } catch (e) { toast(e.message, true); }
      });
      row.querySelector('[data-act="info"]').addEventListener("click", async () => {
        try {
          const r = await api(`/api/endpoints/${ep.id}/system-info`);
          if (!r.available) { toast("No device info exposed by this runtime"); return; }
          const detail = h(`<tr class="detail-row"><td colspan="7">
            <pre class="mono">${esc(JSON.stringify(r.info, null, 2))}</pre></td></tr>`);
          const old = tbody.querySelector(".detail-row");
          if (old) old.remove();
          row.after(detail);
        } catch (e) { toast(e.message, true); }
      });
      row.querySelector('[data-act="del"]').addEventListener("click", async () => {
        if (!confirm(`Remove endpoint ${ep.name}?`)) return;
        try {
          await api(`/api/endpoints/${ep.id}`, { method: "DELETE" });
          refresh();
        } catch (e) { toast(e.message, true); }
      });
      tbody.appendChild(row);
    }
    box.appendChild(table);
  }

  form.querySelector("#ep-add").addEventListener("click", async () => {
    const payload = {
      base_url: form.querySelector("#ep-url").value.trim(),
      name: form.querySelector("#ep-name").value.trim() || undefined,
      endpoint_type: form.querySelector("#ep-type").value || undefined,
      api_key: form.querySelector("#ep-key").value || undefined,
    };
    try {
      await api("/api/endpoints", { method: "POST", body: payload });
      form.querySelector("#ep-url").value = "";
      toast("Endpoint registered");
      refresh();
    } catch (e) { toast(e.message, true); }
  });

  await refresh();
  const off = onEvent((ev) => {
    if (["EndpointStatusChanged", "EndpointRegistered", "EndpointRemoved",
         "TelemetryUpdated"].includes(ev.type)) refresh().catch(() => {});
  });
  return off;
}

// ------------------------------------------------------------------ requests

export async function requests(view) {
  view.appendChild(h(`<h1>Requests</h1>`));
  const filters = h(`<div class="filters">
    <input id="rq-model" placeholder="model">
    <input id="rq-status" placeholder="status code" size="8">
    <button id="rq-go">Filter</button>
  </div>`);
  view.appendChild(filters);
  const box = document.createElement("div");
  const detail = document.createElement("div");
  view.appendChild(box);
  view.appendChild(detail);

  async function refresh() {
    const params = new URLSearchParams();
    const model = filters.querySelector("#rq-model").value.trim();
    const status = filters.querySelector("#rq-status").value.trim();
    if (model) params.set("model", model);
    if (status) params.set("status", status);
    params.set("limit", "100");
    const body = await api(`/api/dashboard/requests?${params}`);
    if (!body.records.length) {
      box.innerHTML = `<p class="muted">No request records.</p>`;
      return;
    }
    const rows = body.records.map((r) => `
      <tr class="clickable" data-id="${esc(r.id)}">
        <td class="mono">${fmtTs(r.ts)}</td>
        <td>${esc(r.model || "—")}</td>
        <td>${esc(r.endpoint_name || "—")}</td>
        <td>${r.status_code >= 400
            ? `<span class="badge"><span class="dot offline"></span>${r.status_code}</span>`
            : r.status_code}</td>
        <td>${(r.duration_ms || 0).toFixed(0)} ms</td>
        <td>${fmtNum(r.prompt_tokens)} / ${fmtNum(r.completion_tokens)}</td>
        <td>${r.stream ? "stream" : ""}</td></tr>`).join("");
    box.innerHTML = `<table><thead><tr><th>time</th><th>model</th>
      <th>endpoint</th><th>status</th><th>duration</th><th>tokens in/out</th>
      <th></th></tr></thead><tbody>${rows}</tbody></table>`;
    box.querySelectorAll("tr.clickable").forEach((tr) =>
      tr.addEventListener("click", async () => {
        const rec = await api(`/api/dashboard/requests/${tr.dataset.id}`);
        detail.innerHTML = `<h2>Record ${esc(rec.id.slice(0, 8))}</h2>
          <div class="card"><pre class="mono">${esc(JSON.stringify(rec, null, 2))}</pre></div>`;
        detail.scrollIntoView({ behavior: "smooth" });
      }));
  }

  filters.querySelector("#rq-go").addEventListener("click", () =>
    refresh().catch((e) => toast(e.message, true)));
  await refresh();
}

// -------------------------------------------------------------------- tokens

export async function tokens(view) {
  view.appendChild(h(`<h1>Token stats</h1>`));
  const chartBox = document.createElement("div");
  const byModel = document.createElement("div");
  view.appendChild(chartBox);
  view.appendChild(h(`<h2>By model (30 days)</h2>`));
  view.appendChild(byModel);

  const stats = await api("/api/dashboard/token-stats?days=30");
  const daily = stats.daily;
  barChart(chartBox, {
    title: "Tokens per day (30 days)",
    labels: daily.map((d) => d.date.slice(5)),
    series: [
      { name: "prompt", color: "--series-1", values: daily.map((d) => d.pt || 0) },
      { name: "completion", color: "--series-3", values: daily.map((d) => d.ct || 0) },
    ],
  });
  const rows = stats.by_model.map((m) => `
    <tr><td>${esc(m.model)}</td><td>${fmtNum(m.requests)}</td>
    <td>${fmtNum(m.pt || 0)}</td><td>${fmtNum(m.ct || 0)}</td></tr>`).join("");
  byModel.innerHTML = stats.by_model.length
    ? `<table><thead><tr><th>model</th><th>requests</th><th>prompt tokens</th>
       <th>completion tokens</th></tr></thead><tbody>${rows}</tbody></table>`
    : `<p class="muted">No data yet.</p>`;
}

// ------------------------------------------------------------------- clients

export async function clients(view) {
  view.appendChild(h(`<h1>Clients</h1>`));
  const controls = h(`<div class="formrow">
    <label>Alert threshold (req/hour)
      <input id="cl-threshold" size="6"></label>
    <button class="primary" id="cl-save">Save</button>
  </div>`);
  view.appendChild(controls);
  const rankBox = document.createElement("div");
  const keyBox = document.createElement("div");
  view.appendChild(rankBox);
  view.appendChild(h(`<h2>By API key (7 days)</h2>`));
  view.appendChild(keyBox);

  async function refresh() {
    const body = await api("/api/dashboard/clients?days=7");
    controls.querySelector("#cl-threshold").value = body.ip_alert_threshold;
    const rows = (body.ranking || []).map((r) => `
      <tr>
        <td class="mono">${esc(r.client_ip)}
          ${r.is_alert
            ? `<span class="badge"><span class="dot offline"></span>alert</span>`
            : ""}</td>
        <td>${fmtNum(r.requests)}</td>
        <td>${fmtNum(r.errors || 0)}</td>
        <td>${fmtNum(r.pt || 0)} / ${fmtNum(r.ct || 0)}</td></tr>`).join("");
    rankBox.innerHTML = rows
      ? `<table><thead><tr><th>client ip</th><th>requests</th><th>errors</th>
         <th>tokens in/out</th></tr></thead><tbody>${rows}</tbody></table>`
      : `<p class="muted">No client traffic recorded.</p>`;
    const keyRows = (body.by_api_key || []).map((r) => `
      <tr><td class="mono">${esc(r.api_key_id)}</td>
      <td>${fmtNum(r.requests)}</td><td>${fmtNum(r.ct || 0)}</td></tr>`).join("");
    keyBox.innerHTML = keyRows
      ? `<table><thead><tr><th>api key</th><th>requests</th>
         <th>completion tokens</th></tr></thead><tbody>${keyRows}</tbody></table>`
      : `<p class="muted">No API-key traffic.</p>`;
  }

  controls.querySelector("#cl-save").addEventListener("click", async () => {
    try {
      await api("/api/dashboard/settings", {
        method: "PUT",
        body: { key: "ip_alert_threshold",
                value: controls.querySelector("#cl-threshold").value.trim() },
      });
      toast("Threshold saved");
      refresh();
    } catch (e) { toast(e.message, true); }
  });

  await refresh();
}

// ---------------------------------------------------------------- playground

export async function playground(view) {
  view.appendChild(h(`<h1>Playground</h1>`));
  const eps = await api("/api/endpoints");
  const models = await api("/v1/models").catch(() => ({ data: [] }));
  const epOptions = eps.endpoints
    .map((e) => `<option value="${esc(e.id)}">${esc(e.name)}</option>`).join("");
  const modelOptions = (models.data || [])
    .map((m) => `<option>${esc(m.id)}</option>`).join("");
  const ui = h(`<div>
    <div class="formrow">
      <select id="pg-mode">
        <option value="lb">via load balancer (/v1/chat/completions)</option>
        <option value="pin">pinned endpoint (playground proxy)</option>
      </select>
      <select id="pg-model">${modelOptions || "<option value=''>no models</option>"}</select>
      <select id="pg-endpoint" class="hidden">${epOptions}</select>
      <label><input type="checkbox" id="pg-stream" checked> stream</label>
    </div>
    <div class="chat-log" id="pg-log"></div>
    <div class="formrow">
      <textarea id="pg-input" rows="2" placeholder="Say something…" style="flex:1"></textarea>
      <button class="primary" id="pg-send">Send</button>
    </div>
  </div>`);
  view.appendChild(ui);
  const log = ui.querySelector("#pg-log");
  const history = [];

  ui.querySelector("#pg-mode").addEventListener("change", (ev) => {
    ui.querySelector("#pg-endpoint").classList.toggle("hidden", ev.target.value !== "pin");
    ui.querySelector("#pg-stream").disabled = ev.target.value === "pin";
  });

  function addMsg(who, text) {
    const node = h(`<div class="msg"><div class="who">${esc(who)}</div>
      <pre>${esc(text)}</pre></div>`);
    log.appendChild(node);
    log.scrollTop = log.scrollHeight;
    return node.querySelector("pre");
  }

  async function send() {
    const input = ui.querySelector("#pg-input");
    const text = input.value.trim();
    if (!text) return;
    input.value = "";
    addMsg(me()?.username || "you", text);
    history.push({ role: "user", content: text });
    const mode = ui.querySelector("#pg-mode").value;
    const model = ui.querySelector("#pg-model").value;
    const stream = ui.querySelector("#pg-stream").checked && mode === "lb";
    const out = addMsg(model || "assistant", "…");
    const btn = ui.querySelector("#pg-send");
    btn.disabled = true;
    try {
      const url = mode === "lb"
        ? "/v1/chat/completions"
        : `/api/endpoints/${ui.querySelector("#pg-endpoint").value}/chat/completions`;
      const resp = await fetch(url, {
        method: "POST",
        headers: {
          "Content-Type": "application/json",
          "Authorization": `Bearer ${localStorage.getItem("llmlb_token")}`,
        },
        body: JSON.stringify({
          model, stream, max_tokens: 512,
          messages: history.slice(-12),
        }),
      });
      if (!resp.ok) {
        const err = await resp.json().catch(() => null);
        throw new Error(err?.error?.message || err?.error || `HTTP ${resp.status}`);
      }
      let full = "";
      if (stream) {
        const reader = resp.body.getReader();
        const dec = new TextDecoder();
        let buf = "";
        for (;;) {
          const { value, done } = await reader.read();
          if (done) break;
          buf += dec.decode(value, { stream: true });
          const lines = buf.split("\n");
          buf = lines.pop();
          for (const line of lines) {
            if (!line.startsWith("data:")) continue;
            const data = line.slice(5).trim();
            if (data === "[DONE]") continue;
            try {
              const chunk = JSON.parse(data);
              const delta = chunk.choices?.[0]?.delta?.content || "";
              if (delta) { full += delta; out.textContent = full; }
            } catch { /* partial frame */ }
          }
          log.scrollTop = log.scrollHeight;
        }
      } else {
        const body = await resp.json();
        full = body.choices?.[0]?.message?.content ?? JSON.stringify(body);
        out.textContent = full;
      }
      history.push({ role: "assistant", content: full });
    } catch (e) {
      out.textContent = `error: ${e.message}`;
    } finally {
      btn.disabled = false;
    }
  }

  ui.querySelector("#pg-send").addEventListener("click", send);
  ui.querySelector("#pg-input").addEventListener("keydown", (ev) => {
    if (ev.key === "Enter" && !ev.shiftKey) { ev.preventDefault(); send(); }
  });
}

// --------------------------------------------------------------------- audit

export async function audit(view) {
  view.appendChild(h(`<h1>Audit log</h1>`));
  const filters = h(`<div class="filters">
    <input id="au-q" placeholder="search (FTS)">
    <input id="au-actor" placeholder="actor" size="12">
    <input id="au-path" placeholder="path prefix" size="14">
    <button id="au-go">Search</button>
    <button id="au-verify">Verify chain</button>
  </div>`);
  view.appendChild(filters);
  const box = document.createElement("div");
  view.appendChild(box);

  async function refresh() {
    const params = new URLSearchParams();
    for (const [id, key] of [["au-q", "q"], ["au-actor", "actor"], ["au-path", "path"]]) {
      const v = filters.querySelector(`#${id}`).value.trim();
      if (v) params.set(key, v);
    }
    params.set("limit", "200");
    const body = await api(`/api/audit-log?${params}`);
    if (!body.entries.length) {
      box.innerHTML = `<p class="muted">No matching entries.</p>`;
      return;
    }
    const rows = body.entries.map((e) => `
      <tr><td class="mono">${fmtTs(e.ts)}</td>
      <td>${esc(e.actor || "anonymous")}<br><span class="muted">${esc(e.actor_type || "")}</span></td>
      <td class="mono">${esc(e.method)} ${esc(e.path)}</td>
      <td>${e.status >= 400
          ? `<span class="badge"><span class="dot offline"></span>${e.status}</span>` : e.status}</td>
      <td>${(e.duration_ms || 0).toFixed(1)} ms</td>
      <td class="mono">${esc(e.ip || "")}</td></tr>`).join("");
    box.innerHTML = `<table><thead><tr><th>time</th><th>actor</th>
      <th>request</th><th>status</th><th>duration</th><th>ip</th></tr></thead>
      <tbody>${rows}</tbody></table>`;
  }

  filters.querySelector("#au-go").addEventListener("click", () =>
    refresh().catch((e) => toast(e.message, true)));
  filters.querySelector("#au-verify").addEventListener("click", async () => {
    try {
      const r = await api("/api/audit-log/verify", { method: "POST" });
      toast(r.ok ? "Audit chain verified — no tampering detected"
                 : `CHAIN BROKEN: ${r.error}`, !r.ok);
    } catch (e) { toast(e.message, true); }
  });
  await refresh();
}

// -------------------------------------------------------- users / keys / invites

export async function access(view) {
  view.appendChild(h(`<h1>Users &amp; API keys</h1>`));
  const usersBox = document.createElement("div");
  const keysBox = document.createElement("div");
  const invBox = document.createElement("div");
  view.appendChild(h(`<h2>Users</h2>`));
  view.appendChild(usersBox);
  view.appendChild(h(`<h2>API keys</h2>`));
  view.appendChild(keysBox);
  view.appendChild(h(`<h2>Invitations</h2>`));
  view.appendChild(invBox);

  async function refreshUsers() {
    const body = await api("/api/users").catch(() => null);
    if (!body) { usersBox.innerHTML = `<p class="muted">Admin only.</p>`; return; }
    const rows = body.users.map((u) => `
      <tr><td><b>${esc(u.username)}</b></td><td>${esc(u.role)}</td>
      <td>${u.must_change_password ? "must change password" : ""}</td>
      <td><button data-id="${esc(u.id)}" class="danger">delete</button></td></tr>`).join("");
    usersBox.innerHTML = `<table><thead><tr><th>user</th><th>role</th><th></th>
      <th></th></tr></thead><tbody>${rows}</tbody></table>`;
    usersBox.querySelectorAll("button").forEach((b) =>
      b.addEventListener("click", async () => {
        if (!confirm("Delete user?")) return;
        try { await api(`/api/users/${b.dataset.id}`, { method: "DELETE" }); refreshUsers(); }
        catch (e) { toast(e.message, true); }
      }));
  }

  async function refreshKeys() {
    const body = await api("/api/api-keys");
    const rows = (body.api_keys || []).map((k) => `
      <tr><td><b>${esc(k.name)}</b> <span class="muted mono">${esc(k.key_prefix)}…</span></td>
      <td class="mono">${(k.permissions || []).map(esc).join(", ")}</td>
      <td>${fmtTs(k.created_at)}</td>
      <td><button data-id="${esc(k.id)}" class="danger">revoke</button></td></tr>`).join("");
    keysBox.innerHTML = `
      <div class="formrow">
        <input id="key-name" placeholder="key name">
        <select id="key-perms" multiple size="3">
          <option value="openai.inference" selected>openai.inference</option>
          <option value="openai.models.read">openai.models.read</option>
          <option value="endpoints.read">endpoints.read</option>
          <option value="endpoints.manage">endpoints.manage</option>
          <option value="metrics.read">metrics.read</option>
          <option value="logs.read">logs.read</option>
        </select>
        <button class="primary" id="key-add">Create key</button>
      </div>
      ${rows ? `<table><thead><tr><th>key</th><th>permissions</th><th>created</th>
        <th></th></tr></thead><tbody>${rows}</tbody></table>`
             : '<p class="muted">No API keys.</p>'}`;
    keysBox.querySelector("#key-add").addEventListener("click", async () => {
      const name = keysBox.querySelector("#key-name").value.trim() || "key";
      const perms = [...keysBox.querySelector("#key-perms").selectedOptions].map((o) => o.value);
      try {
        const r = await api("/api/api-keys", { method: "POST",
                            body: { name, permissions: perms } });
        prompt("API key (copy now — shown once):", r.api_key);
        refreshKeys();
      } catch (e) { toast(e.message, true); }
    });
    keysBox.querySelectorAll("button.danger").forEach((b) =>
      b.addEventListener("click", async () => {
        try { await api(`/api/api-keys/${b.dataset.id}`, { method: "DELETE" }); refreshKeys(); }
        catch (e) { toast(e.message, true); }
      }));
  }

  async function refreshInvites() {
    const body = await api("/api/invitations").catch(() => null);
    if (!body) { invBox.innerHTML = `<p class="muted">Admin only.</p>`; return; }
    const rows = (body.invitations || []).map((i) => `
      <tr><td class="mono">${esc(i.code)}</td><td>${esc(i.role)}</td>
      <td>${i.used_by ? "used" : "open"}</td>
      <td><button data-id="${esc(i.id)}" class="danger">delete</button></td></tr>`).join("");
    invBox.innerHTML = `
      <div class="formrow">
        <select id="inv-role"><option>viewer</option><option>admin</option></select>
        <button class="primary" id="inv-add">Create invitation</button>
      </div>
      ${rows ? `<table><thead><tr><th>code</th><th>role</th><th>state</th><th></th>
        </tr></thead><tbody>${rows}</tbody></table>`
             : '<p class="muted">No invitations.</p>'}`;
    invBox.querySelector("#inv-add").addEventListener("click", async () => {
      try {
        await api("/api/invitations", { method: "POST",
                  body: { role: invBox.querySelector("#inv-role").value } });
        refreshInvites();
      } catch (e) { toast(e.message, true); }
    });
    invBox.querySelectorAll("button.danger").forEach((b) =>
      b.addEventListener("click", async () => {
        try { await api(`/api/invitations/${b.dataset.id}`, { method: "DELETE" }); refreshInvites(); }
        catch (e) { toast(e.message, true); }
      }));
  }

  await Promise.all([refreshUsers(), refreshKeys(), refreshInvites()]);
}

// -------------------------------------------------------------------- system

export async function system(view) {
  view.appendChild(h(`<h1>System</h1>`));
  const sysBox = document.createElement("div");
  const logBox = document.createElement("div");
  view.appendChild(sysBox);
  view.appendChild(h(`<h2>Gateway log</h2>`));
  view.appendChild(logBox);

  async function refresh() {
    const sys = await api("/api/system");
    const upd = sys.update || {};
    sysBox.innerHTML = `
      <div class="cards">
        <div class="card"><div class="tile-label">Version</div>
          <div class="tile-value">${esc(sys.version || "dev")}</div></div>
        <div class="card"><div class="tile-label">Update state</div>
          <div class="tile-value" style="font-size:18px">${esc(upd.state || "n/a")}</div>
          <div class="tile-sub">${esc(upd.available_version || "")}</div></div>
      </div>
      <div class="formrow">
        <button id="upd-check">Check for updates</button>
        <button id="upd-apply" class="primary">Apply update</button>
      </div>`;
    sysBox.querySelector("#upd-check").addEventListener("click", async () => {
      try {
        const r = await api("/api/system/update/check", { method: "POST" });
        toast(r.available ? `Update available: ${r.version}` : "Up to date");
        refresh();
      } catch (e) { toast(e.message, true); }
    });
    sysBox.querySelector("#upd-apply").addEventListener("click", async () => {
      if (!confirm("Drain traffic and apply the update?")) return;
      try {
        await api("/api/system/update/apply", { method: "POST", body: {} });
        toast("Update apply started (draining)");
      } catch (e) { toast(e.message, true); }
    });
  }

  async function refreshLogs() {
    const body = await api("/api/dashboard/logs/lb?lines=200");
    logBox.innerHTML = body.available
      ? `<div class="logbox mono">${body.lines.map(esc).join("<br>")}</div>`
      : `<p class="muted">File logging is not enabled on this server.</p>`;
    const inner = logBox.querySelector(".logbox");
    if (inner) inner.scrollTop = inner.scrollHeight;
  }

  await refresh();
  await refreshLogs().catch(() => {
    logBox.innerHTML = `<p class="muted">Log tail unavailable.</p>`;
  });
  const timer = setInterval(() => refreshLogs().catch(() => {}), 10000);
  return () => clearInterval(timer);
}
